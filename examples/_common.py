"""Shared CLI plumbing for the reproduction scripts.

Each ``examples/main_*.py`` re-creates one of the reference's experiment
scripts (reference repo root, SURVEY.md §2.11) on the gossipy_tpu engine.
All scripts accept ``--rounds`` / ``--nodes`` overrides so the same configs
double as quick smoke runs, and ``--plot PATH`` to save the reference-style
mean curves (reference utils.py:152-183).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Make the scripts runnable from a source checkout without installation.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# Re-runs of the same config load compiled programs from the persistent
# cache instead of recompiling (minutes for the CNN configs).
from gossipy_tpu import enable_compilation_cache

enable_compilation_cache()


def make_parser(description: str, rounds: int, nodes: int | None = None,
                with_plot: bool = True):
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--rounds", type=int, default=rounds,
                   help=f"simulation rounds (reference config: {rounds})")
    if nodes is not None:
        p.add_argument("--nodes", type=int, default=nodes,
                       help=f"number of gossip nodes (reference config: {nodes})")
    if with_plot:
        p.add_argument("--plot", type=str, default=None,
                       help="save metric curves to this path (PNG)")
    p.add_argument("--seed", type=int, default=42)
    return p


def add_repetitions_flag(p):
    """Only for scripts that actually honor it (vmapped repetition batch)."""
    p.add_argument("--repetitions", type=int, default=1,
                   help="independent repetitions, run as ONE vmapped program")
    return p


def add_probes_flag(p):
    """Only for scripts that pass it through to their simulator."""
    p.add_argument("--probes", action="store_true",
                   help="compute the in-graph gossip-dynamics probes "
                        "(consensus distance, merge staleness, realized "
                        "mixing — docs/observability.md) and print their "
                        "summary")
    return p


def add_sentinels_flag(p):
    """Only for scripts that pass it through to their simulator."""
    p.add_argument("--sentinels", action="store_true",
                   help="compute the in-graph numerics sentinels "
                        "(non-finite counts, divergence flags, saturation "
                        "watermarks — docs/observability.md) and print "
                        "their summary")
    return p


def add_chaos_flag(p):
    """Only for scripts that pass it through to their simulator."""
    p.add_argument("--chaos", action="store_true",
                   help="inject the demo fault scenario (docs/robustness.md):"
                        " the population partitioned in half for the middle "
                        "third of the run, then healed. Combine with "
                        "--probes to get the partition consensus gap and "
                        "rounds-to-reconverge in the summary")
    return p


def demo_chaos_config(args):
    """The ``--chaos`` scenario: a half/half partition over the middle
    third of the run (heal round recorded on ``args`` so :func:`finish`
    can name rounds-to-reconverge). None when the flag is off."""
    if not getattr(args, "chaos", False):
        return None
    from gossipy_tpu.simulation import ChaosConfig, PartitionEpisode
    n, r = args.nodes, args.rounds
    a = max(r // 3, 1)
    b = max(2 * r // 3, a + 1)
    args._chaos_heal = b
    half = n // 2
    return ChaosConfig(partitions=(PartitionEpisode(
        components=(tuple(range(half)), tuple(range(half, n))),
        start=a, stop=b),), horizon=r)


def finish(report, args, local: bool = False, label: str = "final"):
    """Print a one-line JSON summary + optionally save the plot.

    ``report`` may be a single SimulationReport or a list of them (one per
    repetition, e.g. from ``GossipSimulator.run_repetitions``): the summary
    then reports the mean final metrics and the plot shows mean±std curves.
    """
    reports = report if isinstance(report, (list, tuple)) else [report]
    evals_per_rep = [r.get_evaluation(local) for r in reports]
    evals = evals_per_rep[0]
    summary = {
        "rounds": len(evals),
        "repetitions": len(reports),
        "sent_messages": sum(r.sent_messages for r in reports),
        "failed_messages": sum(r.failed_messages for r in reports),
        "total_size": sum(r.total_size for r in reports),
    }
    if evals:
        finals = [e[-1][1] for e in evals_per_rep if e]
        summary[label] = {k: round(sum(f[k] for f in finals) / len(finals), 4)
                          for k in finals[0]}
    cm = getattr(reports[0], "probe_consensus_mean", None)
    if cm is not None and len(cm):
        # Gossip-dynamics probe summary (runs started with probes=).
        probes = {"consensus_first": round(float(cm[0]), 6),
                  "consensus_last": round(float(cm[-1]), 6)}
        sm = getattr(reports[0], "probe_stale_max", None)
        if sm is not None and len(sm):
            import numpy as _np
            probes["stale_max"] = int(_np.max(sm))
        acc = getattr(reports[0], "probe_accepted_per_node", None)
        if acc is not None:
            import numpy as _np
            probes["accepted_total"] = int(_np.sum(acc))
        md = getattr(reports[0], "probe_merge_delta", None)
        td = getattr(reports[0], "probe_train_delta", None)
        if md is not None and len(md):
            import numpy as _np
            if _np.isfinite(md[-1]):
                probes["merge_delta_last"] = round(float(md[-1]), 6)
                probes["train_delta_last"] = round(float(td[-1]), 6)
        summary["probes"] = probes
    trips = getattr(reports[0], "health_trip", None)
    if trips is not None:
        # Numerics-sentinel summary (runs started with sentinels=).
        import numpy as _np
        health = {"trips": int(_np.sum(trips))}
        nf = getattr(reports[0], "health_nonfinite_params", None)
        if nf is not None:
            health["nonfinite_params"] = int(_np.sum(nf))
        dv = getattr(reports[0], "health_diverged_per_node", None)
        if dv is not None:
            health["diverged"] = int(_np.sum(dv))
        hwm = getattr(reports[0], "health_delta_hwm", None)
        if hwm is not None and len(hwm) and _np.isfinite(hwm[-1]):
            health["delta_hwm"] = round(float(hwm[-1]), 6)
        summary["health"] = health
    cause = getattr(reports[0], "failed_per_cause", None) or {}
    gap = getattr(reports[0], "chaos_component_gap", None)
    if "chaos" in cause or (gap is not None and len(gap)):
        # Scheduled-fault summary (runs started with chaos=).
        import numpy as _np
        chaos = {}
        if "chaos" in cause:
            chaos["failed_chaos"] = int(_np.sum(cause["chaos"]))
        if gap is not None and len(gap):
            chaos["gap_peak"] = round(float(_np.nanmax(gap)), 6)
            chaos["gap_last"] = round(float(gap[-1]), 6)
            heal = getattr(args, "_chaos_heal", None)
            if heal is not None and heal < len(gap):
                from gossipy_tpu.simulation import rounds_to_reconverge
                chaos["rounds_to_reconverge"] = \
                    rounds_to_reconverge(gap, heal)
        summary["chaos"] = chaos
    print(json.dumps(summary))
    if args.plot:
        from gossipy_tpu.utils import plot_evaluation
        plot_evaluation([[ev for _, ev in e] for e in evals_per_rep if e],
                        title=sys.argv[0], path=args.plot)
    return summary
