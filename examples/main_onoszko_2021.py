"""Onoszko et al. 2021 — PENS decentralized peer selection on CIFAR-10.

Reproduction of reference ``main_onoszko_2021.py:28-124``: CIFAR-10 where the
second half of the images is vertically flipped (two-cluster non-IID), the
5-layer ``CIFAR10Net`` CNN (SGD, lr 0.01, weight decay 1e-3, batch 8, 3 local
epochs, MERGE_UPDATE), 5 PENS nodes with contiguous data assignment over a
clique, async PUSH, ``n_sampled=10, m_top=2, step1_rounds=100``, 10% sampled
evaluation, 500 rounds.

CIFAR-10 itself cannot be downloaded in this environment; ``get_CIFAR10``
substitutes a deterministic synthetic set of the same shape (see
gossipy_tpu/data). ``--subsample`` caps per-split sizes for smoke runs.
"""

from __future__ import annotations

import numpy as np
import optax

from _common import make_parser, finish

from gossipy_tpu import set_seed
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher, get_CIFAR10
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import CIFAR10Net
from gossipy_tpu.simulation import PENSGossipSimulator


def contiguous_assignment(n_samples: int, n_nodes: int) -> list[np.ndarray]:
    """The reference's CustomDataDispatcher: contiguous equal blocks
    (main_onoszko_2021.py:59-75)."""
    per = -(-n_samples // n_nodes)  # ceil
    return [np.arange(i * per, min((i + 1) * per, n_samples))
            for i in range(n_nodes)]


def main():
    parser = make_parser(__doc__, rounds=500, nodes=5)
    parser.add_argument("--subsample", type=int, default=0,
                        help="cap train/test sizes (0 = full)")
    parser.add_argument("--step1-rounds", type=int, default=100)
    args = parser.parse_args()
    key = set_seed(args.seed)

    (Xtr, ytr), (Xte, yte) = get_CIFAR10()
    if args.subsample:
        Xtr, ytr = Xtr[: args.subsample], ytr[: args.subsample]
        Xte, yte = Xte[: args.subsample // 5 or 1], yte[: args.subsample // 5 or 1]
    # Normalize to [-1, 1]-style range and flip the second half vertically
    # (reference: Normalize(0.5, 0.5) + RandomVerticalFlip(p=1) on half).
    Xtr = (Xtr - Xtr.mean()) / (Xtr.std() + 1e-8)
    Xte = (Xte - Xte.mean()) / (Xte.std() + 1e-8)
    half, half_te = len(Xtr) // 2, len(Xte) // 2
    Xtr[half:] = Xtr[half:, ::-1, :, :]
    Xte[half_te:] = Xte[half_te:, ::-1, :, :]

    data_handler = ClassificationDataHandler(Xtr, ytr, Xte, yte)
    n = args.nodes
    dispatcher = DataDispatcher(data_handler, n=n, eval_on_user=False,
                                auto_assign=False)
    dispatcher.set_assignments(contiguous_assignment(len(Xtr), n))

    handler = SGDHandler(
        model=CIFAR10Net(),
        loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(1e-3), optax.sgd(0.01)),
        local_epochs=3, batch_size=8, n_classes=10, input_shape=Xtr.shape[1:],
        create_model_mode=CreateModelMode.MERGE_UPDATE)

    # Documented divergence: the reference passes n_sampled=10 with 5 clique
    # nodes, but its phase-1 buffer is keyed by sender (node.py:777) so it can
    # hold at most n-1 entries and `len(cache) >= 10` never fires — the PENS
    # selection in the shipped config is inert. Capping at n-1 makes the
    # mechanism actually run, as the paper intends.
    simulator = PENSGossipSimulator(
        handler, Topology.clique(n), dispatcher.stacked(),
        n_sampled=min(10, n - 1), m_top=2, step1_rounds=args.step1_rounds,
        delta=100, protocol=AntiEntropyProtocol.PUSH,
        sampling_eval=0.1, sync=False)

    state = simulator.init_nodes(key)
    state, report = simulator.start(state, n_rounds=args.rounds, key=key)
    finish(report, args, local=False)


if __name__ == "__main__":
    main()
