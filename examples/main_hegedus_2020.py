"""Hegedus, Danner & Jelasity 2020 — gossip matrix factorization (MovieLens).

Reproduction of reference ``main_hegedus_2020.py:22-53``: MovieLens ratings,
one user per node, ``MFHandler(dim=5, lam=0.1, lr=0.001)`` under MERGE_UPDATE
(only item factors travel), 20-regular topology, sync PUSH with
UniformDelay(0, 10), 10% sampled evaluation, 100 rounds; metrics are
user-wise (local) RMSE.

The reference uses ml-1m; the default here is ml-100k (same protocol, ~6x
fewer users) to keep the history buffers small on one chip — pass
``--dataset ml-1m`` for the full config. MovieLens cannot be downloaded in
this environment, so a synthetic low-rank rating matrix of matching shape is
substituted (see gossipy_tpu/data).
"""

from __future__ import annotations

from _common import make_parser, finish

from gossipy_tpu import set_seed
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology, UniformDelay
from gossipy_tpu.data import RecSysDataDispatcher, RecSysDataHandler, \
    load_recsys_dataset
from gossipy_tpu.handlers import MFHandler
from gossipy_tpu.simulation import GossipSimulator


def main():
    parser = make_parser(__doc__, rounds=100)
    parser.add_argument("--dataset", choices=["ml-100k", "ml-1m"],
                        default="ml-100k")
    args = parser.parse_args()
    key = set_seed(args.seed)

    ratings, n_users, n_items = load_recsys_dataset(args.dataset)
    data_handler = RecSysDataHandler(ratings, n_users, n_items,
                                     test_size=0.1, seed=args.seed)
    dispatcher = RecSysDataDispatcher(data_handler)

    handler = MFHandler(dim=5, n_items=n_items, lam_reg=0.1,
                        learning_rate=0.001,
                        create_model_mode=CreateModelMode.MERGE_UPDATE)

    simulator = GossipSimulator(
        handler, Topology.random_regular(n_users, 20, seed=42, backend="networkx"),
        dispatcher.stacked(),
        delta=100, protocol=AntiEntropyProtocol.PUSH,
        delay=UniformDelay(0, 10), sampling_eval=0.1, sync=True)

    state = simulator.init_nodes(key)
    state, report = simulator.start(state, n_rounds=args.rounds, key=key)
    finish(report, args, local=True)  # user-wise RMSE (reference plots local)


if __name__ == "__main__":
    main()
