"""Ring-attention training demo: the long-context leg of the comm backend.

Trains a one-layer attention model with the SEQUENCE axis sharded over the
device mesh — queries stay resident per device while key/value blocks rotate
around a ``ppermute`` ring with streaming-softmax statistics
(``gossipy_tpu.parallel.collectives.ring_attention``). No device ever
materializes the [S, S] score matrix or the full key/value sequence, so the
reachable context length scales with the ring size. Gradients flow through
the ring schedule (forward AND backward are exercised here; parity with
dense attention is proven in tests/test_collectives.py).

The reference has no sequence models (SURVEY §2.12/§5); this demo exists to
show the explicit comm backend generalizes beyond the gossip exchange.

Run: ``python examples/demo_ring_attention.py [--devices 8]`` — on a single-
device host it re-execs itself onto a virtual CPU mesh of that size.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--devices", type=int, default=8,
                        help="ring size (virtual CPU mesh if not attached)")
    parser.add_argument("--seq-len", type=int, default=256)
    parser.add_argument("--dim", type=int, default=32)
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()

    import jax

    if len(jax.devices()) < args.devices:
        # Re-exec onto a virtual CPU mesh (same XLA partitioner and
        # collectives as real chips) — the pattern __graft_entry__ uses.
        if os.environ.get("_GOSSIPY_TPU_DEMO_CHILD") == "1":
            sys.exit("virtual mesh provisioning failed: "
                     f"{len(jax.devices())} devices")
        import subprocess

        from _virtual_mesh import virtual_mesh_env
        env = virtual_mesh_env(args.devices, extra_path=REPO)
        env["_GOSSIPY_TPU_DEMO_CHILD"] = "1"
        sys.exit(subprocess.run([sys.executable, os.path.abspath(__file__)]
                                + sys.argv[1:], env=env, cwd=REPO).returncode)

    import jax.numpy as jnp
    import numpy as np
    import optax

    from gossipy_tpu.parallel import make_mesh
    from gossipy_tpu.parallel.collectives import ring_attention

    mesh = make_mesh(args.devices)
    rng = np.random.default_rng(args.seed)
    s_len, dim = args.seq_len, args.dim

    # Retrieval task: every position must attend back to the sequence start
    # and reproduce its content — solvable only through attention.
    x = jnp.asarray(rng.normal(size=(s_len, dim)).astype(np.float32))
    tgt = jnp.broadcast_to(x[0], (s_len, dim))

    key = jax.random.PRNGKey(args.seed)
    kq, kk, kv = jax.random.split(key, 3)
    scale = 1.0 / np.sqrt(dim)
    params = {"wq": jax.random.normal(kq, (dim, dim)) * scale,
              "wk": jax.random.normal(kk, (dim, dim)) * scale,
              "wv": jax.random.normal(kv, (dim, dim)) * scale}
    opt = optax.adam(0.02)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            out = ring_attention(x @ p["wq"], x @ p["wk"], x @ p["wv"], mesh)
            return jnp.mean((out - tgt) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
        if i % 10 == 0:
            print(f"step {i:3d}  loss {losses[-1]:.4f}", file=sys.stderr)

    print(json.dumps({
        "demo": "ring_attention_training",
        "devices": args.devices,
        "seq_len": s_len,
        "per_device_kv_rows": s_len // args.devices,
        "loss_first": round(losses[0], 4),
        "loss_last": round(losses[-1], 4),
        "learned": losses[-1] < 0.5 * losses[0],
    }))


if __name__ == "__main__":
    main()
