"""North-star config: CIFAR-10 CNN gossip learning at 100 nodes.

BASELINE.md's target metric is wall-clock to target test accuracy for a
100-node CIFAR-10 configuration. The reference has no such shipped script —
its CIFAR-10 experiment is 5 PENS nodes (main_onoszko_2021.py) and its
100-node experiments are spambase (main_hegedus_2021.py) — so this composes
both, per BASELINE.md: CIFAR-10 data (Dirichlet non-IID split), the
``CIFAR10Net`` CNN, 100 nodes on a 20-regular graph, PUSH gossip with
MERGE_UPDATE.

TPU-first knobs: ``--bf16`` runs the forward/backward in bfloat16 (MXU
native rate), ``--fused`` uses the pallas fused gather+merge deliver path,
``--history-dtype bfloat16|int8`` stores the params-history ring (the
dominant memory term) in a quantized wire format.
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import optax

from _common import add_chaos_flag, add_probes_flag, add_sentinels_flag, \
    demo_chaos_config, make_parser, finish

from gossipy_tpu import set_seed
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import AssignmentHandler, ClassificationDataHandler, \
    DataDispatcher, get_CIFAR10
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import CIFAR10Net
from gossipy_tpu.simulation import GossipSimulator


def main():
    parser = make_parser(__doc__, rounds=100, nodes=100)
    parser.add_argument("--subsample", type=int, default=0,
                        help="cap train/test sizes (0 = full 50k/10k)")
    parser.add_argument("--bf16", action="store_true",
                        help="bfloat16 forward/backward")
    parser.add_argument("--fused", action="store_true",
                        help="pallas fused gather+merge deliver path")
    parser.add_argument("--beta", type=float, default=0.5,
                        help="Dirichlet non-IID concentration")
    parser.add_argument("--eval-every", type=int, default=1,
                        help="evaluate every n-th round (eval dominates the "
                             "per-round cost at CNN scale)")
    parser.add_argument("--history-dtype", default="float32",
                        choices=("float32", "bfloat16", "int8"),
                        help="params-history ring wire format: bf16/int8 "
                             "cut the dominant memory term and the deliver "
                             "gather traffic 2-4x (quantize-on-snapshot, "
                             "dequantize-on-gather; merge math stays fp32)")
    add_probes_flag(parser)
    add_sentinels_flag(parser)
    add_chaos_flag(parser)
    args = parser.parse_args()
    key = set_seed(args.seed)

    if args.fused:
        import jax
        if jax.default_backend() != "tpu":
            # Off-TPU the pallas kernel runs in the interpreter — orders of
            # magnitude slower than XLA for CNN-sized params.
            print("[cifar10-100nodes] --fused ignored off-TPU (interpreter mode)")
            args.fused = False

    (Xtr, ytr), (Xte, yte) = get_CIFAR10()
    if args.subsample:
        Xtr, ytr = Xtr[: args.subsample], ytr[: args.subsample]
        Xte, yte = Xte[: args.subsample // 5 or 1], yte[: args.subsample // 5 or 1]
    # Normalize BOTH splits with the training statistics.
    mu, sd = Xtr.mean(), Xtr.std() + 1e-8
    Xtr = (Xtr - mu) / sd
    Xte = (Xte - mu) / sd

    n = args.nodes
    data_handler = ClassificationDataHandler(Xtr, ytr, Xte, yte)
    # Dirichlet label skew across the clients (reference
    # AssignmentHandler.label_dirichlet_skew, data/__init__.py:300-335).
    dispatcher = DataDispatcher(
        data_handler, n=n, eval_on_user=False,
        assignment=AssignmentHandler.label_dirichlet_skew, beta=args.beta)
    dispatcher.assign(args.seed)

    handler = SGDHandler(
        model=CIFAR10Net(),
        loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(1e-3), optax.sgd(0.05)),
        local_epochs=1, batch_size=32, n_classes=10, input_shape=Xtr.shape[1:],
        create_model_mode=CreateModelMode.MERGE_UPDATE,
        compute_dtype=jnp.bfloat16 if args.bf16 else None)

    simulator = GossipSimulator(
        handler, Topology.random_regular(n, min(20, n - 1), seed=42, backend="networkx"),
        dispatcher.stacked(),
        delta=100, protocol=AntiEntropyProtocol.PUSH,
        sampling_eval=0.1, sync=True, eval_every=args.eval_every,
        fused_merge=args.fused, history_dtype=args.history_dtype,
        probes=args.probes, sentinels=args.sentinels,
        chaos=demo_chaos_config(args))
    budget = simulator.memory_budget()
    print(f"[cifar10-100nodes] history ring ({args.history_dtype}): "
          f"{budget['history_ring_bytes'] / 2**20:.1f} MB "
          f"(depth {budget['history_depth']}, "
          f"{simulator.wire_bytes_per_message():,} wire bytes/message)")

    # Common initialization (FedAvg-standard): averaging differently-
    # initialized CNNs cancels features and 100-node runs stay at chance.
    state = simulator.init_nodes(key, common_init=True)
    t0 = time.perf_counter()
    state, report = simulator.start(state, n_rounds=args.rounds, key=key)
    elapsed = time.perf_counter() - t0  # includes the one-time round compile
    print(f"[cifar10-100nodes] {args.rounds} rounds in {elapsed:.1f}s "
          f"({args.rounds / elapsed:.2f} r/s, first run includes compile; "
          "re-runs hit the persistent cache)")
    finish(report, args, local=False)


if __name__ == "__main__":
    main()
