"""Centralized baselines: what accuracy/AUC a single global model reaches.

Re-design of reference ``baseline.py:10-92``: a centralized MLP trained on
the full (undistributed) training set — once with our jitted flax/optax
training path and once with sklearn's ``MLPClassifier`` — giving the quality
anchor gossip runs are compared against. The reference's feature-map test
split (``te_fmap``) is specific to an unshipped handler variant and is
omitted; overall test accuracy/AUC are reported.
"""

from __future__ import annotations

import json

import jax
import numpy as np
import optax

from _common import make_parser

from gossipy_tpu import set_seed
from gossipy_tpu.data import ClassificationDataHandler, load_classification_dataset
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import MLP


def flax_mlp(data_handler, n_epochs: int = 300, batch_size: int = 16,
             learning_rate: float = 0.01, l2_reg: float = 0.001,
             seed: int = 42) -> dict:
    """Centralized MLP via the same handler machinery the gossip nodes use."""
    handler = SGDHandler(
        model=MLP(data_handler.size(1), 2, hidden_dims=(100,)),
        loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(l2_reg),
                              optax.sgd(learning_rate)),
        local_epochs=n_epochs, batch_size=batch_size, n_classes=2,
        input_shape=(data_handler.size(1),))
    key = jax.random.PRNGKey(seed)
    state = handler.init(key)
    Xtr, ytr = data_handler.get_train_set()
    mask = np.ones(len(Xtr), dtype=np.float32)
    state = jax.jit(handler.update)(state, (Xtr, ytr, mask), key)
    Xte, yte = data_handler.get_eval_set()
    res = handler.evaluate(state, (np.asarray(Xte), np.asarray(yte),
                                   np.ones(len(Xte), dtype=np.float32)))
    return {k: float(v) for k, v in res.items()}


def sklearn_mlp(data_handler, n_epochs: int = 300, batch_size: int = 16,
                learning_rate: float = 0.01, l2_reg: float = 0.001) -> dict:
    from sklearn.metrics import accuracy_score, roc_auc_score
    from sklearn.neural_network import MLPClassifier
    Xtr, ytr = data_handler.get_train_set()
    Xte, yte = data_handler.get_eval_set()
    clf = MLPClassifier(max_iter=n_epochs, learning_rate_init=learning_rate,
                        alpha=l2_reg, batch_size=batch_size,
                        verbose=False).fit(Xtr, np.asarray(ytr).ravel())
    return {
        "accuracy": float(accuracy_score(yte, clf.predict(Xte))),
        "auc": float(roc_auc_score(yte, clf.predict_proba(Xte)[:, 1])),
    }


def main():
    parser = make_parser(__doc__, rounds=300, with_plot=False)  # no curves here
    parser.add_argument("--dataset", default="spambase")
    args = parser.parse_args()
    set_seed(args.seed)

    X, y = load_classification_dataset(args.dataset)
    data_handler = ClassificationDataHandler(X, y, test_size=0.1, seed=args.seed)

    print(json.dumps({
        "flax_mlp": flax_mlp(data_handler, n_epochs=args.rounds, seed=args.seed),
        "sklearn_mlp": sklearn_mlp(data_handler, n_epochs=args.rounds),
    }))


if __name__ == "__main__":
    main()
