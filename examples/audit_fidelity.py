"""Audit the bulk engine's fidelity divergences on YOUR config.

Runs the same small configuration through the jitted bulk-synchronous
engine and the opt-in sequential high-fidelity engine
(:class:`~gossipy_tpu.simulation.SequentialGossipSimulator` — reference
per-tick semantics: in-round snapshots, same-tick token reactions,
per-message observer events) over a few seeds each, and reports where
the mean accuracy and send-count curves diverge. This is the workflow
PARITY.md's divergence table prescribes before trusting a bulk-engine
study on a new protocol configuration: if the two engines agree on your
config, the bulk engine's compiled scans are safe at any scale; if not,
the printed per-round gaps show which transient to mind.

Usage (repo root):
    python examples/audit_fidelity.py --nodes 16 --rounds 12 --seeds 3
    python examples/audit_fidelity.py --tokenized   # same-tick reactions
"""

from __future__ import annotations

import json

import numpy as np

from _common import make_parser

import jax
import optax

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.flow_control import SimpleTokenAccount
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import GossipSimulator, \
    SequentialGossipSimulator, TokenizedGossipSimulator


def main() -> None:
    p = make_parser(__doc__.splitlines()[0], rounds=12, nodes=16,
                    with_plot=False)
    p.add_argument("--seeds", type=int, default=3)
    p.add_argument("--tokenized", action="store_true",
                   help="audit the token-reaction path (same-tick vs "
                        "next-round delivery)")
    args = p.parse_args()

    rng = np.random.default_rng(args.seed)
    d = 12
    X = rng.normal(size=(30 * args.nodes, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.int64)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=args.seed)
    disp = DataDispatcher(dh, n=args.nodes, eval_on_user=False)
    topo = Topology.random_regular(args.nodes, min(6, args.nodes - 1),
                                   seed=args.seed)

    def handler():
        return SGDHandler(model=LogisticRegression(d, 2),
                          loss=losses.cross_entropy,
                          optimizer=optax.sgd(0.2), local_epochs=1,
                          batch_size=8, n_classes=2, input_shape=(d,),
                          create_model_mode=CreateModelMode.MERGE_UPDATE)

    def run(engine: str, seed: int):
        key = jax.random.PRNGKey(seed)
        if engine == "sequential":
            kw = ({"token_account": SimpleTokenAccount(C=2)}
                  if args.tokenized else {})
            sim = SequentialGossipSimulator(
                handler(), topo, disp.stacked(), delta=20,
                protocol=AntiEntropyProtocol.PUSH, **kw)
        elif args.tokenized:
            sim = TokenizedGossipSimulator(
                handler(), topo, disp.stacked(), delta=20,
                protocol=AntiEntropyProtocol.PUSH,
                token_account=SimpleTokenAccount(C=2))
        else:
            sim = GossipSimulator(handler(), topo, disp.stacked(), delta=20,
                                  protocol=AntiEntropyProtocol.PUSH)
        st = sim.init_nodes(key)
        _, rep = sim.start(st, n_rounds=args.rounds,
                           key=jax.random.fold_in(key, 1))
        return (rep.curves(local=False)["accuracy"],
                np.asarray(rep.sent_per_round, np.float64))

    acc = {"bulk": [], "sequential": []}
    sent = {"bulk": [], "sequential": []}
    for engine in ("bulk", "sequential"):
        for s in range(args.seeds):
            a, m = run(engine, args.seed + s)
            acc[engine].append(a)
            sent[engine].append(m)

    acc_gap = np.abs(np.mean(acc["bulk"], 0) - np.mean(acc["sequential"], 0))
    sent_gap = np.abs(np.mean(sent["bulk"], 0)
                      - np.mean(sent["sequential"], 0))
    print("per-round mean accuracy gap:", np.round(acc_gap, 4).tolist())
    print("per-round mean sent-count gap:", np.round(sent_gap, 2).tolist())
    print(json.dumps({
        "rounds": args.rounds,
        "nodes": args.nodes,
        "seeds": args.seeds,
        "tokenized": bool(args.tokenized),
        "max_accuracy_gap": round(float(acc_gap.max()), 4),
        "tail_accuracy_gap": round(float(acc_gap[-1]), 4),
        "max_sent_gap": round(float(sent_gap.max()), 2),
        "final": {
            "accuracy_bulk": round(float(np.mean(acc["bulk"], 0)[-1]), 4),
            "accuracy_sequential": round(
                float(np.mean(acc["sequential"], 0)[-1]), 4),
        },
    }))


if __name__ == "__main__":
    main()
