"""Run any experiment from a JSON config file.

The reference wires each experiment ad hoc in its own ``main_*`` script;
here one declarative file reproduces a run end to end (SURVEY §5 config
system):

    python examples/main_from_config.py examples/configs/spambase_100.json
    python examples/main_from_config.py --dump-default > my_exp.json

Prints the same one-line JSON summary as the other examples (repetitions
are aggregated as mean finals; ``--plot`` saves the mean±std curves).
"""

from __future__ import annotations

import argparse

from _common import finish

from gossipy_tpu.config import ExperimentConfig, run_experiment


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("config", nargs="?",
                        help="path to an experiment JSON file")
    parser.add_argument("--dump-default", action="store_true",
                        help="print the default config as JSON and exit")
    parser.add_argument("--plot", default=None, metavar="PATH",
                        help="save mean±std evaluation curves")
    args = parser.parse_args()
    if args.dump_default:
        print(ExperimentConfig().to_json())
        return
    if not args.config:
        parser.error("a config file is required (or --dump-default)")
    cfg = ExperimentConfig.from_json(args.config)
    state, report = run_experiment(cfg)
    # Recsys experiments evaluate user-wise only (local RMSE, like the
    # reference's main_hegedus_2020 plots); fall back to the local curves
    # when no global evaluation exists.
    rep0 = report[0] if isinstance(report, (list, tuple)) else report
    use_local = (not rep0.get_evaluation(False)
                 and bool(rep0.get_evaluation(True)))
    finish(report, args, local=use_local)


if __name__ == "__main__":
    main()
