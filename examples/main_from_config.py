"""Run any experiment from a JSON config file.

The reference wires each experiment ad hoc in its own ``main_*`` script;
here one declarative file reproduces a run end to end (SURVEY §5 config
system):

    python examples/main_from_config.py examples/configs/spambase_100.json
    python examples/main_from_config.py --dump-default > my_exp.json
"""

from __future__ import annotations

import sys

import _common  # noqa: F401  (inserts the repo root for source checkouts)

from gossipy_tpu.config import ExperimentConfig, run_experiment


def main():
    if "--dump-default" in sys.argv:
        print(ExperimentConfig().to_json())
        return
    if len(sys.argv) < 2:
        sys.exit(__doc__)
    cfg = ExperimentConfig.from_json(sys.argv[1])
    state, report = run_experiment(cfg)
    if isinstance(report, list):  # repetitions > 1: one report per seed
        import numpy as np

        def last_acc(r):
            a = r.curves(local=False).get("accuracy")
            return float(a[-1]) if a is not None and len(a) else float("nan")

        finals = [last_acc(r) for r in report]
        print(f"[config-run] final global accuracy "
              f"{np.mean(finals):.4f} ± {np.std(finals):.4f} over "
              f"{len(report)} repetitions, {cfg.n_rounds} rounds")
        return
    curves = report.curves(local=False)
    acc = curves.get("accuracy")
    if acc is not None:
        print(f"[config-run] final global accuracy {float(acc[-1]):.4f} "
              f"after {cfg.n_rounds} rounds")
    print(f"[config-run] messages sent {report.sent_messages}, "
          f"failed {report.failed_messages}")


if __name__ == "__main__":
    main()
