"""Berta, Bilicki & Jelasity 2014 — gossip k-means clustering.

Reproduction of reference ``main_berta_2014.py:25-77``: spambase as a
clustering problem (eval set == train set), one node per sample on a clique,
``KMeansHandler(k=2, alpha=0.1, matching="hungarian")`` under MERGE_UPDATE,
sync PUSH with 10% drop, 1% sampled evaluation, 500 rounds of length 1000.
Prints the same two sanity baselines (plain and sklearn k-means NMI) before
the gossip run.
"""

from __future__ import annotations

import numpy as np

from _common import make_parser, finish

from gossipy_tpu import set_seed
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClusteringDataHandler, DataDispatcher, \
    load_classification_dataset
from gossipy_tpu.handlers import KMeansHandler
from gossipy_tpu.simulation import GossipSimulator


def numpy_kmeans(X: np.ndarray, k: int = 2, max_iterations: int = 400,
                 seed: int = 42) -> np.ndarray:
    """Plain Lloyd's algorithm baseline (reference main_berta_2014.py:29-41)."""
    rng = np.random.default_rng(seed)
    centroids = X[rng.choice(len(X), k, replace=False)]
    assign = np.argmin(((X[:, None] - centroids[None]) ** 2).sum(-1), axis=1)
    for _ in range(max_iterations):
        centroids = np.stack([
            X[assign == i].mean(axis=0) if (assign == i).any() else centroids[i]
            for i in range(k)])
        new = np.argmin(((X[:, None] - centroids[None]) ** 2).sum(-1), axis=1)
        if np.array_equal(assign, new):
            break
        assign = new
    return assign


def main():
    args = make_parser(__doc__, rounds=500, nodes=0).parse_args()
    key = set_seed(args.seed)

    X, y = load_classification_dataset("spambase", normalize=True)
    data_handler = ClusteringDataHandler(X, y)

    from sklearn.cluster import KMeans
    from sklearn.metrics.cluster import normalized_mutual_info_score as sk_nmi
    print("K-means NMI:", sk_nmi(y, numpy_kmeans(X, k=2, seed=args.seed)))
    km = KMeans(n_clusters=2, n_init=1, random_state=98765).fit(X)
    print("Sklearn K-means NMI:", sk_nmi(y, km.labels_))

    n = args.nodes or data_handler.size()
    dispatcher = DataDispatcher(data_handler, n=n, eval_on_user=False)

    handler = KMeansHandler(k=2, dim=data_handler.size(1), alpha=0.1,
                            matching="hungarian",
                            create_model_mode=CreateModelMode.MERGE_UPDATE)

    simulator = GossipSimulator(
        handler, Topology.clique(n), dispatcher.stacked(),
        delta=1000, protocol=AntiEntropyProtocol.PUSH,
        drop_prob=0.1, sampling_eval=0.01, sync=True)

    state = simulator.init_nodes(key, local_train=True)
    state, report = simulator.start(state, n_rounds=args.rounds, key=key)
    finish(report, args, local=False)


if __name__ == "__main__":
    main()
