"""Danner & Jelasity 2023 — gossip learning with limited model merging.

Reproduction of reference ``main_danner_2023.py:25-62``: spambase,
LogisticRegression (SGD, lr 1, weight decay 1e-3, CrossEntropy), 100 nodes on
a 20-regular graph, ``LimitedMergeSGDHandler`` (age-gap-thresholded merges,
MERGE_UPDATE), sync PUSH with UniformDelay(0, 10), 20% online, 10% drop,
10% sampled evaluation, 1000 rounds.
"""

from __future__ import annotations

import optax

from _common import make_parser, finish

from gossipy_tpu import set_seed
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology, UniformDelay
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher, \
    load_classification_dataset
from gossipy_tpu.handlers import LimitedMergeSGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import GossipSimulator


def main():
    args = make_parser(__doc__, rounds=1000, nodes=100).parse_args()
    key = set_seed(args.seed)

    X, y = load_classification_dataset("spambase")
    data_handler = ClassificationDataHandler(X, y, test_size=0.1, seed=args.seed)
    n = args.nodes
    dispatcher = DataDispatcher(data_handler, n=n, eval_on_user=False)

    handler = LimitedMergeSGDHandler(
        model=LogisticRegression(data_handler.size(1), 2),
        loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(1e-3), optax.sgd(1.0)),
        local_epochs=1, batch_size=32, n_classes=2,
        input_shape=(data_handler.size(1),),
        age_diff_threshold=1,
        create_model_mode=CreateModelMode.MERGE_UPDATE)

    simulator = GossipSimulator(
        handler, Topology.random_regular(n, min(20, n - 1), seed=42, backend="networkx"),
        dispatcher.stacked(),
        delta=100, protocol=AntiEntropyProtocol.PUSH,
        delay=UniformDelay(0, 10),
        online_prob=0.2, drop_prob=0.1, sampling_eval=0.1, sync=True)

    state = simulator.init_nodes(key)
    state, report = simulator.start(state, n_rounds=args.rounds, key=key)
    finish(report, args, local=False)


if __name__ == "__main__":
    main()
