"""Ormandi et al. 2013 — vanilla gossip learning with Pegasos.

Reproduction of reference ``main_ormandi_2013.py:21-53``: spambase with ±1
labels, one node per training sample, Pegasos (AdaLine weight vector) under
MERGE_UPDATE, fully-connected topology, async PUSH gossip with
UniformDelay(0, 10), 20% online probability and 10% message drop,
10% sampled evaluation.
"""

from __future__ import annotations

import numpy as np

from _common import add_repetitions_flag, make_parser, finish

from gossipy_tpu import set_seed
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology, UniformDelay
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher, \
    load_classification_dataset
from gossipy_tpu.handlers import PegasosHandler
from gossipy_tpu.models import AdaLine
from gossipy_tpu.simulation import GossipSimulator


def main():
    args = add_repetitions_flag(
        make_parser(__doc__, rounds=100, nodes=0)).parse_args()
    key = set_seed(args.seed)

    X, y = load_classification_dataset("spambase")
    y = (2 * y - 1).astype(np.float32)  # 0/1 -> ±1 labels

    data_handler = ClassificationDataHandler(X, y, test_size=0.1, seed=args.seed)
    n = args.nodes or data_handler.size()  # reference: one node per sample
    dispatcher = DataDispatcher(data_handler, n=n, eval_on_user=False)

    handler = PegasosHandler(net=AdaLine(data_handler.size(1)),
                             learning_rate=0.01,
                             create_model_mode=CreateModelMode.MERGE_UPDATE)

    simulator = GossipSimulator(
        handler, Topology.clique(n), dispatcher.stacked(),
        delta=100,
        protocol=AntiEntropyProtocol.PUSH,
        delay=UniformDelay(0, 10),
        online_prob=0.2,   # STUNner smartphone-trace online rate
        drop_prob=0.1,
        sampling_eval=0.1,
        sync=False)

    if args.repetitions > 1:
        # All repetitions run as ONE vmapped XLA program (the reference
        # loops whole Python simulations per seed).
        import jax
        _, reports = simulator.run_repetitions(
            args.rounds, jax.random.split(key, args.repetitions))
        finish(reports, args, local=False)
    else:
        state = simulator.init_nodes(key)
        state, report = simulator.start(state, n_rounds=args.rounds, key=key)
        finish(report, args, local=False)


if __name__ == "__main__":
    main()
