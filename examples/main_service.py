"""Gossip-as-a-service demo: heterogeneous tenants, shape-packed buckets.

Submits FOUR concurrent experiments to the multi-tenant scheduler
(:mod:`gossipy_tpu.service`, docs/service.md):

- ``alice`` / ``bob``: LogReg over spambase-shaped data, different seeds
  and fault rates — SAME compiled-program shape, so the packer fuses them
  (with ``mallory`` below) into ONE tenant-vmapped megabatch program;
- ``carol``: an MLP over the same data — different model, own bucket;
- ``mallory`` (``--trip``, on by default): same shape as alice/bob but
  her data carries non-finite rows, so her lane trips the in-graph
  numerics sentinels — the scheduler writes her flight-recorder repro
  bundle and EVICTS her while alice and bob finish untouched.

Four tenants, TWO compiled megabatch step programs (asserted via the
scheduler's jit-cache counters). ``alice``'s per-tenant report is checked
fp-tolerantly against her SOLO ``run_experiment`` trajectory — packing
changes scheduling, never results.

    python examples/main_service.py --rounds 30 --nodes 64
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile

import numpy as np

from _common import make_parser

from gossipy_tpu.config import ExperimentConfig, run_experiment
from gossipy_tpu.service import GossipService, RunQueue, RunRequest, \
    RunStatus


def tenant_data(seed: int, n: int = 1600, d: int = 30, poison: bool = False):
    """Per-tenant spambase-shaped synthetic shard (the service packs by
    SHAPE — values are free to differ per tenant). ``poison`` plants
    non-finite feature rows, the classic corrupt-ingest failure the
    sentinels exist to catch."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.int64)
    if poison:
        X[: n // 8] = np.inf
    return X, y


def main():
    p = make_parser("multi-tenant scheduler demo", rounds=30, nodes=64,
                    with_plot=False)
    p.add_argument("--slice", type=int, default=10,
                   help="rounds per cooperative scheduling slice")
    p.add_argument("--no-trip", action="store_true",
                   help="skip the poisoned 4th tenant (eviction demo)")
    p.add_argument("--out", default=None,
                   help="artifact root (default: a temp dir)")
    args = p.parse_args()
    out = args.out or tempfile.mkdtemp(prefix="gossipy_service_")

    base = dict(n_nodes=args.nodes, model="logreg", handler="sgd",
                topology="random_regular", topology_params={"degree": 6},
                delta=20, n_rounds=args.rounds, batch_size=16)
    cfg_alice = ExperimentConfig(**base, seed=args.seed)
    requests = [
        RunRequest("alice", cfg_alice, data=tenant_data(1)),
        RunRequest("bob", ExperimentConfig(**base, seed=args.seed + 1,
                                           drop_prob=0.1),
                   data=tenant_data(2)),
        RunRequest("carol",
                   ExperimentConfig(**{**base, "model": "mlp",
                                       "model_params": {
                                           "hidden_dims": [16]}},
                                    seed=args.seed + 2),
                   data=tenant_data(3)),
    ]
    if not args.no_trip:
        requests.append(RunRequest(
            "mallory", ExperimentConfig(**base, seed=args.seed + 3),
            data=tenant_data(4, poison=True)))

    queue = RunQueue()
    handles = {r.tenant: queue.submit(r) for r in requests}
    svc = GossipService(out, slice_rounds=args.slice)
    summary = svc.serve(queue)

    # The packing claim, verified from the scheduler's own counters: all
    # LogReg tenants share one compiled step program, carol gets the
    # second — and each bucket's jit cache holds exactly ONE entry.
    assert summary["n_buckets"] == 2, summary["n_buckets"]
    assert summary["megabatch_step_programs"] == 2
    for b in summary["buckets"]:
        assert b["step_jit_cache_size"] in (1, None), b

    # Packing must not change results: alice solo == alice served
    # (sentinels injected like the service does).
    solo_cfg = dataclasses.replace(
        cfg_alice, simulator_params={**cfg_alice.simulator_params,
                                     "sentinels": True})
    _, solo = run_experiment(solo_cfg, data=tenant_data(1))
    served = handles["alice"].report
    np.testing.assert_allclose(solo.curves(local=False)["accuracy"],
                               served.curves(local=False)["accuracy"],
                               atol=2e-5)

    if not args.no_trip:
        m = handles["mallory"]
        assert m.status is RunStatus.EVICTED, m.status
        assert m.bundle_path and os.path.isdir(m.bundle_path)
        for co in ("alice", "bob"):
            assert handles[co].status is RunStatus.DONE

    print(json.dumps({
        "n_buckets": summary["n_buckets"],
        "megabatch_step_programs": summary["megabatch_step_programs"],
        "alice_parity": "exact-to-2e-5",
        "tenants": {t: {
            "status": h.status.value,
            "rounds": h.rounds_completed,
            "final_accuracy": (round(h.report.final("accuracy"), 4)
                               if h.report is not None else None),
            "bundle": h.bundle_path,
        } for t, h in handles.items()},
        "out_dir": out,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
