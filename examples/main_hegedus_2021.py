"""Hegedus, Danner & Jelasity 2021 — partitioned exchange + token accounts.

Reproduction of reference ``main_hegedus_2021.py:28-69``: spambase,
LogisticRegression (SGD, lr 1, weight decay 1e-3, CrossEntropy), 100 nodes on
a 20-regular graph, model split into 4 partitions with per-partition ages
(``PartitionedSGDHandler``), UPDATE mode, tokenized gossip with
``RandomizedTokenAccount(C=20, A=10)`` and constant utility, sync PUSH with
UniformDelay(0, 10), 10% sampled evaluation, 1000 rounds.

``--variant sampling`` switches to the same paper's subsampled-exchange
protocol (``SamplingBasedNode``, reference node.py:499-562).
"""

from __future__ import annotations

import jax
import optax

from _common import make_parser, finish

from gossipy_tpu import set_seed
from gossipy_tpu.compression import ModelPartition
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology, UniformDelay
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher, \
    load_classification_dataset
from gossipy_tpu.flow_control import RandomizedTokenAccount
from gossipy_tpu.handlers import PartitionedSGDHandler, SamplingSGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import (
    SamplingGossipSimulator,
    TokenizedPartitioningGossipSimulator,
)


def main():
    parser = make_parser(__doc__, rounds=1000, nodes=100)
    parser.add_argument("--variant", choices=["partitioning", "sampling"],
                        default="partitioning")
    args = parser.parse_args()
    key = set_seed(args.seed)

    X, y = load_classification_dataset("spambase")
    data_handler = ClassificationDataHandler(X, y, test_size=0.1, seed=args.seed)
    n = args.nodes
    dispatcher = DataDispatcher(data_handler, n=n, eval_on_user=False)
    topology = Topology.random_regular(n, min(20, n - 1), seed=42, backend="networkx")

    model = LogisticRegression(data_handler.size(1), 2)
    optimizer = optax.chain(optax.add_decayed_weights(1e-3), optax.sgd(1.0))
    common = dict(model=model, loss=losses.cross_entropy, optimizer=optimizer,
                  local_epochs=1, batch_size=32, n_classes=2,
                  input_shape=(data_handler.size(1),),
                  create_model_mode=CreateModelMode.UPDATE)

    if args.variant == "partitioning":
        template = model.init(jax.random.PRNGKey(0),
                              jax.numpy.zeros((1, data_handler.size(1))))["params"]
        handler = PartitionedSGDHandler(ModelPartition(template, 4), **common)
        simulator = TokenizedPartitioningGossipSimulator(
            handler, topology, dispatcher.stacked(),
            token_account=RandomizedTokenAccount(C=20, A=10),
            delta=100, protocol=AntiEntropyProtocol.PUSH,
            delay=UniformDelay(0, 10), sampling_eval=0.1, sync=True)
    else:
        handler = SamplingSGDHandler(0.25, **common)
        simulator = SamplingGossipSimulator(
            handler, topology, dispatcher.stacked(),
            delta=100, protocol=AntiEntropyProtocol.PUSH,
            delay=UniformDelay(0, 10), sampling_eval=0.1, sync=True)

    state = simulator.init_nodes(key)
    state, report = simulator.start(state, n_rounds=args.rounds, key=key)
    finish(report, args, local=False)


if __name__ == "__main__":
    main()
