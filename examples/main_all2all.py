"""All-to-all gossip with mixing weights (Koloskova et al. 2020 style).

Reproduction of reference ``main_all2all.py:25-60``: spambase,
LogisticRegression (SGD, lr 0.1, weight decay 1e-2, CrossEntropy), 100 nodes
on a 20-regular graph, ``WeightedSGDHandler`` under MERGE_UPDATE, broadcast
PUSH to all peers with uniform mixing weights, async, 10% sampled evaluation,
100 rounds. On TPU the whole network's mixing merge is one ``W_eff @ P``
matmul per parameter leaf (see All2AllGossipSimulator).
"""

from __future__ import annotations

import optax

from _common import add_chaos_flag, add_probes_flag, add_sentinels_flag, \
    demo_chaos_config, make_parser, finish

from gossipy_tpu import set_seed
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology, \
    metropolis_hastings_mixing, uniform_mixing
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher, \
    load_classification_dataset
from gossipy_tpu.handlers import WeightedSGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import All2AllGossipSimulator


def main():
    parser = make_parser(__doc__, rounds=100, nodes=100)
    parser.add_argument("--mixing", choices=["uniform", "metropolis"],
                        default="uniform")
    add_probes_flag(parser)
    add_sentinels_flag(parser)
    add_chaos_flag(parser)
    args = parser.parse_args()
    key = set_seed(args.seed)

    X, y = load_classification_dataset("spambase")
    data_handler = ClassificationDataHandler(X, y, test_size=0.1, seed=args.seed)
    n = args.nodes
    dispatcher = DataDispatcher(data_handler, n=n, eval_on_user=False)
    topology = Topology.random_regular(n, min(20, n - 1), seed=42, backend="networkx")

    handler = WeightedSGDHandler(
        model=LogisticRegression(data_handler.size(1), 2),
        loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(1e-2), optax.sgd(0.1)),
        local_epochs=1, batch_size=32, n_classes=2,
        input_shape=(data_handler.size(1),),
        create_model_mode=CreateModelMode.MERGE_UPDATE)

    mix = uniform_mixing if args.mixing == "uniform" else metropolis_hastings_mixing
    simulator = All2AllGossipSimulator(
        handler, topology, dispatcher.stacked(),
        mixing=mix(topology),
        delta=100, protocol=AntiEntropyProtocol.PUSH,
        sampling_eval=0.1, sync=False, probes=args.probes,
        sentinels=args.sentinels, chaos=demo_chaos_config(args))

    state = simulator.init_nodes(key)
    state, report = simulator.start(state, n_rounds=args.rounds, key=key)
    finish(report, args, local=False)


if __name__ == "__main__":
    main()
