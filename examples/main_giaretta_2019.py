"""Giaretta & Girdzijauskas 2019 — gossip learning on a power-law topology.

Reproduction of reference ``main_giaretta_2019.py:23-53``: spambase with ±1
labels, one node per sample, Pegasos under MERGE_UPDATE, Barabási–Albert
(m=10) topology, async PUSH, 10% sampled evaluation. (The PassThrough /
CacheNeigh node behaviors from the same paper are available as
``PassThroughGossipSimulator`` / ``CacheNeighGossipSimulator``; use
``--variant`` to select one.)
"""

from __future__ import annotations

import numpy as np

from _common import make_parser, finish

from gossipy_tpu import set_seed
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher, \
    load_classification_dataset
from gossipy_tpu.handlers import PegasosHandler
from gossipy_tpu.models import AdaLine
from gossipy_tpu.simulation import (
    CacheNeighGossipSimulator,
    GossipSimulator,
    PassThroughGossipSimulator,
)

SIMULATORS = {
    "vanilla": GossipSimulator,
    "passthrough": PassThroughGossipSimulator,
    "cacheneigh": CacheNeighGossipSimulator,
}


def main():
    parser = make_parser(__doc__, rounds=100, nodes=0)
    parser.add_argument("--variant", choices=sorted(SIMULATORS), default="vanilla",
                        help="node behavior (reference node.py:289-496)")
    args = parser.parse_args()
    key = set_seed(args.seed)

    X, y = load_classification_dataset("spambase")
    y = (2 * y - 1).astype(np.float32)

    data_handler = ClassificationDataHandler(X, y, test_size=0.1, seed=args.seed)
    n = args.nodes or data_handler.size()
    dispatcher = DataDispatcher(data_handler, n=n, eval_on_user=False)

    handler = PegasosHandler(net=AdaLine(data_handler.size(1)),
                             learning_rate=0.01,
                             create_model_mode=CreateModelMode.MERGE_UPDATE)

    simulator = SIMULATORS[args.variant](
        handler, Topology.barabasi_albert(n, m=min(10, n - 1), seed=args.seed, backend="networkx"),
        dispatcher.stacked(),
        delta=100,
        protocol=AntiEntropyProtocol.PUSH,
        sampling_eval=0.1,
        sync=False)

    state = simulator.init_nodes(key)
    state, report = simulator.start(state, n_rounds=args.rounds, key=key)
    finish(report, args, local=False)


if __name__ == "__main__":
    main()
