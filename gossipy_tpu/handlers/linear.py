"""AdaLine and Pegasos handlers — manual (autograd-free) linear learners.

Re-design of reference handler.py:337-423. The reference loops over samples
in Python (handler.py:367-368, :418-423); here the per-sample recurrences are
``lax.scan``s, so one node's whole local pass is a single fused kernel and
all N nodes run under one vmap.

Labels are ±1 (Ormandi 2013 experiments); evaluation mirrors
``AdaLineHandler.evaluate`` (handler.py:375-391).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import CreateModelMode
from ..models.nn import AdaLine
from ..utils import signed_binary_metrics
from .base import BaseHandler, ModelState, PeerModel


class AdaLineHandler(BaseHandler):
    """Delta-rule learner (reference handler.py:337-391).

    Per sample: ``w += lr * (y_i - w.x_i) * x_i``; ``n_updates`` counts
    samples seen (handler.py:366).
    """

    uniform_avg_merge = True
    merge_peer_weight = 0.5

    def __init__(self, net: AdaLine, learning_rate: float,
                 create_model_mode: CreateModelMode = CreateModelMode.UPDATE):
        self.net = net
        self.learning_rate = learning_rate
        self.mode = create_model_mode

    def init(self, key: jax.Array) -> ModelState:
        return ModelState(self.net.init(), (), jnp.int32(0))

    def _scan_samples(self, w0, n0, X, y, mask, body):
        def step(carry, inp):
            w, n = carry
            x_i, y_i, m_i = inp
            w_new, n_new = body(w, n, x_i, y_i)
            w = jnp.where(m_i > 0, w_new, w)
            n = jnp.where(m_i > 0, n_new, n)
            return (w, n), None

        (w, n), _ = jax.lax.scan(step, (w0, n0), (X, y, mask))
        return w, n

    def update(self, state: ModelState, data, key: jax.Array) -> ModelState:
        X, y, mask = data
        lr = self.learning_rate

        def body(w, n, x_i, y_i):
            return w + lr * (y_i - w @ x_i) * x_i, n + 1

        w, n = self._scan_samples(state.params, state.n_updates, X, y, mask, body)
        return ModelState(w, (), n)

    def merge(self, state: ModelState, peer: PeerModel, extra=None) -> ModelState:
        w = 0.5 * (state.params + peer.params)  # handler.py:370-373
        return ModelState(w, (), jnp.maximum(state.n_updates, peer.n_updates))

    def evaluate(self, state: ModelState, data) -> dict:
        X, y, mask = data
        return signed_binary_metrics(X @ state.params, y, mask)


class PegasosHandler(AdaLineHandler):
    """Pegasos SVM (reference handler.py:394-423).

    Per sample with running count t: ``eta = 1/(t * lam)``; the margin test
    uses the score from BEFORE the decay (handler.py:421-423):
    ``w <- (1 - eta*lam) * w + [y_i * (w_old.x_i) < 1] * eta * y_i * x_i``.
    ``learning_rate`` is the regularization constant lambda, as in the
    reference's naming.
    """

    def update(self, state: ModelState, data, key: jax.Array) -> ModelState:
        X, y, mask = data
        lam = self.learning_rate

        def body(w, n, x_i, y_i):
            t = (n + 1).astype(jnp.float32)
            eta = 1.0 / (t * lam)
            score = w @ x_i
            w = w * (1.0 - eta * lam)
            hinge_active = (score * y_i - 1.0) < 0
            w = w + jnp.where(hinge_active, eta * y_i, 0.0) * x_i
            return w, n + 1

        w, n = self._scan_samples(state.params, state.n_updates, X, y, mask, body)
        return ModelState(w, (), n)
