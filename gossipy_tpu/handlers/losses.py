"""Masked loss functions.

The reference passes ``torch.nn`` criteria into ``TorchModelHandler``
(handler.py:190,225). Here losses are pure ``(scores, targets, mask) ->
scalar`` functions; ``mask`` weights out shard padding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _masked_mean(v: jax.Array, mask: jax.Array | None) -> jax.Array:
    if mask is None:
        return v.mean()
    m = mask.astype(v.dtype)
    denom = m.sum()
    return jnp.where(denom > 0, (v * m).sum() / jnp.where(denom > 0, denom, 1.0), 0.0)


def cross_entropy(scores: jax.Array, y: jax.Array,
                  mask: jax.Array | None = None) -> jax.Array:
    """``torch.nn.CrossEntropyLoss`` equivalent: log-softmax over scores + NLL.

    Accepts integer labels [B] or one-hot [B, C]. Note the reference applies
    this on top of sigmoid outputs for LogisticRegression — identical here
    since the model itself emits the sigmoid (models/nn.py).
    """
    logp = jax.nn.log_softmax(scores, axis=-1)
    if y.ndim == scores.ndim:
        nll = -(y * logp).sum(axis=-1)
    else:
        nll = -jnp.take_along_axis(logp, y[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return _masked_mean(nll, mask)


def mse(scores: jax.Array, y: jax.Array, mask: jax.Array | None = None) -> jax.Array:
    """``torch.nn.MSELoss`` equivalent."""
    if y.ndim < scores.ndim:
        y = y[..., None]
    err = ((scores - y) ** 2).mean(axis=-1)
    return _masked_mean(err, mask)


def binary_cross_entropy(scores: jax.Array, y: jax.Array,
                         mask: jax.Array | None = None) -> jax.Array:
    """``torch.nn.BCELoss`` equivalent on probability outputs (e.g. Perceptron)."""
    s = jnp.clip(scores.squeeze(-1) if scores.ndim > y.ndim else scores, 1e-7, 1 - 1e-7)
    nll = -(y * jnp.log(s) + (1 - y) * jnp.log(1 - s))
    return _masked_mean(nll, mask)
