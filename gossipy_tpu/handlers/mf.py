"""Matrix-factorization recommender handler (Hegedus 2020 gossip MF).

Re-design of ``MFModelHandler`` (reference handler.py:528-576). Each node is
one user: params = {user factor X [k], user bias b, item factors Y
[n_items, k], item biases c [n_items]}. The per-rating SGD loop
(handler.py:550-560) becomes a ``lax.scan`` over the node's padded rating
list; only the item state (Y, c) is merged between peers (handler.py:562-568).

Intentional divergence: the reference's merge divides by ``2 * (n1 + n2)``
(handler.py:566-567), which SHRINKS the merged factors by half on every
exchange — we use the proper age-weighted average (divide by ``n1 + n2``),
documented per SURVEY.md §7(f).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import CreateModelMode
from ..utils import rmse
from .base import BaseHandler, ModelState, PeerModel


class MFHandler(BaseHandler):
    """Gossip matrix factorization for one-user-per-node recommendation.

    Data convention: ``data = (items, ratings, mask)`` — int32 item ids [S],
    float ratings [S], validity mask [S].
    """

    def __init__(self, dim: int, n_items: int, lam_reg: float = 0.1,
                 learning_rate: float = 0.001,
                 r_min: float = 1.0, r_max: float = 5.0,
                 create_model_mode: CreateModelMode = CreateModelMode.UPDATE):
        self.k = dim
        self.n_items = n_items
        self.reg = lam_reg
        self.lr = learning_rate
        self.r_min = r_min
        self.r_max = r_max
        self.mode = create_model_mode

    def init(self, key: jax.Array) -> ModelState:
        # handler.py:542-548: U(0,1)*sqrt((r_max-r_min)/k) factors, r_min/2 biases.
        kx, ky = jax.random.split(key)
        mul = jnp.sqrt((self.r_max - self.r_min) / self.k)
        params = {
            "X": jax.random.uniform(kx, (self.k,)) * mul,
            "b": jnp.float32(self.r_min / 2.0),
            "Y": jax.random.uniform(ky, (self.n_items, self.k)) * mul,
            "c": jnp.ones((self.n_items,)) * (self.r_min / 2.0),
        }
        # n_updates starts at 1 (handler.py:540).
        return ModelState(params, (), jnp.int32(1))

    def update(self, state: ModelState, data, key: jax.Array) -> ModelState:
        items, ratings, mask = data
        lr, reg = self.lr, self.reg

        def step(carry, inp):
            p, n = carry
            i, r, m = inp
            yi = p["Y"][i]
            err = r - p["X"] @ yi - p["b"] - p["c"][i]
            yi_new = (1.0 - reg * lr) * yi + lr * err * p["X"]
            x_new = (1.0 - reg * lr) * p["X"] + lr * err * yi_new  # uses updated Y[i], handler.py:555-556
            p_new = {
                "X": x_new,
                "b": p["b"] + lr * err,
                "Y": p["Y"].at[i].set(yi_new),
                "c": p["c"].at[i].add(lr * err),
            }
            p = jax.tree.map(lambda a, b: jnp.where(m > 0, a, b), p_new, p)
            return (p, n + (m > 0).astype(n.dtype)), None

        (params, n), _ = jax.lax.scan(
            step, (state.params, state.n_updates),
            (items.astype(jnp.int32), ratings, mask))
        return ModelState(params, (), n)

    def merge(self, state: ModelState, peer: PeerModel, extra=None) -> ModelState:
        n1 = state.n_updates.astype(jnp.float32)
        n2 = peer.n_updates.astype(jnp.float32)
        den = jnp.maximum(n1 + n2, 1.0)
        params = dict(state.params)
        params["Y"] = (state.params["Y"] * n1 + peer.params["Y"] * n2) / den
        params["c"] = (state.params["c"] * n1 + peer.params["c"] * n2) / den
        # Ages: the reference keeps self.n_updates unchanged on MF merge
        # (handler.py:562-568 never touches it); mirror that.
        return ModelState(params, (), state.n_updates)

    def evaluate(self, state: ModelState, data) -> dict:
        items, ratings, mask = data
        p = state.params
        pred_all = p["Y"] @ p["X"] + p["b"] + p["c"]  # [n_items]
        pred = pred_all[items.astype(jnp.int32)]
        return {"rmse": rmse(pred, ratings, mask)}

    def get_size(self) -> int:
        """Message size in scalars (handler.py:575-576): only (Y, c) travel."""
        return self.k * (self.n_items + 1)
