"""Online gossip k-means handler (Berta 2014 experiments).

Re-design of ``KMeansHandler`` (reference handler.py:579-639). Params = the
[k, dim] centroid matrix. Differences from the reference, both documented:

- The reference's batch EMA ``model[idx] = model[idx]*(1-a) + a*x`` relies on
  torch fancy-assignment where, among duplicate indices, an arbitrary (last)
  write wins (handler.py:608-615). We move each centroid toward the *mean* of
  the samples assigned to it — deterministic and batch-size invariant.
- ``matching="hungarian"`` (handler.py:626-630) calls scipy's Hungarian
  solver on host. We split by execution context: EAGER merges (host-side
  analysis, the flight recorder's ``jax.disable_jit`` phase localization,
  direct ``handler.merge`` calls) use the EXACT solver
  (:func:`exact_match`, ``scipy.optimize.linear_sum_assignment``);
  TRACED merges (the jitted engines — and the sequential engine's jitted
  single-node calls) use :func:`greedy_match`, a sequential
  cheapest-pair assignment that stays inside jit.

  The tradeoff, quantified in ``tests/test_handlers.py``
  (``TestKMeansMatching``): greedy is exact whenever centroids are
  well-separated relative to the inter-set drift (each centroid's true
  partner is its global nearest — the typical gossip regime, where peers
  train on samples of the same clusters), but on crafted cost matrices
  it can exceed the optimal assignment cost by an unbounded factor
  (locking a cheap pair that forces an expensive completion). Greedy is
  O(k^3) like one Hungarian augmentation sweep and shape-static; the
  exact solver is host-only. Both produce a permutation, so the merged
  centroid count never changes — only WHICH pairs average.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import CreateModelMode
from ..utils import nmi
from .base import BaseHandler, ModelState, PeerModel


def greedy_match(cost: jax.Array) -> jax.Array:
    """Greedy linear assignment: repeatedly take the globally-cheapest
    (row, col) pair. Returns for each row of ``cost`` the matched column.
    Optimal for well-separated centroids; see the module doc (and
    :func:`exact_match`) for the divergence contract."""
    k = cost.shape[0]
    big = jnp.inf

    def body(i, carry):
        c, match = carry
        flat = jnp.argmin(c)
        r, col = flat // k, flat % k
        match = match.at[r].set(col)
        c = c.at[r, :].set(big)
        c = c.at[:, col].set(big)
        return c, match

    _, match = jax.lax.fori_loop(0, k, body,
                                 (cost, jnp.zeros((k,), dtype=jnp.int32)))
    return match


def exact_match(cost) -> np.ndarray:
    """Exact minimum-cost linear assignment (Hungarian algorithm via
    ``scipy.optimize.linear_sum_assignment``). Host-side only — the
    eager counterpart of :func:`greedy_match`. Returns for each row the
    matched column (int32 [k])."""
    from scipy.optimize import linear_sum_assignment
    rows, cols = linear_sum_assignment(np.asarray(cost))
    out = np.zeros(cost.shape[0], dtype=np.int32)
    out[rows] = cols.astype(np.int32)
    return out


class KMeansHandler(BaseHandler):
    """Online k-means with EMA centroid updates and averaged merges."""

    def __init__(self, k: int, dim: int, alpha: float = 0.1,
                 matching: str = "naive",
                 create_model_mode: CreateModelMode = CreateModelMode.UPDATE):
        assert matching in {"naive", "hungarian"}, "Invalid matching method."
        self.k = k
        self.dim = dim
        self.alpha = alpha
        self.matching = matching
        self.mode = create_model_mode

    def init(self, key: jax.Array) -> ModelState:
        centroids = jax.random.uniform(key, (self.k, self.dim))  # handler.py:594-595
        return ModelState(centroids, (), jnp.int32(0))

    def _assign(self, centroids: jax.Array, X: jax.Array) -> jax.Array:
        d2 = ((X[:, None, :] - centroids[None, :, :]) ** 2).sum(-1)
        return jnp.argmin(d2, axis=1)

    def update(self, state: ModelState, data, key: jax.Array) -> ModelState:
        X, _, mask = data
        c = state.params
        idx = self._assign(c, X)
        onehot = jax.nn.one_hot(idx, self.k) * mask[:, None]   # [S, k]
        counts = onehot.sum(axis=0)                            # [k]
        sums = onehot.T @ X                                    # [k, dim]
        means = sums / jnp.maximum(counts, 1.0)[:, None]
        moved = c * (1 - self.alpha) + self.alpha * means
        c = jnp.where((counts > 0)[:, None], moved, c)
        return ModelState(c, (), state.n_updates + 1)

    def _match(self, cost: jax.Array) -> jax.Array:
        """Centroid assignment for a merge: exact Hungarian on the
        host/eager path, greedy inside a trace (see module doc)."""
        if isinstance(cost, jax.core.Tracer):
            return greedy_match(cost)
        try:
            return jnp.asarray(exact_match(cost))
        except ImportError:  # scipy unavailable: greedy everywhere
            return greedy_match(cost)

    def merge(self, state: ModelState, peer: PeerModel, extra=None) -> ModelState:
        c1, c2 = state.params, peer.params
        if self.matching == "naive":
            c = (c1 + c2) / 2.0  # handler.py:624-625
        else:
            d2 = ((c1[:, None, :] - c2[None, :, :]) ** 2).sum(-1)
            match = self._match(jnp.sqrt(d2))
            c = (c1 + c2[match]) / 2.0  # handler.py:626-630
        return ModelState(c, (), jnp.maximum(state.n_updates, peer.n_updates))

    def evaluate(self, state: ModelState, data) -> dict:
        X, y, mask = data
        y_pred = self._assign(state.params, X)
        return {"nmi": nmi(y.astype(jnp.int32), y_pred, self.k, self.k, mask)}

    def get_size(self) -> int:
        return self.k * self.dim  # handler.py:638-639
