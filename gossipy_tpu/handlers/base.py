"""Handler protocol: pure train/merge/eval functions over pytree model states.

The reference's ``ModelHandler`` (gossipy/model/handler.py:58-182) is a
stateful object that deep-copies itself into a global cache on every send.
Here a handler is a *static configuration object* whose methods are pure
functions over :class:`ModelState`; the simulation engine vmaps them across
the node axis and closes over the handler when jitting (no mutable state, no
copies — "sending a model" is a gather along the node axis).

``CreateModelMode`` dispatch (reference handler.py:117-136) happens at trace
time (the mode is static), so each compiled program contains exactly one
branch.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax

from ..core import CreateModelMode


class ModelState(NamedTuple):
    """One node's full learning state (stacks along a leading node axis).

    - ``params``: model parameter pytree
    - ``opt_state``: optimizer state pytree (``()`` for stateless rules)
    - ``n_updates``: int32 age — scalar, or [n_parts] for partitioned handlers
      (reference handler.py:92, PartitionedTMH at :475)
    """

    params: Any
    opt_state: Any
    n_updates: jax.Array


class PeerModel(NamedTuple):
    """What travels in a message: the sender's params + age snapshot.

    The reference ships the whole deep-copied handler through ``CACHE``
    (handler.py:160-176); optimizer state is omitted here — for the plain-SGD
    experiments it is empty anyway, and receivers train received models with
    their own optimizer slot.
    """

    params: Any
    n_updates: jax.Array


class BaseHandler:
    """Common mode-dispatch logic. Subclasses define init/update/merge/evaluate.

    Method contracts (single node; the engine vmaps):

    - ``init(key) -> ModelState``
    - ``update(state, data, key) -> ModelState`` — local training pass
    - ``merge(state, peer, extra=None) -> ModelState``
    - ``evaluate(state, data) -> dict[str, Array]``
    - ``call(state, peer, data, key, extra=None) -> ModelState`` — the
      receive-time composition (reference handler.py:117-136)
    """

    mode: CreateModelMode = CreateModelMode.MERGE_UPDATE
    # True when ``merge`` is exactly the uniform parameter average with
    # age = max (the engine's pallas fused path may then replace it).
    uniform_avg_merge: bool = False
    # The peer coefficient of that blend (``out = (1 - w) * own + w * peer``),
    # declared by handlers whose merge the fused kernel may replace. None
    # everywhere else, so a future weighted-merge handler that flips
    # ``uniform_avg_merge`` on without declaring its weight fails loudly at
    # simulator construction instead of silently averaging at 0.5.
    merge_peer_weight: Optional[float] = None

    # -- abstract ----------------------------------------------------------
    def init(self, key: jax.Array) -> ModelState:
        raise NotImplementedError

    def update(self, state: ModelState, data, key: jax.Array) -> ModelState:
        raise NotImplementedError

    def merge(self, state: ModelState, peer: PeerModel, extra=None) -> ModelState:
        raise NotImplementedError

    def evaluate(self, state: ModelState, data) -> dict:
        raise NotImplementedError

    # -- shared ------------------------------------------------------------
    def peer_view(self, state: ModelState) -> PeerModel:
        """The message payload for this node's state."""
        return PeerModel(state.params, state.n_updates)

    def call(self, state: ModelState, peer: PeerModel, data, key: jax.Array,
             extra=None) -> ModelState:
        """Receive-time dispatch on the (static) create-model mode."""
        mode = self.mode
        if mode == CreateModelMode.UPDATE:
            # Train the received model on local data, adopt it (handler.py:122-125).
            recv_state = ModelState(peer.params, state.opt_state, peer.n_updates)
            return self.update(recv_state, data, key)
        if mode == CreateModelMode.MERGE_UPDATE:
            merged = self.merge(state, peer, extra)
            return self.update(merged, data, key)
        if mode == CreateModelMode.UPDATE_MERGE:
            k1, k2 = jax.random.split(key)
            mine = self.update(state, data, k1)
            recv_state = ModelState(peer.params, state.opt_state, peer.n_updates)
            theirs = self.update(recv_state, data, k2)
            return self.merge(mine, PeerModel(theirs.params, theirs.n_updates), extra)
        if mode == CreateModelMode.PASS:
            return ModelState(peer.params, state.opt_state, peer.n_updates)
        raise ValueError(f"Unknown create model mode {mode}")


def select_state(cond: jax.Array, a: ModelState, b: ModelState) -> ModelState:
    """``cond ? a : b`` leafwise — used to mask no-op receives in the engine."""
    import jax.numpy as jnp
    return jax.tree.map(lambda x, y: jnp.where(cond, x, y), a, b)
