"""Model handlers: pure train/merge/eval engines (reference gossipy/model/handler.py)."""

from .base import BaseHandler, ModelState, PeerModel, select_state
from .linear import AdaLineHandler, PegasosHandler
from .kmeans import KMeansHandler
from .mf import MFHandler
from .sgd import (
    LimitedMergeSGDHandler,
    PartitionedSGDHandler,
    SamplingSGDHandler,
    SGDHandler,
    WeightedSGDHandler,
)
from . import losses

__all__ = [
    "BaseHandler", "ModelState", "PeerModel", "select_state",
    "AdaLineHandler", "PegasosHandler", "KMeansHandler", "MFHandler",
    "SGDHandler", "WeightedSGDHandler", "LimitedMergeSGDHandler",
    "SamplingSGDHandler", "PartitionedSGDHandler", "losses",
]
