"""SGD-trained neural model handlers (the ``TorchModelHandler`` family).

Re-design of reference gossipy/model/handler.py:185-334 and its variants
(:455-525 partitioned, :426-452 sampled, :642-688 weighted, :690-739
limited-merge). Training is a ``lax.scan`` over permuted minibatches of the
node's padded shard; autograd via ``jax.value_and_grad``; optimizers are
optax gradient transformations. Everything is a pure function of
``(ModelState, data, key)`` so the engine can vmap it across all nodes.

Data convention: ``data = (X, y, mask)`` with static shard length S; ``mask``
flags real rows vs padding (SURVEY.md §7 hard part (a)).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import optax

from ..compression import ModelPartition, sample_mask, sampled_merge
from ..core import CreateModelMode
from ..utils import classification_metrics
from .base import BaseHandler, ModelState, PeerModel


def _tree_avg(p1, p2):
    return jax.tree.map(lambda a, b: (a + b) / 2.0, p1, p2)


class SGDHandler(BaseHandler):
    """Train/merge/eval for a flax model under an optax optimizer.

    Equivalent of ``TorchModelHandler`` (reference handler.py:185-334):

    - ``update`` = ``local_epochs`` x permuted minibatch SGD (handler.py:235-248),
      as a ``lax.scan`` over static-size batches with mask-weighted loss.
      ``n_updates`` increments once per non-empty batch (handler.py:258).
    - ``merge`` = uniform parameter average, age = max (handler.py:260-280).
    - ``evaluate`` = accuracy/precision/recall/F1 (+AUC for binary)
      (handler.py:282-334) in pure JAX.
    """

    uniform_avg_merge = True
    merge_peer_weight = 0.5

    def __init__(self,
                 model,
                 loss: Callable,
                 optimizer: optax.GradientTransformation | None = None,
                 learning_rate: float = 0.01,
                 local_epochs: int = 1,
                 batch_size: int = 32,
                 n_classes: int = 2,
                 input_shape: Sequence[int] = (2,),
                 create_model_mode: CreateModelMode = CreateModelMode.MERGE_UPDATE,
                 compute_dtype: Optional[Any] = None,
                 remat: bool = False):
        assert (batch_size == 0 and local_epochs > 0) or batch_size > 0, \
            "batch_size == 0 (full batch) requires local_epochs > 0"  # handler.py:226
        self.model = model
        self.loss = loss
        self.optimizer = optimizer if optimizer is not None else optax.sgd(learning_rate)
        self.local_epochs = local_epochs
        self.batch_size = batch_size
        self.n_classes = n_classes
        self.input_shape = tuple(input_shape)
        self.mode = create_model_mode
        # Mixed precision: cast params+inputs to this dtype for the forward/
        # backward pass (bfloat16 keeps the MXU fed at full rate on TPU);
        # master params, optimizer state and merges stay float32. No
        # reference analogue (torch runs f32 end to end).
        self.compute_dtype = compute_dtype
        # Rematerialization: recompute the forward during the backward pass
        # instead of storing activations (jax.checkpoint). Activations of
        # the per-node training batch — [nodes x batch, ...] once the
        # engine vmaps over the population — are the peak-HBM driver for
        # conv models; remat trades one extra forward for that memory,
        # letting larger populations/batches fit on a chip. Numerically
        # identical (tested). No reference analogue.
        self.remat = remat

    # -- model plumbing ----------------------------------------------------

    def apply(self, params, x):
        if self.compute_dtype is not None:
            params = jax.tree.map(lambda a: a.astype(self.compute_dtype), params)
            x = x.astype(self.compute_dtype)
            return self.model.apply({"params": params}, x).astype(jnp.float32)
        return self.model.apply({"params": params}, x)

    def init(self, key: jax.Array) -> ModelState:
        dummy = jnp.zeros((1,) + self.input_shape, dtype=jnp.float32)
        params = self.model.init(key, dummy)["params"]
        opt_state = self.optimizer.init(params)
        return ModelState(params, opt_state, jnp.int32(0))

    # -- training ----------------------------------------------------------

    def _adjust_gradient(self, grads, n_updates):
        """Hook for subclasses (PartitionedSGDHandler divides by partition age)."""
        return grads

    def _count_updates(self, n_updates, any_real):
        return n_updates + any_real.astype(n_updates.dtype)

    def _sgd_step(self, state: ModelState, xb, yb, mb) -> ModelState:
        params, opt_state, n_updates = state
        apply = jax.checkpoint(self.apply) if self.remat else self.apply

        def loss_fn(p):
            return self.loss(apply(p, xb), yb, mb)

        grads = jax.grad(loss_fn)(params)
        any_real = mb.sum() > 0
        # PartitionedTMH increments ages BEFORE the gradient adjustment
        # (handler.py:503-512); for the plain handler the increment is
        # equivalent to the post-step one at handler.py:258.
        n_new = self._count_updates(n_updates, any_real)
        grads = self._adjust_gradient(grads, n_new)
        updates, opt_new = self.optimizer.update(grads, opt_state, params)
        p_new = optax.apply_updates(params, updates)
        # Empty (fully padded) batches are no-ops.
        params = jax.tree.map(lambda a, b: jnp.where(any_real, a, b), p_new, params)
        opt_state = jax.tree.map(lambda a, b: jnp.where(any_real, a, b), opt_new, opt_state)
        return ModelState(params, opt_state, n_new)

    def update(self, state: ModelState, data, key: jax.Array) -> ModelState:
        X, y, mask = data
        S = X.shape[0]
        B = self.batch_size if self.batch_size else S
        n_batches = max(1, math.ceil(S / B))
        pad = n_batches * B - S

        def run_epoch(state, ekey):
            perm = jax.random.permutation(ekey, S)
            if pad:
                # Wrap indices so the padded tail is valid even when B >> S;
                # slot_ok masks every slot past the real shard length.
                perm = perm[jnp.arange(n_batches * B) % S]
            slot_ok = (jnp.arange(n_batches * B) < S).astype(mask.dtype)

            def step(st, i):
                idx = jax.lax.dynamic_slice(perm, (i * B,), (B,))
                mb = mask[idx] * jax.lax.dynamic_slice(slot_ok, (i * B,), (B,))
                return self._sgd_step(st, X[idx], y[idx], mb), None

            state, _ = jax.lax.scan(step, state, jnp.arange(n_batches))
            return state, None

        if self.local_epochs > 0:
            keys = jax.random.split(key, self.local_epochs)
            state, _ = jax.lax.scan(run_epoch, state, keys)
            return state
        # local_epochs == 0: one step on batch_size random samples (handler.py:245-247)
        perm = jax.random.permutation(key, S)[:B]
        return self._sgd_step(state, X[perm], y[perm], mask[perm])

    # -- merging -----------------------------------------------------------

    def merge(self, state: ModelState, peer: PeerModel, extra=None) -> ModelState:
        params = _tree_avg(state.params, peer.params)
        return ModelState(params, state.opt_state,
                          jnp.maximum(state.n_updates, peer.n_updates))

    # -- evaluation --------------------------------------------------------

    def evaluate(self, state: ModelState, data) -> dict:
        X, y, mask = data
        scores = self.apply(state.params, X)
        return classification_metrics(scores, y, self.n_classes, mask)


class WeightedSGDHandler(SGDHandler):
    """Merge with caller-supplied weights over 1 + K models (``WeightedTMH``).

    Reference handler.py:642-688: ``merged = w0 * self + sum_k w_k * other_k``.
    ``extra`` = (stacked peer params with leading K axis, weights [K+1],
    peer ages [K], valid mask [K]).
    """

    def merge_many(self, state: ModelState, peers_params, weights,
                   peer_ages, valid) -> ModelState:
        w0 = weights[0]
        wk = weights[1:] * valid  # zero out empty slots
        # Renormalize so the dropped slots' mass goes back to a proper average.
        total = w0 + wk.sum()
        w0 = w0 / total
        wk = wk / total

        def leaf(p_self, p_peers):
            wk_b = wk.reshape((-1,) + (1,) * p_self.ndim)
            return w0 * p_self + (wk_b * p_peers).sum(axis=0)

        params = jax.tree.map(lambda a, b: leaf(a, b), state.params, peers_params)
        ages = jnp.where(valid > 0, peer_ages, 0)
        n_up = jnp.maximum(state.n_updates, ages.max(initial=0))
        return ModelState(params, state.opt_state, n_up)


class LimitedMergeSGDHandler(SGDHandler):
    """Danner 2023 limited merging (``LimitedMergeTMH``, handler.py:690-739).

    If the age gap exceeds L, adopt the younger... actually the OLDER model
    wholesale (the one with more updates wins); otherwise age-weighted average.
    """

    uniform_avg_merge = False

    def __init__(self, *args, age_diff_threshold: int = 1, **kwargs):
        super().__init__(*args, **kwargs)
        self.L = age_diff_threshold

    def merge(self, state: ModelState, peer: PeerModel, extra=None) -> ModelState:
        a1 = state.n_updates.astype(jnp.float32)
        a2 = peer.n_updates.astype(jnp.float32)
        tot = a1 + a2
        # Two age-0 models fall back to a plain average (cf. the identical
        # guard in ModelPartition.merge); without this the weighted branch
        # would zero both models out.
        w1 = jnp.where(tot > 0, a1 / jnp.where(tot > 0, tot, 1.0), 0.5)
        w2 = jnp.where(tot > 0, a2 / jnp.where(tot > 0, tot, 1.0), 0.5)
        keep_self = a1 > a2 + self.L
        keep_peer = a2 > a1 + self.L

        def leaf(p1, p2):
            avg = w1 * p1 + w2 * p2
            return jnp.where(keep_self, p1, jnp.where(keep_peer, p2, avg))

        params = jax.tree.map(leaf, state.params, peer.params)
        return ModelState(params, state.opt_state,
                          jnp.maximum(state.n_updates, peer.n_updates))


class _PartialMergeCall:
    """Receive-time dispatch for SUBSET-merge handlers.

    ``SamplingTMH.__call__`` / ``PartitionedTMH.__call__`` (reference
    handler.py:435-452, 478-494) differ from the base ``ModelHandler``
    dispatch in UPDATE mode: the received model is trained on local data and
    then only its subset (sample/partition) is merged into SELF — the local
    model is never replaced wholesale (adopting it would defeat the
    bandwidth-saving subset exchange). Other modes match the base dispatch.
    """

    def call(self, state: ModelState, peer: PeerModel, data, key: jax.Array,
             extra=None) -> ModelState:
        if self.mode == CreateModelMode.UPDATE:
            recv_state = ModelState(peer.params, state.opt_state, peer.n_updates)
            trained = self.update(recv_state, data, key)
            return self.merge(state, PeerModel(trained.params, trained.n_updates),
                              extra)
        return super().call(state, peer, data, key, extra)


class SamplingSGDHandler(_PartialMergeCall, SGDHandler):
    """Merge only a random coordinate subset (``SamplingTMH``, handler.py:426-452).

    ``extra`` is a PRNG key identifying the sample; both sides of an exchange
    derive the same mask from it (the reference ships explicit index sets in
    the message — a key is the 2-word equivalent).
    """

    uniform_avg_merge = False

    def __init__(self, sample_size: float, *args, **kwargs):
        super().__init__(*args, **kwargs)
        assert self.mode != CreateModelMode.PASS, \
            "Mode PASS not allowed for sampled models."  # handler.py:449-450
        self.sample_size = sample_size

    def merge(self, state: ModelState, peer: PeerModel, extra=None) -> ModelState:
        assert extra is not None, "SamplingSGDHandler.merge needs a sample key"
        mask = sample_mask(extra, state.params, self.sample_size)
        params = sampled_merge(state.params, peer.params, mask)
        # Reference SamplingTMH._merge does not advance n_updates (handler.py:431-433).
        return ModelState(params, state.opt_state, state.n_updates)


class PartitionedSGDHandler(_PartialMergeCall, SGDHandler):
    """Partitioned model exchange (``PartitionedTMH``, handler.py:455-525).

    - ``n_updates`` is an int32 [n_parts] age vector (handler.py:475).
    - ``merge`` averages one partition, age-weighted (handler.py:497-501).
    - Gradients are divided by the partition's age before the step
      (handler.py:514-520).
    ``extra`` = the (traced) partition id from the message payload.
    """

    uniform_avg_merge = False

    def __init__(self, partition: ModelPartition, *args, **kwargs):
        super().__init__(*args, **kwargs)
        assert self.mode != CreateModelMode.PASS, \
            "Mode PASS not allowed for partitioned models."  # handler.py:491-492
        self.partition = partition

    def init(self, key: jax.Array) -> ModelState:
        st = super().init(key)
        return ModelState(st.params, st.opt_state,
                          jnp.zeros((self.partition.n_parts,), dtype=jnp.int32))

    def _count_updates(self, n_updates, any_real):
        return n_updates + any_real.astype(n_updates.dtype)  # all parts +1 (handler.py:506)

    def _adjust_gradient(self, grads, n_updates):
        ages = jnp.maximum(n_updates.astype(jnp.float32), 1.0)

        def leaf(g, pid):
            return g / ages[pid]

        return jax.tree.map(leaf, grads, self.partition.part_ids)

    def merge(self, state: ModelState, peer: PeerModel, extra=None) -> ModelState:
        assert extra is not None, "PartitionedSGDHandler.merge needs a partition id"
        pid = jnp.asarray(extra) % self.partition.n_parts
        a1 = state.n_updates[pid]
        a2 = peer.n_updates[pid]
        params = self.partition.merge(state.params, peer.params, pid, weights=(a1, a2))
        n_up = state.n_updates.at[pid].set(jnp.maximum(a1, a2))
        return ModelState(params, state.opt_state, n_up)
