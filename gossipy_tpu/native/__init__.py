"""Native (C++) runtime components, loaded through ctypes.

The reference is pure Python (SURVEY.md §2: "no C++/Rust/CUDA components"),
so nothing here ports reference code — these are the host-side pieces that
become bottlenecks at the node counts the TPU engine makes practical:

- ``graphgen.cpp``: dense-adjacency topology generators (Erdos-Renyi,
  pairing-model random regular, Barabasi-Albert, ring). networkx needs
  minutes for a 20-regular 50k-node graph; the native generator writes the
  bool adjacency straight into a numpy buffer.

The shared library is built on demand with ``g++ -O3 -shared -fPIC`` and
cached next to the source; every entry point has a pure-Python fallback
(networkx) selected automatically when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "graphgen.cpp")
_LIB = os.path.join(_HERE, "_graphgen.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[str]:
    """Compile the shared library if the cached build is missing/stale."""
    try:
        if (os.path.exists(_LIB)
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return _LIB
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB],
            check=True, capture_output=True, timeout=120)
        return _LIB
    except Exception:
        return None


def load() -> Optional[ctypes.CDLL]:
    """The graphgen library, building it on first use; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _build()
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        u8p = np.ctypeslib.ndpointer(dtype=np.uint8, ndim=2, flags="C_CONTIGUOUS")
        lib.gen_erdos_renyi.argtypes = [ctypes.c_int32, ctypes.c_double,
                                        ctypes.c_uint64, u8p]
        lib.gen_erdos_renyi.restype = None
        lib.gen_random_regular.argtypes = [ctypes.c_int32, ctypes.c_int32,
                                           ctypes.c_uint64, u8p]
        lib.gen_random_regular.restype = ctypes.c_int32
        lib.gen_barabasi_albert.argtypes = [ctypes.c_int32, ctypes.c_int32,
                                            ctypes.c_uint64, u8p]
        lib.gen_barabasi_albert.restype = None
        lib.gen_ring.argtypes = [ctypes.c_int32, ctypes.c_int32, u8p]
        lib.gen_ring.restype = None
        i32p = np.ctypeslib.ndpointer(dtype=np.int32, ndim=2,
                                      flags="C_CONTIGUOUS")
        lib.gen_random_regular_edges.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64, i32p]
        lib.gen_random_regular_edges.restype = ctypes.c_int64
        lib.gen_erdos_renyi_edges.argtypes = [
            ctypes.c_int32, ctypes.c_double, ctypes.c_uint64, i32p,
            ctypes.c_int64]
        lib.gen_erdos_renyi_edges.restype = ctypes.c_int64
        lib.gen_barabasi_albert_edges.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64, i32p]
        lib.gen_barabasi_albert_edges.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def erdos_renyi(n: int, p: float, seed: int = 42) -> np.ndarray:
    lib = load()
    assert lib is not None, "native graphgen unavailable"
    adj = np.zeros((n, n), dtype=np.uint8)
    lib.gen_erdos_renyi(n, float(p), seed, adj)
    return adj.view(bool)  # same itemsize; zero-copy


def random_regular(n: int, k: int, seed: int = 42) -> np.ndarray:
    lib = load()
    assert lib is not None, "native graphgen unavailable"
    adj = np.zeros((n, n), dtype=np.uint8)
    rc = lib.gen_random_regular(n, k, seed, adj)
    if rc == -1:
        raise ValueError(f"no {k}-regular graph on {n} nodes (n*k must be "
                         "even and k < n)")
    if rc != 0:
        raise RuntimeError("pairing model failed to find a simple graph")
    return adj.view(bool)  # same itemsize; zero-copy


def barabasi_albert(n: int, m: int, seed: int = 42) -> np.ndarray:
    lib = load()
    assert lib is not None, "native graphgen unavailable"
    assert 1 <= m < n, "need 1 <= m < n"
    adj = np.zeros((n, n), dtype=np.uint8)
    lib.gen_barabasi_albert(n, m, seed, adj)
    return adj.view(bool)  # same itemsize; zero-copy


def random_regular_edges(n: int, k: int, seed: int = 42) -> np.ndarray:
    """Undirected edge list [E, 2] of a k-regular graph — the O(E) path for
    node counts where the dense [n, n] buffer would not fit."""
    lib = load()
    assert lib is not None, "native graphgen unavailable"
    edges = np.empty((n * k // 2 + 1, 2), dtype=np.int32)
    m = lib.gen_random_regular_edges(n, k, seed, edges)
    if m == -1:
        raise ValueError(f"no {k}-regular graph on {n} nodes (n*k must be "
                         "even and k < n)")
    if m < 0:
        raise RuntimeError("pairing model failed to find a simple graph")
    return edges[:m]


def erdos_renyi_edges(n: int, p: float, seed: int = 42) -> np.ndarray:
    """Undirected edge list [E, 2] of G(n, p) via skip-sampling (O(E + n))."""
    lib = load()
    assert lib is not None, "native graphgen unavailable"
    mean = p * n * (n - 1) / 2
    cap = int(mean + 6 * np.sqrt(mean + 1) + 64)
    while True:
        edges = np.empty((cap, 2), dtype=np.int32)
        m = lib.gen_erdos_renyi_edges(n, float(p), seed, edges, cap)
        if m <= cap:
            return edges[:m]
        cap = int(m) + 64  # same seed -> same sequence; retry exact-sized


def barabasi_albert_edges(n: int, m: int, seed: int = 42) -> np.ndarray:
    """Undirected edge list [E, 2] of a Barabasi-Albert graph."""
    lib = load()
    assert lib is not None, "native graphgen unavailable"
    assert 1 <= m < n, "need 1 <= m < n"
    edges = np.empty((m * (n - m - 1) + m + 1, 2), dtype=np.int32)
    cnt = lib.gen_barabasi_albert_edges(n, m, seed, edges)
    return edges[:cnt]


def ring(n: int, k: int = 1) -> np.ndarray:
    lib = load()
    assert lib is not None, "native graphgen unavailable"
    adj = np.zeros((n, n), dtype=np.uint8)
    lib.gen_ring(n, k, adj)
    return adj.view(bool)  # same itemsize; zero-copy
