// Fast P2P topology generation for large simulated networks.
//
// The reference builds its topologies with networkx on the Python side
// (gossipy main_* scripts; StaticP2PNetwork at gossipy/core.py:364-389).
// networkx's pure-Python generators become the setup bottleneck for
// 10k+-node simulations (the TPU engine itself handles such node counts
// easily), so the heavy generators live here: dense bool adjacency written
// straight into a numpy-owned buffer through ctypes, seeded mt19937_64 for
// reproducibility. Graph *semantics* match the classic models (G(n,p),
// pairing-model random regular with retries, Barabasi-Albert preferential
// attachment via the repeated-endpoints trick); exact edge sets differ from
// networkx's RNG stream, so a topology is reproducible per (backend, seed).
//
// Build: see gossipy_tpu/native/__init__.py (g++ -O3 -shared -fPIC).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

extern "C" {

// G(n, p): every undirected edge present independently with prob p.
void gen_erdos_renyi(int32_t n, double p, uint64_t seed, uint8_t* adj) {
    std::mt19937_64 rng(seed);
    std::uniform_real_distribution<double> u(0.0, 1.0);
    std::memset(adj, 0, (size_t)n * n);
    for (int32_t i = 0; i < n; ++i) {
        for (int32_t j = i + 1; j < n; ++j) {
            if (u(rng) < p) {
                adj[(size_t)i * n + j] = 1;
                adj[(size_t)j * n + i] = 1;
            }
        }
    }
}

// k-regular random graph via the pairing (configuration) model with
// edge-swap repair: shuffle k copies of every vertex, pair adjacent stubs,
// then fix self-loops/multi-edges by double-edge swaps against random good
// edges (whole-graph rejection has acceptance ~e^{-k^2/4} — hopeless for
// k=20; local swaps preserve the degree sequence and a near-uniform draw).
// Returns 0 on success, -1 if n*k is odd or k >= n, -2 if repair failed.
int32_t gen_random_regular(int32_t n, int32_t k, uint64_t seed, uint8_t* adj) {
    if (k >= n || ((int64_t)n * k) % 2 != 0) return -1;
    std::mt19937_64 rng(seed);
    std::vector<int32_t> stubs((size_t)n * k);
    for (int32_t v = 0; v < n; ++v)
        for (int32_t c = 0; c < k; ++c) stubs[(size_t)v * k + c] = v;

    for (int attempt = 0; attempt < 20; ++attempt) {
        std::shuffle(stubs.begin(), stubs.end(), rng);
        std::memset(adj, 0, (size_t)n * n);
        // Accept all pairs; remember the conflicting ones for repair.
        std::vector<std::pair<int32_t, int32_t>> edges;   // good edges
        std::vector<std::pair<int32_t, int32_t>> bad;     // loops/dups
        edges.reserve(stubs.size() / 2);
        for (size_t s = 0; s + 1 < stubs.size(); s += 2) {
            int32_t a = stubs[s], b = stubs[s + 1];
            if (a == b || adj[(size_t)a * n + b]) {
                bad.emplace_back(a, b);
            } else {
                adj[(size_t)a * n + b] = 1;
                adj[(size_t)b * n + a] = 1;
                edges.emplace_back(a, b);
            }
        }
        // Repair: swap each bad pair (a,b) with a random good edge (c,d):
        // (a,b),(c,d) -> (a,c),(b,d). Valid iff both new edges are simple.
        bool ok = true;
        if (edges.empty() && !bad.empty()) ok = false;  // nothing to swap with
        for (auto& ab : bad) {
            if (!ok) break;
            int32_t a = ab.first, b = ab.second;
            bool fixed = false;
            for (int tries = 0; tries < 2000 && !fixed; ++tries) {
                std::uniform_int_distribution<size_t> d(0, edges.size() - 1);
                size_t ei = d(rng);
                int32_t c = edges[ei].first, e = edges[ei].second;
                // Randomize orientation of the picked edge.
                if (rng() & 1) std::swap(c, e);
                if (a == c || a == e || b == c || b == e) continue;
                if (adj[(size_t)a * n + c] || adj[(size_t)b * n + e]) continue;
                adj[(size_t)c * n + e] = 0;
                adj[(size_t)e * n + c] = 0;
                adj[(size_t)a * n + c] = 1;
                adj[(size_t)c * n + a] = 1;
                adj[(size_t)b * n + e] = 1;
                adj[(size_t)e * n + b] = 1;
                edges[ei] = {a, c};
                edges.emplace_back(b, e);
                fixed = true;
            }
            if (!fixed) { ok = false; break; }
        }
        if (ok) return 0;
    }
    return -2;
}

// Barabasi-Albert preferential attachment: start from m connected seeds,
// attach each new node to m distinct targets drawn from the
// repeated-endpoints list (degree-proportional).
void gen_barabasi_albert(int32_t n, int32_t m, uint64_t seed, uint8_t* adj) {
    std::mt19937_64 rng(seed);
    std::memset(adj, 0, (size_t)n * n);
    if (m < 1 || n <= m) return;
    std::vector<int32_t> endpoints;  // every edge contributes both endpoints
    endpoints.reserve((size_t)2 * m * n);
    // Seed: star over the first m+1 nodes (connected, every node has degree>=1).
    for (int32_t v = 1; v <= m; ++v) {
        adj[(size_t)0 * n + v] = 1;
        adj[(size_t)v * n + 0] = 1;
        endpoints.push_back(0);
        endpoints.push_back(v);
    }
    std::vector<int32_t> targets(m);
    for (int32_t v = m + 1; v < n; ++v) {
        int32_t picked = 0;
        while (picked < m) {
            std::uniform_int_distribution<size_t> d(0, endpoints.size() - 1);
            int32_t t = endpoints[d(rng)];
            bool dup = (t == v) || adj[(size_t)v * n + t];
            for (int32_t q = 0; q < picked && !dup; ++q)
                if (targets[q] == t) dup = true;
            if (!dup) targets[picked++] = t;
        }
        for (int32_t q = 0; q < m; ++q) {
            int32_t t = targets[q];
            adj[(size_t)v * n + t] = 1;
            adj[(size_t)t * n + v] = 1;
            endpoints.push_back(v);
            endpoints.push_back(t);
        }
    }
}

// ---------------------------------------------------------------------------
// Edge-list generators for large n.
//
// A dense [n, n] adjacency is ~2.5 GB of host RAM at n = 50k — the scale
// wall of both the reference (gossipy/core.py StaticP2PNetwork keeps a dense
// matrix) and the dense generators above. These emit an undirected edge list
// (int32 pairs, each edge once) that Python folds into a CSR neighbor table;
// membership checks run against per-node neighbor vectors (degree is small,
// a linear scan beats hashing at these sizes).
// ---------------------------------------------------------------------------

static bool nbr_has(const std::vector<std::vector<int32_t>>& nbrs,
                    int32_t a, int32_t b) {
    const auto& v = nbrs[(size_t)a];
    return std::find(v.begin(), v.end(), b) != v.end();
}

static void nbr_add(std::vector<std::vector<int32_t>>& nbrs,
                    int32_t a, int32_t b) {
    nbrs[(size_t)a].push_back(b);
    nbrs[(size_t)b].push_back(a);
}

static void nbr_del(std::vector<std::vector<int32_t>>& nbrs,
                    int32_t a, int32_t b) {
    auto& va = nbrs[(size_t)a];
    va.erase(std::find(va.begin(), va.end(), b));
    auto& vb = nbrs[(size_t)b];
    vb.erase(std::find(vb.begin(), vb.end(), a));
}

// k-regular pairing model, edge-list output (same algorithm as
// gen_random_regular above, neighbor vectors instead of a dense matrix).
// Writes n*k/2 (a, b) pairs into out; returns the edge count, -1 on invalid
// (n*k odd or k >= n), -2 if repair failed.
int64_t gen_random_regular_edges(int32_t n, int32_t k, uint64_t seed,
                                 int32_t* out) {
    if (k >= n || ((int64_t)n * k) % 2 != 0) return -1;
    std::mt19937_64 rng(seed);
    std::vector<int32_t> stubs((size_t)n * k);
    for (int32_t v = 0; v < n; ++v)
        for (int32_t c = 0; c < k; ++c) stubs[(size_t)v * k + c] = v;

    for (int attempt = 0; attempt < 20; ++attempt) {
        std::shuffle(stubs.begin(), stubs.end(), rng);
        std::vector<std::vector<int32_t>> nbrs(n);
        for (auto& v : nbrs) v.reserve(k);
        std::vector<std::pair<int32_t, int32_t>> edges, bad;
        edges.reserve(stubs.size() / 2);
        for (size_t s = 0; s + 1 < stubs.size(); s += 2) {
            int32_t a = stubs[s], b = stubs[s + 1];
            if (a == b || nbr_has(nbrs, a, b)) {
                bad.emplace_back(a, b);
            } else {
                nbr_add(nbrs, a, b);
                edges.emplace_back(a, b);
            }
        }
        bool ok = true;
        if (edges.empty() && !bad.empty()) ok = false;
        for (auto& ab : bad) {
            if (!ok) break;
            int32_t a = ab.first, b = ab.second;
            bool fixed = false;
            for (int tries = 0; tries < 2000 && !fixed; ++tries) {
                std::uniform_int_distribution<size_t> d(0, edges.size() - 1);
                size_t ei = d(rng);
                int32_t c = edges[ei].first, e = edges[ei].second;
                if (rng() & 1) std::swap(c, e);
                if (a == c || a == e || b == c || b == e) continue;
                if (nbr_has(nbrs, a, c) || nbr_has(nbrs, b, e)) continue;
                nbr_del(nbrs, c, e);
                nbr_add(nbrs, a, c);
                nbr_add(nbrs, b, e);
                edges[ei] = {a, c};
                edges.emplace_back(b, e);
                fixed = true;
            }
            if (!fixed) { ok = false; break; }
        }
        if (ok) {
            int64_t m = (int64_t)edges.size();
            for (int64_t i = 0; i < m; ++i) {
                out[2 * i] = edges[(size_t)i].first;
                out[2 * i + 1] = edges[(size_t)i].second;
            }
            return m;
        }
    }
    return -2;
}

// G(n, p) via geometric skip-sampling over the upper triangle: O(E + n)
// instead of O(n^2) Bernoulli draws. Writes up to cap edges; returns the
// total edge count (callers retry with a bigger buffer if count > cap).
int64_t gen_erdos_renyi_edges(int32_t n, double p, uint64_t seed,
                              int32_t* out, int64_t cap) {
    if (p <= 0.0 || n < 2) return 0;
    std::mt19937_64 rng(seed);
    std::uniform_real_distribution<double> u(0.0, 1.0);
    const double log1mp = std::log(1.0 - p);
    int64_t count = 0;
    int32_t i = 0, j = 0;  // j walks the row's remaining slots (i+1..n-1)
    // Positions advance by 1 + Geom(p) over the flattened upper triangle.
    int64_t pos = -1;
    const int64_t total = (int64_t)n * (n - 1) / 2;
    while (true) {
        double r = u(rng);
        int64_t skip = (p >= 1.0) ? 1
            : 1 + (int64_t)(std::log(1.0 - r) / log1mp);
        pos += skip;
        if (pos >= total) break;
        // Map linear pos -> (i, j) by walking rows (amortized O(n) overall).
        while (true) {
            int64_t row_len = n - 1 - i;
            int64_t row_start = (int64_t)i * (2 * n - i - 1) / 2;
            if (pos < row_start + row_len) { j = (int32_t)(i + 1 + (pos - row_start)); break; }
            ++i;
        }
        if (count < cap) {
            out[2 * count] = i;
            out[2 * count + 1] = j;
        }
        ++count;
    }
    return count;
}

// Barabasi-Albert, edge-list output (same repeated-endpoints model as
// gen_barabasi_albert above). Edge count is exactly m * (n - m - 1) + m.
int64_t gen_barabasi_albert_edges(int32_t n, int32_t m, uint64_t seed,
                                  int32_t* out) {
    if (m < 1 || n <= m) return 0;
    std::mt19937_64 rng(seed);
    std::vector<std::vector<int32_t>> nbrs(n);
    std::vector<int32_t> endpoints;
    endpoints.reserve((size_t)2 * m * n);
    int64_t count = 0;
    for (int32_t v = 1; v <= m; ++v) {
        nbr_add(nbrs, 0, v);
        endpoints.push_back(0);
        endpoints.push_back(v);
        out[2 * count] = 0;
        out[2 * count + 1] = v;
        ++count;
    }
    std::vector<int32_t> targets(m);
    for (int32_t v = m + 1; v < n; ++v) {
        int32_t picked = 0;
        while (picked < m) {
            std::uniform_int_distribution<size_t> d(0, endpoints.size() - 1);
            int32_t t = endpoints[d(rng)];
            bool dup = (t == v) || nbr_has(nbrs, v, t);
            for (int32_t q = 0; q < picked && !dup; ++q)
                if (targets[q] == t) dup = true;
            if (!dup) targets[picked++] = t;
        }
        for (int32_t q = 0; q < m; ++q) {
            int32_t t = targets[q];
            nbr_add(nbrs, v, t);
            endpoints.push_back(v);
            endpoints.push_back(t);
            out[2 * count] = v;
            out[2 * count + 1] = t;
            ++count;
        }
    }
    return count;
}

// Ring lattice: each node linked to its k nearest neighbors per side.
void gen_ring(int32_t n, int32_t k, uint8_t* adj) {
    std::memset(adj, 0, (size_t)n * n);
    for (int32_t i = 0; i < n; ++i) {
        for (int32_t d = 1; d <= k; ++d) {
            int32_t a = (i + d) % n, b = ((i - d) % n + n) % n;
            adj[(size_t)i * n + a] = 1;
            adj[(size_t)i * n + b] = 1;
        }
    }
}

}  // extern "C"
