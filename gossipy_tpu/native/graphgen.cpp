// Fast P2P topology generation for large simulated networks.
//
// The reference builds its topologies with networkx on the Python side
// (gossipy main_* scripts; StaticP2PNetwork at gossipy/core.py:364-389).
// networkx's pure-Python generators become the setup bottleneck for
// 10k+-node simulations (the TPU engine itself handles such node counts
// easily), so the heavy generators live here: dense bool adjacency written
// straight into a numpy-owned buffer through ctypes, seeded mt19937_64 for
// reproducibility. Graph *semantics* match the classic models (G(n,p),
// pairing-model random regular with retries, Barabasi-Albert preferential
// attachment via the repeated-endpoints trick); exact edge sets differ from
// networkx's RNG stream, so a topology is reproducible per (backend, seed).
//
// Build: see gossipy_tpu/native/__init__.py (g++ -O3 -shared -fPIC).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

extern "C" {

// G(n, p): every undirected edge present independently with prob p.
void gen_erdos_renyi(int32_t n, double p, uint64_t seed, uint8_t* adj) {
    std::mt19937_64 rng(seed);
    std::uniform_real_distribution<double> u(0.0, 1.0);
    std::memset(adj, 0, (size_t)n * n);
    for (int32_t i = 0; i < n; ++i) {
        for (int32_t j = i + 1; j < n; ++j) {
            if (u(rng) < p) {
                adj[(size_t)i * n + j] = 1;
                adj[(size_t)j * n + i] = 1;
            }
        }
    }
}

// k-regular random graph via the pairing (configuration) model with
// edge-swap repair: shuffle k copies of every vertex, pair adjacent stubs,
// then fix self-loops/multi-edges by double-edge swaps against random good
// edges (whole-graph rejection has acceptance ~e^{-k^2/4} — hopeless for
// k=20; local swaps preserve the degree sequence and a near-uniform draw).
// Returns 0 on success, -1 if n*k is odd or k >= n, -2 if repair failed.
int32_t gen_random_regular(int32_t n, int32_t k, uint64_t seed, uint8_t* adj) {
    if (k >= n || ((int64_t)n * k) % 2 != 0) return -1;
    std::mt19937_64 rng(seed);
    std::vector<int32_t> stubs((size_t)n * k);
    for (int32_t v = 0; v < n; ++v)
        for (int32_t c = 0; c < k; ++c) stubs[(size_t)v * k + c] = v;

    for (int attempt = 0; attempt < 20; ++attempt) {
        std::shuffle(stubs.begin(), stubs.end(), rng);
        std::memset(adj, 0, (size_t)n * n);
        // Accept all pairs; remember the conflicting ones for repair.
        std::vector<std::pair<int32_t, int32_t>> edges;   // good edges
        std::vector<std::pair<int32_t, int32_t>> bad;     // loops/dups
        edges.reserve(stubs.size() / 2);
        for (size_t s = 0; s + 1 < stubs.size(); s += 2) {
            int32_t a = stubs[s], b = stubs[s + 1];
            if (a == b || adj[(size_t)a * n + b]) {
                bad.emplace_back(a, b);
            } else {
                adj[(size_t)a * n + b] = 1;
                adj[(size_t)b * n + a] = 1;
                edges.emplace_back(a, b);
            }
        }
        // Repair: swap each bad pair (a,b) with a random good edge (c,d):
        // (a,b),(c,d) -> (a,c),(b,d). Valid iff both new edges are simple.
        bool ok = true;
        if (edges.empty() && !bad.empty()) ok = false;  // nothing to swap with
        for (auto& ab : bad) {
            if (!ok) break;
            int32_t a = ab.first, b = ab.second;
            bool fixed = false;
            for (int tries = 0; tries < 2000 && !fixed; ++tries) {
                std::uniform_int_distribution<size_t> d(0, edges.size() - 1);
                size_t ei = d(rng);
                int32_t c = edges[ei].first, e = edges[ei].second;
                // Randomize orientation of the picked edge.
                if (rng() & 1) std::swap(c, e);
                if (a == c || a == e || b == c || b == e) continue;
                if (adj[(size_t)a * n + c] || adj[(size_t)b * n + e]) continue;
                adj[(size_t)c * n + e] = 0;
                adj[(size_t)e * n + c] = 0;
                adj[(size_t)a * n + c] = 1;
                adj[(size_t)c * n + a] = 1;
                adj[(size_t)b * n + e] = 1;
                adj[(size_t)e * n + b] = 1;
                edges[ei] = {a, c};
                edges.emplace_back(b, e);
                fixed = true;
            }
            if (!fixed) { ok = false; break; }
        }
        if (ok) return 0;
    }
    return -2;
}

// Barabasi-Albert preferential attachment: start from m connected seeds,
// attach each new node to m distinct targets drawn from the
// repeated-endpoints list (degree-proportional).
void gen_barabasi_albert(int32_t n, int32_t m, uint64_t seed, uint8_t* adj) {
    std::mt19937_64 rng(seed);
    std::memset(adj, 0, (size_t)n * n);
    if (m < 1 || n <= m) return;
    std::vector<int32_t> endpoints;  // every edge contributes both endpoints
    endpoints.reserve((size_t)2 * m * n);
    // Seed: star over the first m+1 nodes (connected, every node has degree>=1).
    for (int32_t v = 1; v <= m; ++v) {
        adj[(size_t)0 * n + v] = 1;
        adj[(size_t)v * n + 0] = 1;
        endpoints.push_back(0);
        endpoints.push_back(v);
    }
    std::vector<int32_t> targets(m);
    for (int32_t v = m + 1; v < n; ++v) {
        int32_t picked = 0;
        while (picked < m) {
            std::uniform_int_distribution<size_t> d(0, endpoints.size() - 1);
            int32_t t = endpoints[d(rng)];
            bool dup = (t == v) || adj[(size_t)v * n + t];
            for (int32_t q = 0; q < picked && !dup; ++q)
                if (targets[q] == t) dup = true;
            if (!dup) targets[picked++] = t;
        }
        for (int32_t q = 0; q < m; ++q) {
            int32_t t = targets[q];
            adj[(size_t)v * n + t] = 1;
            adj[(size_t)t * n + v] = 1;
            endpoints.push_back(v);
            endpoints.push_back(t);
        }
    }
}

// Ring lattice: each node linked to its k nearest neighbors per side.
void gen_ring(int32_t n, int32_t k, uint8_t* adj) {
    std::memset(adj, 0, (size_t)n * n);
    for (int32_t i = 0; i < n; ++i) {
        for (int32_t d = 1; d <= k; ++d) {
            int32_t a = (i + d) % n, b = ((i - d) % n + n) % n;
            adj[(size_t)i * n + a] = 1;
            adj[(size_t)i * n + b] = 1;
        }
    }
}

}  // extern "C"
