"""Core protocol primitives: enums, topologies, delay models, mixing matrices.

TPU-native re-design of the reference's ``gossipy/core.py``:

- Enums stay plain Python (they are static, trace-time configuration).
- ``P2PNetwork``'s dict-of-peer-lists (reference core.py:311-389) becomes a
  dense boolean adjacency matrix + degree vector — peer sampling for ALL nodes
  is one vectorized categorical draw.
- ``Delay`` objects (reference core.py:155-307) become pure samplers returning
  integer delay arrays for a whole batch of messages at once.
- ``MixingMatrix`` (reference core.py:392-453) becomes a dense [N, N] weight
  matrix so the all-to-all merge is a single einsum on the MXU.

Known reference quirk intentionally FIXED here: ``P2PNetwork.size(node)`` uses
``if node:`` so node 0 reports the global size instead of its degree
(reference core.py:346-349). Our ``degrees`` vector is correct for all nodes.
"""

from __future__ import annotations

import dataclasses
from enum import IntEnum
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class CreateModelMode(IntEnum):
    """Merge discipline on message receipt (reference core.py:31-44)."""

    UPDATE = 1        # train the received model on local data, adopt it
    MERGE_UPDATE = 2  # average local+received, then train
    UPDATE_MERGE = 3  # train both, then average
    PASS = 4          # adopt the received model as-is


class AntiEntropyProtocol(IntEnum):
    """Gossip exchange protocol (reference core.py:47-58)."""

    PUSH = 1
    PULL = 2
    PUSH_PULL = 3


class MessageType(IntEnum):
    """Wire message type (reference core.py:61-75)."""

    PUSH = 1
    PULL = 2
    REPLY = 3
    PUSH_PULL = 4


# ---------------------------------------------------------------------------
# Topology
# ---------------------------------------------------------------------------

class Topology:
    """A static P2P topology as a dense adjacency matrix.

    Replaces ``StaticP2PNetwork`` (reference core.py:364-389). The adjacency
    is a host-side numpy bool [N, N] (built once) plus device copies used
    inside jitted code. ``sample_peers`` draws one uniform-random neighbor for
    every node simultaneously — the vectorized equivalent of N calls to
    ``GossipNode.get_peer()`` (reference node.py:96-109).
    """

    def __init__(self, adjacency: np.ndarray):
        adjacency = np.asarray(adjacency)
        assert adjacency.ndim == 2 and adjacency.shape[0] == adjacency.shape[1], \
            "adjacency must be a square matrix"
        adj = adjacency.astype(bool)
        np.fill_diagonal(adj, False)
        self.num_nodes: int = adj.shape[0]
        self.adjacency: np.ndarray = adj
        self.degrees: np.ndarray = adj.sum(axis=1).astype(np.int32)
        # Device-side copies (small: N^2 bools).
        self.adjacency_dev = jnp.asarray(adj)
        self.degrees_dev = jnp.asarray(self.degrees)

    # -- constructors -------------------------------------------------------

    @staticmethod
    def clique(n: int) -> "Topology":
        """Fully-connected topology (reference ``topology=None`` case, core.py:342)."""
        a = np.ones((n, n), dtype=bool)
        return Topology(a)

    @staticmethod
    def ring(n: int, k: int = 1) -> "Topology":
        """Ring lattice where each node links to its k nearest neighbors per side."""
        a = np.zeros((n, n), dtype=bool)
        idx = np.arange(n)
        for d in range(1, k + 1):
            a[idx, (idx + d) % n] = True
            a[idx, (idx - d) % n] = True
        return Topology(a)

    # Node count above which the "auto" backend switches from networkx to
    # the native C++ generators (gossipy_tpu/native): networkx's pure-Python
    # generators take minutes at the node counts the TPU engine handles.
    NATIVE_THRESHOLD = 2048

    @staticmethod
    def _use_native(n: int, backend: str) -> bool:
        assert backend in ("auto", "networkx", "native"), \
            f"backend must be 'auto', 'networkx' or 'native', got {backend!r}"
        if backend == "networkx":
            return False
        from . import native
        if backend == "native":
            assert native.available(), "native graphgen unavailable (no g++?)"
            return True
        if n >= Topology.NATIVE_THRESHOLD and native.available():
            # Reproducibility foot-gun: crossing the threshold silently
            # changes the generator's RNG stream, hence the experiment's
            # edge set. Say so loudly; pin backend= to silence.
            from . import LOG
            LOG.warning(
                "Topology backend='auto' selected the native generator for "
                "n=%d (threshold %d): edge sets differ from networkx's RNG "
                "stream. Pin backend='native' or backend='networkx' for "
                "cross-size reproducibility.", n, Topology.NATIVE_THRESHOLD)
            return True
        return False

    @staticmethod
    def random_regular(n: int, degree: int, seed: int = 42,
                       backend: str = "auto") -> "Topology":
        """k-regular random graph (used by reference main_hegedus_2021.py:44).

        ``backend``: "networkx" (reference-matching RNG stream), "native"
        (C++ pairing model, fast at large n), or "auto" (native above
        ``NATIVE_THRESHOLD`` nodes). Edge sets are reproducible per
        (backend, seed) but differ between backends.
        """
        if Topology._use_native(n, backend):
            from . import native
            return Topology(native.random_regular(n, degree, seed))
        import networkx as nx
        g = nx.random_regular_graph(degree, n, seed=seed)
        return Topology(nx.to_numpy_array(g))

    @staticmethod
    def barabasi_albert(n: int, m: int, seed: int = 42,
                        backend: str = "auto") -> "Topology":
        """Preferential-attachment graph (reference main_giaretta_2019.py)."""
        if Topology._use_native(n, backend):
            from . import native
            return Topology(native.barabasi_albert(n, m, seed))
        import networkx as nx
        g = nx.barabasi_albert_graph(n, m, seed=seed)
        return Topology(nx.to_numpy_array(g))

    @staticmethod
    def erdos_renyi(n: int, p: float, seed: int = 42,
                    backend: str = "auto") -> "Topology":
        if Topology._use_native(n, backend):
            from . import native
            return Topology(native.erdos_renyi(n, p, seed))
        import networkx as nx
        g = nx.erdos_renyi_graph(n, p, seed=seed)
        return Topology(nx.to_numpy_array(g))

    # -- queries ------------------------------------------------------------

    def get_peers(self, node_id: int) -> list[int]:
        """Peer id list of one node (API parity with reference core.py:380-389)."""
        return list(np.where(self.adjacency[node_id])[0])

    def size(self, node: Optional[int] = None) -> int:
        """Number of nodes, or the degree of ``node`` if given.

        Unlike the reference (core.py:346-349, the ``if node:`` bug), node 0
        correctly reports its degree.
        """
        if node is None:
            return self.num_nodes
        return int(self.degrees[node])

    def sample_peers(self, key: jax.Array) -> jax.Array:
        """Draw one uniform-random neighbor for every node. Returns int32 [N].

        Nodes with zero degree get peer -1 (callers mask those sends).
        """
        return sample_peers(key, self.adjacency_dev)


def sample_peers(key: jax.Array, adjacency: jax.Array) -> jax.Array:
    """Uniform neighbor draw for all rows of a boolean adjacency [N, N]."""
    logits = jnp.where(adjacency, 0.0, -jnp.inf)
    peers = jax.random.categorical(key, logits, axis=-1)
    has_peer = adjacency.any(axis=-1)
    return jnp.where(has_peer, peers, -1).astype(jnp.int32)


class SparseTopology:
    """CSR neighbor-list topology for node counts where a dense [N, N]
    adjacency no longer fits (~2.5 GB at 50k nodes).

    Same query surface as :class:`Topology` (``num_nodes`` / ``degrees`` /
    ``degrees_dev`` / ``get_peers`` / ``size`` / ``sample_peers``), so the
    gossip engine runs unchanged; device memory is O(E): ``indices`` [2E]
    neighbor ids grouped per node, ``indptr`` [N+1] row offsets.
    ``sample_peers`` is a per-node ``randint(degree)`` into the neighbor
    row — one [N] gather instead of an [N, N] categorical.

    This breaks the scale wall the reference shares (its
    ``StaticP2PNetwork``, core.py:311-361, is dense-only). Mixing weights
    come along for the ride: :func:`uniform_mixing` /
    :func:`metropolis_hastings_mixing` return O(E) :class:`SparseMixing`
    edge weights for a SparseTopology, and the All2All simulator merges
    them without any [N, N] tensor (padded [N, max_deg] gather+einsum on
    TPU / near-regular graphs, edge-list segment-sum otherwise — see
    ``All2AllGossipSimulator``); only the explicit ``ring_mix`` matmul
    schedule still needs a dense :class:`Topology`.
    """

    def __init__(self, num_nodes: int, edges: np.ndarray):
        """``edges``: undirected edge list [E, 2] (each edge once, no
        self-loops/duplicates — the generators guarantee this)."""
        edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
        n = int(num_nodes)
        src = np.concatenate([edges[:, 0], edges[:, 1]])
        dst = np.concatenate([edges[:, 1], edges[:, 0]])
        order = np.lexsort((dst, src))  # rows ascending, sorted within row
        self.num_nodes = n
        self.indices: np.ndarray = dst[order].astype(np.int32)
        counts = np.bincount(src, minlength=n).astype(np.int64)
        self.indptr: np.ndarray = np.concatenate(
            [[0], np.cumsum(counts)]).astype(np.int32)
        self.degrees: np.ndarray = counts.astype(np.int32)
        self.indices_dev = jnp.asarray(self.indices)
        self.indptr_dev = jnp.asarray(self.indptr)
        self.degrees_dev = jnp.asarray(self.degrees)

    # -- constructors (native edge-list generators; O(E) end to end) --------

    @staticmethod
    def random_regular(n: int, degree: int, seed: int = 42) -> "SparseTopology":
        from . import native
        return SparseTopology(n, native.random_regular_edges(n, degree, seed))

    @staticmethod
    def erdos_renyi(n: int, p: float, seed: int = 42) -> "SparseTopology":
        from . import native
        return SparseTopology(n, native.erdos_renyi_edges(n, p, seed))

    @staticmethod
    def barabasi_albert(n: int, m: int, seed: int = 42) -> "SparseTopology":
        from . import native
        return SparseTopology(n, native.barabasi_albert_edges(n, m, seed))

    @staticmethod
    def ring(n: int, k: int = 1) -> "SparseTopology":
        idx = np.arange(n, dtype=np.int64)
        edges = []
        for d in range(1, k + 1):
            if 2 * d < n:
                edges.append(np.stack([idx, (idx + d) % n], axis=1))
            elif 2 * d == n:  # antipodal link: one edge per pair
                half = idx[: n // 2]
                edges.append(np.stack([half, half + n // 2], axis=1))
        return SparseTopology(n, np.concatenate(edges) if edges
                              else np.empty((0, 2), np.int64))

    @staticmethod
    def from_dense(topology: "Topology") -> "SparseTopology":
        i, j = np.nonzero(np.triu(topology.adjacency))
        return SparseTopology(topology.num_nodes, np.stack([i, j], axis=1))

    # -- queries (Topology-compatible) --------------------------------------

    def get_peers(self, node_id: int) -> list[int]:
        lo, hi = int(self.indptr[node_id]), int(self.indptr[node_id + 1])
        return list(self.indices[lo:hi])

    def size(self, node: Optional[int] = None) -> int:
        if node is None:
            return self.num_nodes
        return int(self.degrees[node])

    def sample_peers(self, key: jax.Array) -> jax.Array:
        """One uniform neighbor per node, int32 [N]; -1 for isolated nodes."""
        deg = self.degrees_dev
        r = jax.random.randint(key, (self.num_nodes,), 0,
                               jnp.maximum(deg, 1), dtype=jnp.int32)
        peers = self.indices_dev[self.indptr_dev[:-1] + r]
        return jnp.where(deg > 0, peers, -1).astype(jnp.int32)

    @property
    def adjacency(self):
        raise AttributeError(
            "SparseTopology does not materialize a dense adjacency; use "
            "Topology for features that need one (mixing matrices, "
            "All2AllGossipSimulator) or from_dense/to_dense for small N")

    adjacency_dev = adjacency

    def to_dense(self) -> "Topology":
        """Materialize a dense :class:`Topology` (small N only)."""
        a = np.zeros((self.num_nodes, self.num_nodes), dtype=bool)
        for i in range(self.num_nodes):
            a[i, self.indices[self.indptr[i]:self.indptr[i + 1]]] = True
        return Topology(a)


# ---------------------------------------------------------------------------
# Delay models
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Delay:
    """Base message-latency model (reference core.py:155-177).

    Delays are sampled for whole message batches: ``sample(key, shape, size)``
    returns an int32 array of delays in simulation time units, where ``size``
    is the (static) message size in atomic scalars — the quantity the
    reference computes per message via ``Sizeable.get_size()``
    (reference gossipy/__init__.py:134-156, core.py:109-144).
    """

    def max_delay(self, size: int) -> int:
        raise NotImplementedError

    def sample(self, key: jax.Array, shape: tuple, size: int) -> jax.Array:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class ConstantDelay(Delay):
    """Fixed delay (reference core.py:179-216)."""

    delay: int = 0

    def max_delay(self, size: int) -> int:
        return self.delay

    def sample(self, key, shape, size):
        return jnp.full(shape, self.delay, dtype=jnp.int32)


@dataclasses.dataclass(frozen=True)
class UniformDelay(Delay):
    """Uniform integer delay in [min_delay, max_delay] (reference core.py:219-259)."""

    min_delay: int
    max_delay_: int

    def __post_init__(self):
        assert 0 <= self.min_delay <= self.max_delay_

    def max_delay(self, size: int) -> int:
        return self.max_delay_

    def sample(self, key, shape, size):
        return jax.random.randint(key, shape, self.min_delay, self.max_delay_ + 1,
                                  dtype=jnp.int32)


@dataclasses.dataclass(frozen=True)
class LinearDelay(Delay):
    """Overhead + size-proportional delay (reference core.py:262-307).

    ``delay = floor(timexunit * size) + overhead``; with static model sizes
    this is deterministic per message class.
    """

    timexunit: float
    overhead: int

    def max_delay(self, size: int) -> int:
        return int(self.timexunit * size) + self.overhead

    def sample(self, key, shape, size: int):
        return jnp.full(shape, int(self.timexunit * size) + self.overhead,
                        dtype=jnp.int32)


# ---------------------------------------------------------------------------
# Mixing matrices (all-to-all decentralized SGD, Koloskova et al. 2020)
# ---------------------------------------------------------------------------

class SparseMixing(NamedTuple):
    """Mixing weights in edge-list (CSR-aligned) form, O(E) memory.

    The dense [N, N] mixing matrix is the scale wall of the All-to-All
    simulator (the reference's ``MixingMatrix`` family, core.py:392-453, is
    dense-only); over a :class:`SparseTopology` the same weights live on the
    directed edge list instead: ``edge_w[e]`` is W[rows[e], senders[e]] for
    the 2E directed edges of the CSR structure, ``self_w[i]`` is W[i, i].
    The All2All merge becomes a gather + ``segment_sum`` instead of an
    einsum.
    """

    edge_w: jnp.ndarray    # [2E] float32, W[receiver, sender] per edge
    self_w: jnp.ndarray    # [N]  float32, W[i, i]
    rows: jnp.ndarray      # [2E] int32, receiver (CSR row) per edge
    senders: jnp.ndarray   # [2E] int32, sender (CSR indices) per edge
    num_nodes: int


def _csr_edge_arrays(topo: "SparseTopology"):
    rows = np.repeat(np.arange(topo.num_nodes, dtype=np.int32),
                     np.asarray(topo.degrees))
    return rows, topo.indices


def uniform_mixing(topology) -> "jnp.ndarray | SparseMixing":
    """Uniform mixing weights: row i weights node i and each of its deg(i)
    peers by 1/(deg(i)+1) — the matrix form of ``UniformMixing.get``
    (reference core.py:419-434), which returns the per-node weight vector
    [self] + peers.

    Dense :class:`Topology` -> dense [N, N] matrix; :class:`SparseTopology`
    -> :class:`SparseMixing` edge weights (O(E), no [N, N] anywhere).
    """
    if isinstance(topology, SparseTopology):
        rows, senders = _csr_edge_arrays(topology)
        inv = 1.0 / (np.asarray(topology.degrees, dtype=np.float64) + 1.0)
        return SparseMixing(jnp.asarray(inv[rows], dtype=jnp.float32),
                            jnp.asarray(inv, dtype=jnp.float32),
                            jnp.asarray(rows), jnp.asarray(senders),
                            topology.num_nodes)
    a = topology.adjacency.astype(np.float64)
    deg = a.sum(axis=1)
    w = a / (deg[:, None] + 1.0)
    np.fill_diagonal(w, 1.0 / (deg + 1.0))
    return jnp.asarray(w, dtype=jnp.float32)


def metropolis_hastings_mixing(topology) -> "jnp.ndarray | SparseMixing":
    """Metropolis-Hastings mixing weights (symmetric, doubly stochastic).

    W_ij = 1 / (1 + max(deg_i, deg_j)) for edges, W_ii = 1 - sum_j W_ij.
    The reference's ``MetropolisHastingsMixing`` (core.py:437-453) computes
    ``[1/deg_i] + [1/(min(deg_k, deg_i)+1)]`` whose rows do not sum to 1 and
    which inherits the node-0 degree bug; we implement the standard
    (convergent) MH weights instead — an intentional, documented divergence.

    Dense :class:`Topology` -> dense [N, N] matrix; :class:`SparseTopology`
    -> :class:`SparseMixing` edge weights (O(E)).
    """
    if isinstance(topology, SparseTopology):
        rows, senders = _csr_edge_arrays(topology)
        deg = np.asarray(topology.degrees, dtype=np.float64)
        ew = 1.0 / (1.0 + np.maximum(deg[rows], deg[senders]))
        self_w = 1.0 - np.bincount(rows, weights=ew,
                                   minlength=topology.num_nodes)
        return SparseMixing(jnp.asarray(ew, dtype=jnp.float32),
                            jnp.asarray(self_w, dtype=jnp.float32),
                            jnp.asarray(rows), jnp.asarray(senders),
                            topology.num_nodes)
    a = topology.adjacency.astype(np.float64)
    deg = a.sum(axis=1)
    denom = 1.0 + np.maximum(deg[:, None], deg[None, :])
    w = a / denom
    np.fill_diagonal(w, 0.0)
    np.fill_diagonal(w, 1.0 - w.sum(axis=1))
    return jnp.asarray(w, dtype=jnp.float32)


def mixing_weight_rows(w: jnp.ndarray, topology: Topology) -> jnp.ndarray:
    """Per-node weight vectors in reference layout ([self_weight, peer weights...]).

    Provided for API parity with ``MixingMatrix.__getitem__``
    (reference core.py:412-413); padded with zeros to the max degree.
    """
    n = topology.num_nodes
    max_deg = int(topology.degrees.max()) if n else 0
    out = np.zeros((n, max_deg + 1), dtype=np.float32)
    w_np = np.asarray(w)
    for i in range(n):
        peers = np.where(topology.adjacency[i])[0]
        out[i, 0] = w_np[i, i]
        out[i, 1:1 + len(peers)] = w_np[i, peers]
    return jnp.asarray(out)
