"""Checkpoint / resume for simulation state.

Replaces the reference's whole-world pickling (``GossipSimulator.save`` /
``load`` dill-dump of the simulator object + global CACHE,
reference gossipy/simul.py:460-494). Here simulation state is already one
pytree (:class:`~gossipy_tpu.simulation.engine.SimState`), so a checkpoint is
an orbax snapshot of that pytree plus the run's PRNG key — no object graphs,
no global caches. Because ``SimState.round`` is part of the state, a restored
run continues exactly where it stopped (``GossipSimulator.start`` keys every
round's randomness on the absolute round number).

Usage::

    save_checkpoint(path, state, key=key)
    state, key = restore_checkpoint(path, sim.init_nodes(jax.random.PRNGKey(0)))
    sim.start(state, n_rounds=50, key=key)   # resumes from state.round

Multi-host note: orbax handles sharded arrays natively — a SimState whose
node axis is sharded over a mesh (gossipy_tpu/parallel) checkpoints and
restores with its shardings when ``template`` carries them. Mesh restores
place leaves per the partition-rule registry (``parallel/rules.py``) via
``GossipSimulator.load(mesh=)`` — placement is derived, never
hand-assembled here.

Cohort-mode note: with ``cohort=`` the checkpoint unit is the resident
:class:`~gossipy_tpu.simulation.cohort.CohortPool` (host numpy leaves,
nominal-N sized) instead of a SimState — the same ``save_checkpoint`` /
``restore_checkpoint`` pair round-trips it, and
``GossipSimulator.load`` uses the cheap zero-filled
``cohort.pool_template`` as the restore template so restores stay
O(pool bytes) with no O(N) init compute. A restored pool continues
bit-for-bit: cohort draws key on ``(key, absolute round)`` and the
round counter is part of the pool.

Compatibility note: a restore target must be built with the SAME simulator
configuration, including ``mailbox_slots`` — the mailbox is a [D, N, K]
state array and a template with a different K cannot receive the snapshot.
``history_dtype`` is part of that contract too: the params-history ring is
checkpointed in its wire format (bf16/int8 rings round-trip at their
reduced size, the int8 scale sidecar rides along as ``history_scale``),
and a template built with a different format has mismatching ring dtypes/
tree structure. Quantize-on-snapshot means converting a checkpoint between
formats is a state transform, not a restore-time cast.
Since round 4 the default ``mailbox_slots=None`` DERIVES K from the
topology (Poisson fan-in bound; engine.py), so on hub-heavy topologies the
derived K can differ from the old fixed default: pin ``mailbox_slots=6``
when restoring checkpoints saved before that change (and expect
failed-message counts to differ from pre-round-4 runs there — the bigger
derived mailbox drops fewer overflow messages).
"""

from __future__ import annotations

import inspect
import os
from typing import Any, Optional

import jax
import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Any, key: Optional[jax.Array] = None,
                    force: bool = True,
                    meta: Optional[dict] = None) -> str:
    """Save a SimState (or any pytree) + optional PRNG key to ``path``.

    ``meta`` (JSON-able dict) is written as a ``<path>.meta.json``
    SIDECAR next to the checkpoint directory — host-readable context
    (round index, why the snapshot was taken) that a post-mortem can
    read without paying an orbax restore; the flight recorder
    (:mod:`gossipy_tpu.telemetry.health`) stamps its bundles through
    this. The sidecar lives outside the orbax directory so the restore
    path never sees an unexpected file.

    Returns the absolute checkpoint path.
    """
    import json

    from .telemetry.tracing import span
    path = os.path.abspath(path)
    # Process-default tracer resolved at enter time: checkpoint writes
    # appear on the run's timeline whenever tracing is on, and cost one
    # no-op handle when it is off.
    with span("checkpoint.save", cat="checkpoint", path=path):
        payload = {"state": state}
        if key is not None:
            payload["key"] = key
        _checkpointer().save(path, payload, force=force)
        if meta is not None:
            with open(path + ".meta.json", "w") as fh:
                json.dump(meta, fh, indent=2)
                fh.write("\n")
    return path


def load_checkpoint_meta(path: str) -> Optional[dict]:
    """Read the ``meta`` sidecar written by :func:`save_checkpoint`, or
    None when the checkpoint has no sidecar."""
    import json
    sidecar = os.path.abspath(path) + ".meta.json"
    if not os.path.exists(sidecar):
        return None
    with open(sidecar) as fh:
        return json.load(fh)


def slice_lane(tree: Any, i: int) -> Any:
    """Extract lane ``i`` of a batched pytree (leading batch axis on every
    array leaf) as HOST numpy arrays — the bridge from a [T, ...]-stacked
    seed/tenant-vmapped :class:`SimState` (``run_repetitions`` outputs,
    the service scheduler's megabatch states) to the solo-shaped state a
    checkpoint, flight-recorder bundle, or replay template expects.

    Materializing on the host is deliberate: the copy survives a later
    donation of the batched source (the scheduler donates its state batch
    to the next chunk while keeping per-tenant last-healthy copies), and
    :func:`save_checkpoint` accepts numpy leaves directly. Scalar
    (0-dim) leaves pass through unsliced.
    """
    def take(l):
        a = np.asarray(l)
        return a[i] if a.ndim else a
    return jax.tree.map(take, tree)


def restore_checkpoint(path: str, template_state: Any,
                       template_key: Optional[jax.Array] = None):
    """Restore ``(state, key)`` from ``path``.

    ``template_state`` (e.g. a fresh ``sim.init_nodes(...)`` result) supplies
    the pytree structure, dtypes, and shardings for the restore —
    the orbax equivalent of the reference rebuilding its object graph from
    dill. Returns ``(state, key)``; ``key`` is None when none was saved.
    """
    import orbax.checkpoint as ocp

    from .telemetry.tracing import span

    def attempt(template):
        # Restore INTO the template's shardings/dtypes (not the
        # file-recorded ones) so a checkpoint written on one mesh topology
        # restores correctly onto another.
        restore_args = ocp.checkpoint_utils.construct_restore_args(template)
        return _checkpointer().restore(os.path.abspath(path), item=template,
                                       restore_args=restore_args)

    # The on-disk payload may or may not contain a "key" entry; orbax
    # requires the template tree to match it exactly, so try with a key
    # template first (defaulting one when the caller didn't pass it), then
    # without.
    key_tmpl = template_key if template_key is not None else jax.random.PRNGKey(0)
    with span("checkpoint.restore", cat="checkpoint",
              path=os.path.abspath(path)):
        try:
            restored = attempt({"state": template_state, "key": key_tmpl})
            return restored["state"], restored["key"]
        except ValueError:
            restored = attempt({"state": template_state})
            return restored["state"], None


class CheckpointManager:
    """Periodic checkpointing over a chunked simulation run.

    The reference has no periodic checkpointing (only the one-shot
    ``save``, simul.py:460-478); this adds an every-``interval``-rounds
    checkpoint cycle with retention, driven from the host between scan
    chunks::

        mgr = CheckpointManager(dir, interval=100, max_to_keep=3)
        state = mgr.run(sim, state, until_round=1000, key=key)
    """

    def __init__(self, directory: str, interval: int = 100,
                 max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.interval = int(interval)
        self.max_to_keep = int(max_to_keep)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, rnd: int) -> str:
        return os.path.join(self.directory, f"round_{rnd:08d}")

    def checkpoints(self) -> list[int]:
        """Sorted round numbers with an on-disk checkpoint."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("round_"):
                try:
                    out.append(int(name.split("_", 1)[1]))
                except ValueError:
                    pass
        return sorted(out)

    def latest(self) -> Optional[int]:
        cps = self.checkpoints()
        return cps[-1] if cps else None

    def _retain(self):
        import shutil
        cps = self.checkpoints()
        for rnd in cps[: max(0, len(cps) - self.max_to_keep)]:
            shutil.rmtree(self._path(rnd), ignore_errors=True)

    def run(self, sim, state, until_round: int, key: jax.Array,
            reports: Optional[list] = None):
        """Advance the simulation to ABSOLUTE round ``until_round``,
        checkpointing every ``interval`` rounds.

        Unlike ``sim.start(n_rounds=...)`` (which is incremental),
        ``until_round`` is an absolute target: if the directory already holds
        checkpoints, the newest one is restored (into the passed ``state`` as
        template) and only the missing rounds run. A state already at or past
        ``until_round`` is returned unchanged. Per-chunk reports are appended
        to ``reports`` when given.

        Note: ``sim.start`` compiles one program per distinct chunk length,
        so a tail chunk (``until_round`` not a multiple of ``interval``)
        costs one extra compilation — prefer targets that are multiples of
        the interval for big models.
        """
        newest = self.latest()
        # Buffer-donation bookkeeping: the chunk loop donates its input
        # state to each jitted run (the ring is not double-buffered), but
        # NEVER the caller's own pytree — when no checkpoint was restored,
        # the first chunk's input is caller-owned and must stay alive.
        caller_owned = newest is None
        if newest is not None:
            state, saved_key = restore_checkpoint(self._path(newest), state, key)
            if saved_key is not None:
                key = saved_key
        start_round = int(np.asarray(state.round))
        done = 0
        target = until_round - start_round
        # The sequential (eager) engine's start() has no donation knob.
        donatable = "donate_state" in inspect.signature(sim.start).parameters
        while done < target:
            chunk = min(self.interval, target - done)
            kw = ({"donate_state": not caller_owned} if donatable else {})
            state, report = sim.start(state, n_rounds=chunk, key=key, **kw)
            caller_owned = False
            if reports is not None:
                reports.append(report)
            done += chunk
            save_checkpoint(self._path(start_round + done), state, key=key)
            self._retain()
        return state
