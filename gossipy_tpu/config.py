"""Experiment configuration: dataclass + JSON, one file per experiment.

The reference configures experiments ad hoc inside each ``main_*`` script
(argparse flags + hard-coded constructors; SURVEY §5 flags this as the
missing config system). Here an experiment is ONE declarative
:class:`ExperimentConfig` — serializable to JSON, buildable into a live
simulator, runnable in one call — so a run is reproducible from a file:

    cfg = ExperimentConfig.from_json("exp.json")
    report = run_experiment(cfg)

Registries cover the shipped model families, topologies, delays, handlers
and simulator variants; unknown names raise with the valid options listed.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional

import numpy as np

from .core import (
    AntiEntropyProtocol,
    ConstantDelay,
    CreateModelMode,
    LinearDelay,
    SparseTopology,
    Topology,
    UniformDelay,
    uniform_mixing,
)

# --------------------------------------------------------------------------
# Registries
# --------------------------------------------------------------------------

def _topology(kind: str, n: int, params: dict, backend: str, sparse: bool):
    if sparse:
        builders = {
            "ring": SparseTopology.ring,
            "random_regular": SparseTopology.random_regular,
            "barabasi_albert": SparseTopology.barabasi_albert,
            "erdos_renyi": SparseTopology.erdos_renyi,
        }
        if kind not in builders:
            raise ValueError(f"no sparse builder for topology {kind!r}; "
                             f"options: {sorted(builders)}")
        return builders[kind](n, **params)
    def clique(n, **kw):
        if kw:
            # Strict like from_dict's unknown-field check: a clique takes no
            # parameters, so silently swallowing them would hide typos.
            raise ValueError("topology 'clique' accepts no params, got "
                             f"{sorted(kw)}")
        return Topology.clique(n)

    builders = {
        "clique": clique,
        "ring": Topology.ring,
        "random_regular": lambda n, **kw: Topology.random_regular(
            n, backend=backend, **kw),
        "barabasi_albert": lambda n, **kw: Topology.barabasi_albert(
            n, backend=backend, **kw),
        "erdos_renyi": lambda n, **kw: Topology.erdos_renyi(
            n, backend=backend, **kw),
    }
    if kind not in builders:
        raise ValueError(f"unknown topology {kind!r}; "
                         f"options: {sorted(builders)}")
    return builders[kind](n, **params)


def _model(name: str, params: dict, input_dim: int, n_classes: int):
    from . import models

    name = name.lower()

    def no_params():
        if params:
            # Strict like from_dict's unknown-field check: these models take
            # no parameters, so silently swallowing them would hide typos.
            raise ValueError(f"model {name!r} accepts no model_params, got "
                             f"{sorted(params)}")

    if name in ("logreg", "logistic_regression"):
        no_params()
        return models.LogisticRegression(input_dim, n_classes)
    def only(*keys):
        unknown = set(params) - set(keys)
        if unknown:
            raise ValueError(f"unknown model_params for {name!r}: "
                             f"{sorted(unknown)}; valid: {sorted(keys)}")

    if name == "mlp":
        only("hidden_dims")
        return models.MLP(input_dim, n_classes,
                          hidden_dims=tuple(params.get("hidden_dims", (64,))))
    if name == "perceptron":
        no_params()
        return models.Perceptron(input_dim)
    if name in ("linreg", "linear_regression"):
        only("out_dim")
        return models.LinearRegression(input_dim, params.get("out_dim", 1))
    if name == "cifar10net":
        only("conv_impl")
        return models.CIFAR10Net(
            conv_impl=params.get("conv_impl", "auto"))
    raise ValueError(f"unknown model {name!r}; options: logreg, mlp, "
                     "perceptron, linreg, cifar10net")


def _delay(kind: str, params: dict):
    builders = {"constant": ConstantDelay, "uniform": UniformDelay,
                "linear": LinearDelay}
    if kind not in builders:
        raise ValueError(f"unknown delay {kind!r}; options: {sorted(builders)}")
    return builders[kind](**params)


def _handler(cfg: "ExperimentConfig", model, input_shape, n_classes,
             n_items: int = 0):
    import jax.numpy as jnp
    import optax

    from . import handlers

    kinds = {
        "sgd": handlers.SGDHandler,
        "weighted": handlers.WeightedSGDHandler,
        "limited_merge": handlers.LimitedMergeSGDHandler,
        "sampling": handlers.SamplingSGDHandler,
        "partitioned": handlers.PartitionedSGDHandler,
        "adaline": handlers.AdaLineHandler,
        "pegasos": handlers.PegasosHandler,
        "kmeans": handlers.KMeansHandler,
        "mf": handlers.MFHandler,
    }
    if cfg.handler not in kinds:
        raise ValueError(f"unknown handler {cfg.handler!r}; "
                         f"options: {sorted(kinds)}")
    cls = kinds[cfg.handler]
    mode = CreateModelMode[cfg.create_model_mode]
    params = dict(cfg.handler_params)
    if cfg.handler in ("adaline", "pegasos"):
        from .models import AdaLine
        return cls(net=AdaLine(input_shape[0]),
                   learning_rate=cfg.learning_rate, **params)
    if cfg.handler == "kmeans":
        # main_berta_2014 family: k defaults to the label count (spambase
        # clustering uses k=2 on binary labels).
        return cls(k=params.pop("k", n_classes), dim=input_shape[0],
                   create_model_mode=mode, **params)
    if cfg.handler == "mf":
        # main_hegedus_2020 family: one user per node, item factors travel.
        return cls(dim=params.pop("dim", 5), n_items=n_items,
                   learning_rate=cfg.learning_rate, create_model_mode=mode,
                   **params)
    losses = {"cross_entropy": handlers.losses.cross_entropy,
              "mse": handlers.losses.mse}
    if cfg.loss not in losses:
        raise ValueError(f"unknown loss {cfg.loss!r}; "
                         f"options: {sorted(losses)}")
    opt = optax.sgd(cfg.learning_rate)
    if cfg.weight_decay:
        opt = optax.chain(optax.add_decayed_weights(cfg.weight_decay), opt)
    common = dict(model=model, loss=losses[cfg.loss], optimizer=opt,
                  local_epochs=cfg.local_epochs, batch_size=cfg.batch_size,
                  n_classes=n_classes, input_shape=input_shape,
                  create_model_mode=mode,
                  compute_dtype=jnp.bfloat16 if cfg.bf16 else None)
    if cfg.handler == "partitioned":
        # The partition index sets derive from the model template
        # (main_hegedus_2021); only n_parts is a config knob.
        import jax

        from .compression import ModelPartition
        template = model.init(jax.random.PRNGKey(0),
                              jnp.zeros((1,) + tuple(input_shape)))["params"]
        partition = ModelPartition(template, params.pop("n_parts", 4))
        return cls(partition, **common, **params)
    return cls(**common, **params)


def _token_account(cfg: "ExperimentConfig"):
    """The configured token-account instance (default kind: simple)."""
    from . import flow_control
    accounts = {
        "purely_proactive": flow_control.PurelyProactiveTokenAccount,
        "purely_reactive": flow_control.PurelyReactiveTokenAccount,
        "simple": flow_control.SimpleTokenAccount,
        "generalized": flow_control.GeneralizedTokenAccount,
        "randomized": flow_control.RandomizedTokenAccount,
    }
    acc_kind = cfg.token_account or "simple"
    if acc_kind not in accounts:
        raise ValueError(f"unknown token account {acc_kind!r}; "
                         f"options: {sorted(accounts)}")
    return accounts[acc_kind](**cfg.token_account_params)


def _simulator(cfg: "ExperimentConfig", handler, topology, data):
    from .simulation import (
        All2AllGossipSimulator,
        CacheNeighGossipSimulator,
        GossipSimulator,
        PartitioningGossipSimulator,
        PassThroughGossipSimulator,
        PENSGossipSimulator,
        SamplingGossipSimulator,
        SequentialGossipSimulator,
        TokenizedGossipSimulator,
        TokenizedPartitioningGossipSimulator,
    )

    common = dict(
        delta=cfg.delta,
        protocol=AntiEntropyProtocol[cfg.protocol],
        delay=_delay(cfg.delay, dict(cfg.delay_params)),
        drop_prob=cfg.drop_prob, online_prob=cfg.online_prob,
        sampling_eval=cfg.sampling_eval, sync=cfg.sync,
        eval_every=cfg.eval_every,
    )
    if cfg.chaos is not None:
        # Validated here (ChaosConfig.from_dict raises on unknown fields)
        # so a typo'd chaos spec fails at build, not deep in a trace.
        from .simulation.faults import ChaosConfig
        common["chaos"] = ChaosConfig.from_dict(cfg.chaos)
    if cfg.cohort is not None:
        # Same early-validation discipline; only the base engine drives
        # the resident-pool segment loop.
        if cfg.simulator != "gossip":
            raise ValueError("cohort mode requires simulator 'gossip' "
                             f"(got {cfg.simulator!r})")
        from .simulation.cohort import CohortConfig
        common["cohort"] = CohortConfig.from_dict(cfg.cohort)
    common.update(cfg.simulator_params)
    kind = cfg.simulator
    if kind == "gossip":
        return GossipSimulator(handler, topology, data, **common)
    if kind == "sequential":
        # The opt-in high-fidelity mode (simulation/sequential.py):
        # reference per-tick semantics, per-round evaluation only.
        ev = common.pop("eval_every", 1)
        if ev != 1:
            raise ValueError(
                "the sequential simulator evaluates every round "
                "(reference tick-loop semantics); eval_every must be 1")
        account = _token_account(cfg) if cfg.token_account else None
        return SequentialGossipSimulator(handler, topology, data,
                                         token_account=account, **common)
    if kind in ("tokenized", "tokenized_partitioning"):
        account = _token_account(cfg)
        sim_cls = (TokenizedPartitioningGossipSimulator
                   if kind == "tokenized_partitioning"
                   else TokenizedGossipSimulator)
        return sim_cls(handler, topology, data, token_account=account,
                       **common)
    if kind == "all2all":
        from .core import metropolis_hastings_mixing
        mixers = {"uniform": uniform_mixing,
                  "metropolis": metropolis_hastings_mixing}
        mix_name = common.pop("mixing", "uniform")
        if mix_name not in mixers:
            raise ValueError(f"unknown mixing {mix_name!r}; "
                             f"options: {sorted(mixers)}")
        return All2AllGossipSimulator(handler, topology, data,
                                      mixing=mixers[mix_name](topology),
                                      **common)
    simple = {"passthrough": PassThroughGossipSimulator,
              "cache_neigh": CacheNeighGossipSimulator,
              "sampling": SamplingGossipSimulator,
              "partitioning": PartitioningGossipSimulator,
              "pens": PENSGossipSimulator}
    if kind not in simple:
        raise ValueError(
            f"unknown simulator {kind!r}; options: "
            f"{sorted(simple) + ['gossip', 'sequential', 'tokenized', 'all2all', 'tokenized_partitioning']}")
    return simple[kind](handler, topology, data, **common)


# --------------------------------------------------------------------------
# The config dataclass
# --------------------------------------------------------------------------

# Config fields a service tenant may vary WITHOUT changing the compiled
# round program (gossipy_tpu/service/packer.py buckets runs by the rest):
# ``seed`` only changes data values / init draws (array shapes are hashed
# separately by the packer, so a seed that DID change a shape still splits
# the bucket); ``drop_prob``/``online_prob`` are traced per-tenant scalars
# in the megabatch program; ``n_rounds``/``repetitions`` are host-side
# run-length knobs outside the per-round trace. ``chaos`` is
# tenant-variable in its schedule VALUES only — the compiled
# FaultSchedule rides the tenant axis as data, while its array SHAPES
# (and the static facts derived from the config: component count,
# edge-mask form) are hashed separately by the packer and split buckets.
TENANT_VARIABLE_FIELDS = ("seed", "drop_prob", "online_prob", "n_rounds",
                          "repetitions", "chaos")


@dataclasses.dataclass
class ExperimentConfig:
    """One gossip-learning experiment, declaratively.

    Field groups mirror the knobs the reference spreads across its
    ``main_*`` scripts: data (dataset/assignment), model+handler, topology,
    protocol timing, faults, and run length.
    """

    # data
    task: str = "classification"         # "classification" | "clustering" | "recsys"
    dataset: str = "spambase"            # classification names, the image sets
    n_nodes: int = 100                   # "cifar10"/"fashion_mnist", "femnist",
    assignment: str = "uniform"          # or (task="recsys") "ml-100k"/"ml-1m".
                                         # n_nodes=0 = one node per sample
                                         # (main_ormandi/berta); recsys derives
                                         # it from the user count.
    assignment_params: dict = dataclasses.field(default_factory=dict)
    eval_on_user: bool = False
    test_size: float = 0.2               # tabular split (images ship a test set)
    subsample: int = 0                   # cap train samples (0 = all)
    flip_half: bool = False              # vertically flip the 2nd half of an
                                         # image set (main_onoszko_2021's
                                         # cluster non-IID construction)
    # model + handler
    model: str = "logreg"
    model_params: dict = dataclasses.field(default_factory=dict)
    handler: str = "sgd"
    handler_params: dict = dataclasses.field(default_factory=dict)
    loss: str = "cross_entropy"
    learning_rate: float = 0.1
    weight_decay: float = 0.0
    local_epochs: int = 1
    batch_size: int = 32
    create_model_mode: str = "MERGE_UPDATE"
    bf16: bool = False
    # topology
    topology: str = "random_regular"
    topology_params: dict = dataclasses.field(default_factory=lambda: {"degree": 20})
    topology_backend: str = "networkx"
    sparse_topology: bool = False
    # protocol / timing / faults
    simulator: str = "gossip"            # gossip | sequential (high-fidelity
                                         # eager mode) | tokenized |
                                         # tokenized_partitioning | all2all |
                                         # passthrough | cache_neigh |
                                         # sampling | partitioning | pens
    simulator_params: dict = dataclasses.field(default_factory=dict)
                                         # extra constructor kwargs (e.g.
                                         # compact_deliver, mailbox_slots,
                                         # fused_merge, mixing)
    protocol: str = "PUSH"
    delta: int = 100
    delay: str = "constant"
    delay_params: dict = dataclasses.field(default_factory=dict)
    drop_prob: float = 0.0
    online_prob: float = 1.0
    chaos: Optional[dict] = None         # ChaosConfig.to_dict() form:
                                         # scheduled outages/partitions/
                                         # churn/spikes (simulation.faults)
    cohort: Optional[dict] = None        # CohortConfig.to_dict() form:
                                         # sampled active-cohort mode
                                         # (simulation.cohort) — n_nodes
                                         # becomes the NOMINAL population,
                                         # each round materializes only
                                         # cohort["size"] nodes
    sampling_eval: float = 0.0
    sync: bool = True
    eval_every: int = 1
    token_account: Optional[str] = None
    token_account_params: dict = dataclasses.field(default_factory=dict)
    # run
    n_rounds: int = 100
    seed: int = 42
    repetitions: int = 1  # >1 = vmapped seed batch via run_repetitions
    common_init: bool = False  # same initial weights on every node (CIFAR CNN)

    def __post_init__(self):
        if self.repetitions < 1:
            raise ValueError(
                f"repetitions must be >= 1, got {self.repetitions}")
        if self.task not in ("classification", "clustering", "recsys"):
            raise ValueError(f"unknown task {self.task!r}; options: "
                             "classification, clustering, recsys")
        if self.task == "recsys" and self.handler != "mf":
            raise ValueError("task 'recsys' requires handler 'mf' "
                             "(one user-row per node, MF factors travel)")
        if self.task != "recsys" and self.handler == "mf":
            raise ValueError("handler 'mf' requires task 'recsys'")
        if self.cohort is not None and self.repetitions > 1:
            raise ValueError("cohort mode is host-driven per segment and "
                             "cannot ride the repetition vmap; run seeds "
                             "as separate experiments")

    # -- serialization ------------------------------------------------------

    def to_json(self, path: Optional[str] = None) -> str:
        s = json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)
        if path is not None:
            with open(path, "w") as f:
                f.write(s + "\n")
        return s

    @staticmethod
    def from_json(path_or_str: str) -> "ExperimentConfig":
        if path_or_str.lstrip().startswith("{"):
            d = json.loads(path_or_str)
        else:
            with open(path_or_str) as f:
                d = json.load(f)
        return ExperimentConfig.from_dict(d)

    @staticmethod
    def from_dict(d: dict) -> "ExperimentConfig":
        fields = {f.name for f in dataclasses.fields(ExperimentConfig)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(f"unknown config fields: {sorted(unknown)}; "
                             f"valid fields: {sorted(fields)}")
        return ExperimentConfig(**d)

    def shape_fields(self) -> dict:
        """The config fields that pin the compiled round program — every
        field except :data:`TENANT_VARIABLE_FIELDS`. Two configs with
        equal ``shape_fields()`` build simulators whose round programs
        trace identically (same model/handler constants, topology,
        mailbox geometry, probes/sentinels), so the service packer can
        fuse them into one seed/config-vmapped megabatch; the variable
        fields ride the batch as data."""
        d = dataclasses.asdict(self)
        for f in TENANT_VARIABLE_FIELDS:
            d.pop(f, None)
        return d


# --------------------------------------------------------------------------
# Build + run
# --------------------------------------------------------------------------

def build_experiment(cfg: ExperimentConfig,
                     data: Optional[tuple] = None) -> tuple[Any, Any]:
    """Instantiate ``(simulator, dispatcher)`` from a config.

    ``data``: optional pre-loaded ``(X, y)`` overriding ``cfg.dataset``
    (e.g. synthetic data in tests, or a custom matrix).
    """
    from .data import (
        AssignmentHandler,
        ClassificationDataHandler,
        ClusteringDataHandler,
        DataDispatcher,
        RecSysDataDispatcher,
        RecSysDataHandler,
        load_classification_dataset,
        load_recsys_dataset,
    )

    known = {"gossip", "sequential", "tokenized", "tokenized_partitioning",
             "all2all", "passthrough", "cache_neigh", "sampling",
             "partitioning", "pens"}
    if cfg.simulator not in known:
        # Cheap name check up front: a typo should not first surface as a
        # topology/model construction error.
        raise ValueError(f"unknown simulator {cfg.simulator!r}; "
                         f"options: {sorted(known)}")

    if cfg.task == "recsys":
        # main_hegedus_2020 shape: one user-row per node; n_nodes and the
        # item count come from the ratings matrix, not the config.
        ratings, n_users, n_items = (data if data is not None
                                     else load_recsys_dataset(cfg.dataset))
        dh = RecSysDataHandler(ratings, n_users, n_items,
                               test_size=cfg.test_size, seed=cfg.seed)
        disp = RecSysDataDispatcher(dh)
        disp.assign(cfg.seed)
        handler = _handler(cfg, None, (n_items,), 0, n_items=n_items)
        topology = _topology(cfg.topology, n_users,
                             dict(cfg.topology_params), cfg.topology_backend,
                             cfg.sparse_topology)
        return _simulator(cfg, handler, topology, disp.stacked()), disp

    def subsample(X, y, n):
        # Seeded shuffle BEFORE slicing: several loaders return rows sorted
        # by class (sklearn iris/wine), where a prefix slice would silently
        # produce single-class data.
        order = np.random.default_rng(cfg.seed).permutation(len(X))[:n]
        return X[order], y[order]

    writer_assignment = None  # femnist: natural per-writer shards
    image_sets = {"cifar10": "get_CIFAR10", "fashion_mnist": "get_FashionMNIST"}
    if cfg.task == "clustering" and (cfg.dataset in image_sets
                                     or cfg.dataset == "femnist"):
        # The clustering path (eval set == train set, kmeans over flat
        # feature vectors) is tabular-only; catching it here beats an opaque
        # shape error from the kmeans handler later.
        raise ValueError("task 'clustering' supports tabular datasets only "
                         f"(got {cfg.dataset!r})")
    if data is None and cfg.dataset == "femnist":
        from . import data as data_mod
        (Xtr, ytr, tr_a), (Xte, yte, te_a) = data_mod.get_FEMNIST(
            n_writers=cfg.n_nodes or 100)
        mu, sd = Xtr.mean(), Xtr.std() + 1e-8
        X = (Xtr - mu) / sd
        dh = ClassificationDataHandler(X, ytr, (Xte - mu) / sd, yte)
        y = np.concatenate([ytr, yte])
        writer_assignment = (tr_a, te_a)
    elif data is None and cfg.dataset in image_sets:
        from . import data as data_mod
        (Xtr, ytr), (Xte, yte) = getattr(data_mod, image_sets[cfg.dataset])()
        if cfg.subsample:
            Xtr, ytr = subsample(Xtr, ytr, cfg.subsample)
            Xte, yte = subsample(Xte, yte, cfg.subsample // 5 or 1)
        # Normalize both splits with TRAIN statistics (the flagship
        # examples/main_cifar10_100nodes.py recipe).
        mu, sd = Xtr.mean(), Xtr.std() + 1e-8
        X = (Xtr - mu) / sd
        Xte = (Xte - mu) / sd
        if cfg.flip_half:
            # main_onoszko_2021's cluster non-IID: the 2nd half of each
            # split sees vertically-flipped images.
            X = X.copy(); Xte = Xte.copy()
            X[len(X) // 2:] = X[len(X) // 2:, ::-1, :, :]
            Xte[len(Xte) // 2:] = Xte[len(Xte) // 2:, ::-1, :, :]
        dh = ClassificationDataHandler(X, ytr, Xte, yte)
        # A small subsample may miss classes; count over both splits.
        y = np.concatenate([ytr, yte])
    else:
        X, y = data if data is not None \
            else load_classification_dataset(cfg.dataset)
        if cfg.subsample:
            X, y = subsample(X, y, cfg.subsample)
        if cfg.handler in ("adaline", "pegasos"):
            # The linear-threshold handlers train on ±1 labels (the
            # reference's main_ormandi/main_giaretta convert the same way).
            y = (2 * y - 1).astype(np.float32)
        if cfg.task == "clustering":
            # Eval set == train set (main_berta_2014; reference
            # data/handler.py:138-164).
            dh = ClusteringDataHandler(X, y)
        else:
            dh = ClassificationDataHandler(X, y, test_size=cfg.test_size,
                                           seed=cfg.seed)
    n_classes = int(np.max(y)) + 1
    assignment = None
    if cfg.assignment == "contiguous":
        # main_onoszko_2021's CustomDataDispatcher: contiguous equal blocks
        # (with flip_half this puts flipped/unflipped images on disjoint
        # nodes — the cluster non-IID setup).
        n_tr = len(dh.get_train_set()[0])
        n_for_blocks = cfg.n_nodes or n_tr
        per = -(-n_tr // n_for_blocks)
        writer_assignment = ([np.arange(i * per, min((i + 1) * per, n_tr))
                              for i in range(n_for_blocks)], None)
    elif cfg.assignment != "uniform":
        if not hasattr(AssignmentHandler, cfg.assignment):
            raise ValueError(f"unknown assignment {cfg.assignment!r}")
        assignment = getattr(AssignmentHandler, cfg.assignment)
    # auto_assign=False + explicit assign(cfg.seed): the config's seed must
    # control the partition (the constructor's auto-assign would draw it
    # with its own default seed), and the partition must be drawn once.
    # n_nodes=0 = one node per (train) sample, like main_ormandi/main_berta.
    n_nodes = len(writer_assignment[0]) if writer_assignment is not None \
        else cfg.n_nodes
    disp = DataDispatcher(dh, n=n_nodes, eval_on_user=cfg.eval_on_user,
                          auto_assign=False,
                          **({} if assignment is None
                             else {"assignment": assignment}),
                          **cfg.assignment_params)
    if writer_assignment is not None:
        disp.set_assignments(*writer_assignment)
    else:
        disp.assign(cfg.seed)
    n_nodes = disp.size()

    input_shape = X.shape[1:]
    # kmeans/adaline/pegasos carry their own parameterization; building an
    # (unused) flax model for them would just burn an init.
    model = None if cfg.handler in ("kmeans", "adaline", "pegasos") else \
        _model(cfg.model, dict(cfg.model_params), input_shape[0]
               if len(input_shape) == 1 else input_shape, n_classes)
    handler = _handler(cfg, model, input_shape, n_classes)
    topology = _topology(cfg.topology, n_nodes,
                         dict(cfg.topology_params), cfg.topology_backend,
                         cfg.sparse_topology)
    sim = _simulator(cfg, handler, topology, disp.stacked())
    return sim, disp


def run_experiment(cfg: ExperimentConfig, data: Optional[tuple] = None):
    """Build and run the experiment.

    Returns ``(state, SimulationReport)``; with ``cfg.repetitions > 1``
    returns ``(states, [SimulationReport])`` — on the bulk engines the
    whole seed batch executes as one vmapped program
    (:meth:`GossipSimulator.run_repetitions`; ``states`` is a stacked
    pytree with a leading seed axis), while ``simulator="sequential"``
    loops seeds eagerly and returns a plain list of
    :class:`~gossipy_tpu.simulation.SeqState`. The report lists feed
    :func:`gossipy_tpu.utils.plot_evaluation`'s mean±std curves either
    way.
    """
    import jax

    from . import set_seed

    key = set_seed(cfg.seed)
    sim, _ = build_experiment(cfg, data)
    if cfg.repetitions > 1:
        keys = jax.random.split(key, cfg.repetitions)
        return sim.run_repetitions(cfg.n_rounds, keys,
                                   common_init=cfg.common_init)
    if getattr(sim, "cohort", None) is not None:
        pool = sim.init_cohort_pool(key, common_init=cfg.common_init)
        return sim.start(pool, n_rounds=cfg.n_rounds, key=key)
    state = sim.init_nodes(key, common_init=cfg.common_init)
    return sim.start(state, n_rounds=cfg.n_rounds, key=key)
