"""Simulation runtime (reference gossipy/simul.py re-designed for TPU)."""

from .engine import GossipSimulator, Mailbox, SimState
from .report import SimulationReport

__all__ = ["GossipSimulator", "SimulationReport", "SimState", "Mailbox"]
