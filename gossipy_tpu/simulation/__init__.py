"""Simulation runtime (reference gossipy/simul.py re-designed for TPU)."""

from .engine import GossipSimulator, Mailbox, SimState
from .nodes import (
    CacheNeighGossipSimulator,
    PartitioningGossipSimulator,
    PassThroughGossipSimulator,
    PENSGossipSimulator,
    SamplingGossipSimulator,
)
from .report import SimulationReport
from .variants import All2AllGossipSimulator, TokenizedGossipSimulator

__all__ = [
    "GossipSimulator", "SimulationReport", "SimState", "Mailbox",
    "TokenizedGossipSimulator", "All2AllGossipSimulator",
    "PassThroughGossipSimulator", "CacheNeighGossipSimulator",
    "SamplingGossipSimulator", "PartitioningGossipSimulator",
    "PENSGossipSimulator",
]
