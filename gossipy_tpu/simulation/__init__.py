"""Simulation runtime (reference gossipy/simul.py re-designed for TPU)."""

from .cohort import CohortConfig, CohortPool, NominalTopology, PoolStore
from .engine import (GossipSimulator, Mailbox, MemoryBudgetExceeded,
                     SimState)
from .faults import (
    ChaosConfig,
    ChurnProcess,
    FaultSchedule,
    FaultSpike,
    OutageEpisode,
    PartitionEpisode,
    build_fault_schedule,
    rounds_to_reconverge,
)
from .events import (
    CallbackReceiver,
    JSONLinesReceiver,
    ProgressReceiver,
    SimulationEventReceiver,
    SimulationEventSender,
)
from .nodes import (
    CacheNeighGossipSimulator,
    PartitioningGossipSimulator,
    PassThroughGossipSimulator,
    PENSGossipSimulator,
    SamplingGossipSimulator,
)
from .report import SimulationReport
from .sequential import MessageRecord, SequentialGossipSimulator, SeqState
from .variants import (
    All2AllGossipSimulator,
    TokenizedGossipSimulator,
    TokenizedPartitioningGossipSimulator,
)

__all__ = [
    "GossipSimulator", "SimulationReport", "SimState", "Mailbox",
    "TokenizedGossipSimulator", "All2AllGossipSimulator",
    "TokenizedPartitioningGossipSimulator",
    "PassThroughGossipSimulator", "CacheNeighGossipSimulator",
    "SamplingGossipSimulator", "PartitioningGossipSimulator",
    "PENSGossipSimulator",
    "SimulationEventReceiver", "SimulationEventSender", "ProgressReceiver",
    "JSONLinesReceiver", "CallbackReceiver",
    "SequentialGossipSimulator", "SeqState", "MessageRecord",
    "ChaosConfig", "OutageEpisode", "PartitionEpisode", "ChurnProcess",
    "FaultSpike", "FaultSchedule", "build_fault_schedule",
    "rounds_to_reconverge",
    "CohortConfig", "CohortPool", "NominalTopology", "PoolStore",
    "MemoryBudgetExceeded",
]
