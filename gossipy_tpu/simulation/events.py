"""Observer event stream for simulation runs.

Re-design of the reference's Observer pattern (``SimulationEventReceiver`` /
``SimulationEventSender``, gossipy/simul.py:37-177). Two deliberate changes:

- **Granularity is per round, not per message.** The reference fires
  ``update_message`` for every Python ``Message`` object; a jitted round has
  no per-message host boundary, so receivers get per-round aggregates
  (messages sent / failed / bytes this round) — the quantities the
  reference's own ``SimulationReport`` reduces to anyway (simul.py:216-234).
- **Senders own their receiver list.** The reference keeps ``_receivers`` as
  a CLASS attribute shared by every sender instance (simul.py:94, a latent
  cross-simulator leak); here each simulator instance has its own list.

Two delivery modes (both can be active):

- *replay* (default): after the jitted scan finishes, the recorded per-round
  arrays are replayed through every receiver in order. Zero overhead inside
  the compiled program.
- *live*: when a receiver declares ``live = True``, the engine inserts an
  ordered ``io_callback`` at each round boundary so the receiver observes
  rounds as they execute (progress bars, early-stopping monitors, tracing).
  This forces a host sync per round — opt in deliberately.

``jax.profiler`` integration (SURVEY.md §5 "tracing"): pass
``profile_dir=...`` to ``GossipSimulator.start`` to wrap the run in a
profiler trace viewable in TensorBoard/XProf.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class SimulationEventReceiver:
    """Receiver interface (reference simul.py:37-88, per-round granularity).

    Subclass and override any subset; set class attribute ``live = True`` to
    be notified from inside the running program (ordered io_callback) instead
    of replay-after-run.
    """

    live: bool = False

    def update_message(self, round: int, sent: int, failed: int,
                       size: int) -> None:
        """Per-round message traffic: ``sent`` messages generated, ``failed``
        lost (drop / churn / overflow), ``size`` total scalars shipped."""

    def update_failure_causes(self, round: int, causes: dict) -> None:
        """Per-round failure breakdown: ``{"drop": n, "offline": n,
        "overflow": n}`` (telemetry.FAILURE_CAUSES order; values sum to
        ``update_message``'s ``failed``). Fired right after
        ``update_message`` by engines that track causes — both the jitted
        and the sequential engine do."""

    def update_single_message(self, failed: bool, msg) -> None:
        """Per-MESSAGE event (the reference's ``update_message(failed,
        msg)`` granularity, simul.py:55-66). Only the opt-in sequential
        high-fidelity engine (:mod:`.sequential`) emits these — a jitted
        round has no per-message host boundary; ``msg`` is a
        :class:`~gossipy_tpu.simulation.sequential.MessageRecord`."""

    def update_probes(self, round: int, probes: dict) -> None:
        """Per-round gossip-dynamics probe values (fired only by runs with
        ``probes=`` enabled; see :mod:`gossipy_tpu.telemetry.probes`).
        ``probes`` carries the JSON-able per-round summary — subsets of
        ``consensus_mean``/``consensus_max``, ``stale_mean``/``stale_max``/
        ``stale_hist``, ``accepted_total``, ``merge_delta``/``train_delta``
        (None when the decomposition is not exact for the simulator) —
        depending on which probes are on. Fired after
        ``update_failure_causes``, live and replayed alike."""

    def update_health(self, round: int, health: dict) -> None:
        """Per-round numerics-sentinel vitals (fired only by runs with
        ``sentinels=`` enabled; see :mod:`gossipy_tpu.telemetry.health`).
        ``health`` carries the JSON-able per-round summary — subsets of
        ``nonfinite_params``/``nonfinite_delta``/``nonfinite_metrics``,
        ``first_bad_slot``, ``mix_nonfinite``, ``diverged``/
        ``param_norm_max``, ``delta_norm``/``delta_hwm``,
        ``mailbox_hwm_run`` and ``trip`` — depending on the active
        :class:`~gossipy_tpu.telemetry.SentinelConfig`. Fired after
        ``update_probes``, live and replayed alike."""

    def update_chaos(self, round: int, chaos: dict) -> None:
        """Per-round scheduled-fault recovery vitals (fired only by runs
        with ``chaos=`` enabled; see :mod:`gossipy_tpu.simulation.faults`).
        ``chaos`` carries the JSON-able per-round summary — subsets of
        ``component_gap``/``within_mean``/``active_components`` (when
        consensus probes are also on) and ``failed_chaos`` (the
        scheduled-fault failure cause). Fired after ``update_health``,
        live and replayed alike."""

    def update_perf(self, round: int, perf: dict) -> None:
        """Per-round performance stats (fired only by runs with ``perf=``
        enabled; see :mod:`gossipy_tpu.telemetry.cost`). ``perf`` carries
        the JSON-able row — subsets of ``round_ms`` (host-measured wall
        ms, uniform within one ``start()`` segment) and ``mfu_est``
        (null off known accelerators). The values are HOST-derived after
        the segment finishes, so — unlike the probe/health/chaos rows —
        they replay only (live receivers saw the round before its timing
        existed). Fired after ``update_chaos``."""

    def update_metrics(self, round: int, metrics: dict) -> None:
        """Per-round cumulative engine counters (fired only by runs with
        ``metrics=`` enabled; see :mod:`gossipy_tpu.telemetry.metrics`).
        ``metrics`` carries engine-LIFETIME monotone totals —
        ``rounds_total``, ``sent_total``, ``failed_total`` — so a
        tailing dashboard reads counters straight off the stream.
        Host-derived after the segment finishes (like ``update_perf``),
        so replay-only. Fired after ``update_perf``."""

    def update_cohort(self, round: int, cohort: dict) -> None:
        """Per-round active-cohort accounting (fired only by ``cohort=``
        runs; see :mod:`gossipy_tpu.simulation.cohort`). ``cohort``
        carries ``coverage`` (fraction of the nominal pool any cohort
        has touched so far) and ``active_nodes`` (the materialized
        cohort width C). Host-driven segment loop — replay-only, like
        ``update_perf``. Fired after ``update_metrics``."""

    def update_evaluation(self, round: int, on_user: bool,
                          metrics: dict[str, float]) -> None:
        """Mean metrics for this round (``on_user`` = local test sets)."""

    def update_timestep(self, round: int) -> None:
        """A round finished (the reference's per-``t`` tick, simul.py:161-171)."""

    def update_end(self) -> None:
        """The run finished."""


class SimulationEventSender:
    """Mixin managing per-INSTANCE receivers (cf. reference simul.py:91-177)."""

    def add_receiver(self, receiver: SimulationEventReceiver) -> None:
        self._receivers_list().append(receiver)

    def remove_receiver(self, receiver: SimulationEventReceiver) -> None:
        try:
            self._receivers_list().remove(receiver)
        except ValueError:
            pass

    def _receivers_list(self) -> list[SimulationEventReceiver]:
        if not hasattr(self, "_receivers"):
            self._receivers: list[SimulationEventReceiver] = []
        return self._receivers

    def has_live_receivers(self) -> bool:
        return any(r.live for r in self._receivers_list())

    # -- dispatch ----------------------------------------------------------

    def _notify_round(self, round: int, sent: int, failed: int, size: int,
                      local: Optional[dict], glob: Optional[dict],
                      live_only: bool = False,
                      include_live: bool = False,
                      causes: Optional[dict] = None,
                      probes: Optional[dict] = None,
                      health: Optional[dict] = None,
                      chaos: Optional[dict] = None,
                      perf: Optional[dict] = None,
                      metrics: Optional[dict] = None,
                      cohort: Optional[dict] = None) -> None:
        for r in self._receivers_list():
            if live_only and not r.live:
                continue
            if not live_only and r.live and not include_live:
                continue  # live receivers already saw this round in-run
            r.update_message(round, sent, failed, size)
            if causes is not None:
                r.update_failure_causes(round, causes)
            if probes is not None:
                r.update_probes(round, probes)
            if health is not None:
                r.update_health(round, health)
            if chaos is not None:
                r.update_chaos(round, chaos)
            if perf is not None:
                r.update_perf(round, perf)
            if metrics is not None:
                r.update_metrics(round, metrics)
            if cohort is not None:
                r.update_cohort(round, cohort)
            if local is not None:
                r.update_evaluation(round, True, local)
            if glob is not None:
                r.update_evaluation(round, False, glob)
            r.update_timestep(round)

    def _notify_end(self) -> None:
        for r in self._receivers_list():
            r.update_end()

    def replay_events(self, first_round: int, stats: dict,
                      metric_names: list[str],
                      include_live: bool = False,
                      fire_end: bool = True) -> None:
        """Replay recorded per-round stats (host arrays) through non-live
        receivers, then fire ``update_end``. ``include_live=True`` also
        replays to live receivers — used when the backend cannot run host
        callbacks and the in-run delivery was disabled. ``fire_end=False``
        suppresses the final ``update_end`` — chunked drivers (the service
        scheduler streaming one slice of rounds at a time) replay several
        segments through the same receivers and fire the end themselves."""
        if not self._receivers_list():
            return
        sent = np.asarray(stats["sent"])
        failed = np.asarray(stats["failed"])
        size = np.asarray(stats["size"])
        local = np.asarray(stats["local"])
        glob = np.asarray(stats["global"])
        cause_arrs = None
        if "failed_drop" in stats:
            cause_arrs = {c: np.asarray(stats["failed_" + c])
                          for c in ("drop", "offline", "overflow")}
            if "failed_chaos" in stats:
                cause_arrs["chaos"] = np.asarray(stats["failed_chaos"])
        from ..telemetry.cost import PERF_STAT_KEYS, perf_event_row
        from ..telemetry.health import HEALTH_STAT_KEYS, health_event_row
        from ..telemetry.probes import PROBE_STAT_KEYS, probe_event_row
        from .faults import CHAOS_PROBE_KEYS, chaos_event_row
        probe_arrs = {k: np.asarray(stats[k]) for k in PROBE_STAT_KEYS
                      if k in stats}
        health_arrs = {k: np.asarray(stats[k]) for k in HEALTH_STAT_KEYS
                       if k in stats}
        chaos_arrs = {k: np.asarray(stats[k])
                      for k in ("failed_chaos",) + CHAOS_PROBE_KEYS
                      if k in stats}
        perf_arrs = {k: np.asarray(stats[k]) for k in PERF_STAT_KEYS
                     if k in stats}
        # Host-assembled list of per-round dicts (engine metrics= feed);
        # unlike the array stats above it never transits the device.
        metrics_rows = stats.get("metrics_rows")
        cohort_cov = stats.get("cohort_coverage")
        cohort_active = stats.get("cohort_active_nodes")

        def row(arr, i):
            vals = arr[i]
            if np.all(np.isnan(vals)):
                return None
            return {k: float(v) for k, v in zip(metric_names, vals)}

        for i in range(sent.shape[0]):
            causes = ({c: int(a[i]) for c, a in cause_arrs.items()}
                      if cause_arrs is not None else None)
            probes = probe_event_row({k: a[i] for k, a in probe_arrs.items()})
            health = health_event_row(
                {k: a[i] for k, a in health_arrs.items()})
            chaos = chaos_event_row({k: a[i] for k, a in chaos_arrs.items()})
            perf = perf_event_row({k: a[i] for k, a in perf_arrs.items()})
            metrics = (metrics_rows[i]
                       if metrics_rows is not None and i < len(metrics_rows)
                       else None)
            cohort = None
            if cohort_cov is not None:
                cohort = {"coverage": float(cohort_cov[i]),
                          "active_nodes": (int(cohort_active[i])
                                           if cohort_active is not None
                                           else None)}
            self._notify_round(first_round + i + 1, int(sent[i]),
                               int(failed[i]), int(size[i]),
                               row(local, i), row(glob, i),
                               include_live=include_live, causes=causes,
                               probes=probes, health=health, chaos=chaos,
                               perf=perf, metrics=metrics, cohort=cohort)
        if fire_end:
            self._notify_end()


class ProgressReceiver(SimulationEventReceiver):
    """Live round-progress printer (replaces the reference's rich progress
    bars around the time loop, simul.py:384).

    Each printed line carries the last evaluated metric, the throughput
    over the window since the previous print (rounds/s of host wall-clock
    — meaningful when live; replayed events print the replay rate), and
    the window's failed-message rate, so a long TPU run stays legible
    from the terminal: ``[round 120] accuracy=0.9104 | 812.4 r/s |
    failed 2.1%``.
    """

    live = True

    def __init__(self, every: int = 10, metric: str = "accuracy"):
        import time
        self.every = int(every)
        self.metric = metric
        self._last: dict[str, float] = {}
        self._clock = time.perf_counter
        self._t_window: float = self._clock()
        self._win_sent = 0
        self._win_failed = 0

    def update_message(self, round, sent, failed, size):
        self._win_sent += sent
        self._win_failed += failed

    def update_evaluation(self, round, on_user, metrics):
        if not on_user:
            self._last = metrics

    def update_timestep(self, round):
        if round % self.every == 0:
            val = self._last.get(self.metric)
            extra = f" {self.metric}={val:.4f}" if val is not None else ""
            now = self._clock()
            rate = self.every / max(now - self._t_window, 1e-9)
            fail_pct = (self._win_failed / self._win_sent
                        if self._win_sent else 0.0)
            print(f"[round {round}]{extra} | {rate:.1f} r/s | "
                  f"failed {fail_pct:.1%}", flush=True)
            self._t_window = now
            self._win_sent = self._win_failed = 0


class CallbackReceiver(SimulationEventReceiver):
    """Forward each round as ONE flat dict to a user callable — the
    generic metric-sink the reference lists as an open TODO ("Weights
    and Biases support", README.md:50). Any experiment tracker works
    without a bespoke receiver class::

        import wandb
        sim.add_receiver(CallbackReceiver(wandb.log))
        # or TensorBoard:
        sim.add_receiver(CallbackReceiver(
            lambda row: [writer.add_scalar(k, v, row["round"])
                         for k, v in row.items()
                         if isinstance(v, (int, float))]))

    Per round the callable receives ``{"round", "sent", "failed",
    "size"}`` plus, when the run produces them, ``failed_by_cause``
    (dict), ``local``/``global`` metric dicts, and the ``probes`` /
    ``health`` rows (the same payloads ``update_probes`` /
    ``update_health`` carry). Works replayed (default) or live
    (``live=True``); callable exceptions propagate — wrap your sink if
    it may fail.
    """

    def __init__(self, fn, live: bool = False):
        self.fn = fn
        self.live = bool(live)
        self._row: dict = {}

    def update_message(self, round, sent, failed, size):
        self._row = {"round": round, "sent": sent, "failed": failed,
                     "size": size}

    def update_failure_causes(self, round, causes):
        self._row["failed_by_cause"] = dict(causes)

    def update_probes(self, round, probes):
        self._row["probes"] = dict(probes)

    def update_health(self, round, health):
        self._row["health"] = dict(health)

    def update_chaos(self, round, chaos):
        self._row["chaos"] = dict(chaos)

    def update_perf(self, round, perf):
        self._row["perf"] = dict(perf)

    def update_metrics(self, round, metrics):
        self._row["metrics"] = dict(metrics)

    def update_cohort(self, round, cohort):
        self._row["cohort"] = dict(cohort)

    def update_evaluation(self, round, on_user, metrics):
        self._row["local" if on_user else "global"] = dict(metrics)

    def update_timestep(self, round):
        row, self._row = self._row, {}
        self.fn(row)


class JSONLinesReceiver(SimulationEventReceiver):
    """Append one JSON object per round to a file, kept tool-agnostic:
    any dashboard can tail the .jsonl (for a push-style sink — W&B,
    TensorBoard — use :class:`CallbackReceiver` instead).

    Line schema (``"schema": 7``), one object per round — versions are
    strictly additive, so a reader written against any version parses
    every later one by ignoring unknown keys (and every earlier one via
    :meth:`parse_line`, which fills absent fields with null):

        ======= =================== =====================================
        since   field               meaning
        ======= =================== =====================================
        v1      ``schema``          line-format version int
        v1      ``round``           1-based round number
        v1      ``sent``            messages generated this round
        v1      ``failed``          messages lost this round (all causes)
        v1      ``size``            total scalars shipped this round
        v1      ``local``           ``{metric: mean} | null`` (user tests)
        v1      ``global``          ``{metric: mean} | null`` (global set)
        v2      ``failed_by_cause`` ``{drop, offline, overflow} | null``;
                                    values sum to ``failed``
        v3      ``probes``          gossip-dynamics probe row ``| null``:
                                    subsets of ``consensus_mean``,
                                    ``consensus_max``, ``stale_mean``,
                                    ``stale_max``, ``stale_hist`` (list),
                                    ``accepted_total``, ``merge_delta``,
                                    ``train_delta`` per the run's
                                    ``ProbeConfig`` (null without
                                    ``probes=``)
        v4      ``health``          numerics-sentinel row ``| null``:
                                    subsets of ``nonfinite_params``,
                                    ``nonfinite_delta``,
                                    ``nonfinite_metrics``,
                                    ``first_bad_slot``, ``mix_nonfinite``,
                                    ``diverged``, ``param_norm_max``,
                                    ``delta_norm``, ``delta_hwm``,
                                    ``mailbox_hwm_run``, ``trip`` per the
                                    run's ``SentinelConfig`` (null
                                    without ``sentinels=``)
        v5      ``chaos``           scheduled-fault row ``| null``:
                                    subsets of ``component_gap``,
                                    ``within_mean``,
                                    ``active_components``,
                                    ``failed_chaos`` per the run's
                                    ``ChaosConfig`` (null without
                                    ``chaos=``; ``failed_by_cause`` also
                                    gains a ``chaos`` key on such runs)
        v6      ``perf``            performance row ``| null``: subsets
                                    of ``round_ms`` (host-measured wall
                                    ms, uniform within one ``start()``
                                    segment) and ``mfu_est`` per the
                                    run's ``PerfConfig`` (null without
                                    ``perf=``; replay-only — a live
                                    stream writes null here because the
                                    timing is host-derived after the
                                    segment)
        v8      ``cohort``          active-cohort accounting row
                                    ``| null``: ``coverage`` (fraction
                                    of the nominal pool any cohort has
                                    touched) and ``active_nodes`` (the
                                    materialized cohort width C) — null
                                    without ``cohort=``
        v7      ``metrics``         cumulative engine-counter row
                                    ``| null``: ``rounds_total``,
                                    ``sent_total``, ``failed_total`` —
                                    engine-LIFETIME monotone totals from
                                    the SLO metrics feed (null without
                                    ``metrics=``; replay-only, like
                                    ``perf``). The final registry
                                    snapshot itself travels as the
                                    telemetry sink's terminal
                                    ``metrics_snapshot`` event, not on
                                    round rows
        ======= =================== =====================================

    Works replayed (default) or live (``live=True`` streams rows during the
    jitted run through the ordered io_callback).

    One instance serves ONE simulator at a time: rows are assembled in a
    mutable per-round buffer, so attaching the same instance to two
    concurrently-running simulators interleaves fields across them. Use it
    as a context manager (``with JSONLinesReceiver(p) as rx: ...``) or call
    :meth:`close` when done.
    """

    SCHEMA = 8

    def __init__(self, path: str, live: bool = False):
        import json
        self._json = json
        self.path = path
        self.live = bool(live)
        self._row: dict = {}
        self._fh = open(path, "a", buffering=1)

    def update_message(self, round, sent, failed, size):
        self._row = {"schema": self.SCHEMA, "round": round, "sent": sent,
                     "failed": failed, "failed_by_cause": None,
                     "size": size, "probes": None, "health": None,
                     "chaos": None, "perf": None, "metrics": None,
                     "cohort": None, "local": None, "global": None}

    def update_failure_causes(self, round, causes):
        self._row["failed_by_cause"] = dict(causes)

    def update_probes(self, round, probes):
        self._row["probes"] = dict(probes)

    def update_health(self, round, health):
        self._row["health"] = dict(health)

    def update_chaos(self, round, chaos):
        self._row["chaos"] = dict(chaos)

    def update_perf(self, round, perf):
        self._row["perf"] = dict(perf)

    def update_metrics(self, round, metrics):
        self._row["metrics"] = dict(metrics)

    def update_cohort(self, round, cohort):
        self._row["cohort"] = dict(cohort)

    def update_evaluation(self, round, on_user, metrics):
        self._row["local" if on_user else "global"] = metrics

    def update_timestep(self, round):
        self._fh.write(self._json.dumps(self._row) + "\n")

    def update_end(self):
        self._fh.flush()

    @classmethod
    def parse_line(cls, line: str) -> dict:
        """Version-tolerant row reader: normalize a v1..v8 line into
        the CURRENT schema's shape (fields a line's version predates come
        back null, unknown future fields pass through untouched). The one
        reader consumers should use instead of re-encoding the version
        history themselves."""
        import json
        row = json.loads(line)
        schema = row.get("schema", 1)
        if schema < 2:
            row.setdefault("failed_by_cause", None)
        if schema < 3:
            row.setdefault("probes", None)
        if schema < 4:
            row.setdefault("health", None)
        if schema < 5:
            row.setdefault("chaos", None)
        if schema < 6:
            row.setdefault("perf", None)
        if schema < 7:
            row.setdefault("metrics", None)
        if schema < 8:
            row.setdefault("cohort", None)
        return row

    def close(self):
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
