"""The gossip simulation engine: one round = one jitted XLA program.

Re-design of ``GossipSimulator`` (reference gossipy/simul.py:273-503). The
reference steps Python time ``t`` over ``n_rounds * delta`` ticks, touching
one node object at a time (simul.py:389-451). Here the WHOLE network state is
a stacked pytree (leading node axis) and a round is a single traced function:

    send phase     decide senders (phase arithmetic) -> sample peers
                   (vectorized categorical over the adjacency) -> sample
                   drop/delay -> scatter message *metadata* into a ring-buffer
                   mailbox
    deliver phase  read this round's mailbox cell; for each of K static slots
                   gather the sender's snapshot from the params history ring
                   and apply ``handler.call`` (merge+update) under a validity
                   mask; queue replies (PULL/PUSH_PULL)
    reply phase    same over the reply mailbox (reference keeps separate
                   ``msg_queues``/``rep_queues``, simul.py:385-430)
    eval phase     vmapped local + global evaluation, mean over nodes

Key TPU-native choice: messages carry **node indices, not models**. The
payload "deep copy" of the reference (``ModelHandler.caching`` ->
``CACHE.push``, handler.py:160-176) becomes a per-round snapshot of the
stacked params (``history[r % D]``); delivery is a gather along the node
axis, which XLA turns into ICI collectives when the node axis is sharded.

Fidelity notes (documented divergences, SURVEY.md §7c):

- Bulk-synchronous rounds: within a round every send snapshots the
  round-start model, while the reference's shuffled sequential loop lets a
  node forward a model it merged moments earlier in the same round.
- Async nodes fire at every multiple of their period inside the round
  window, capped at a static ``max_fires_per_round`` (default 2; periods are
  drawn ~N(delta, delta/10), so more than two fires per round is a
  pathological tail the reference's unbounded loop would allow).
- Replies carry the replier's round-start snapshot rather than its
  just-updated model.
- Mailboxes have a static per-round capacity of ``mailbox_slots`` messages
  per receiver; overflow messages count as failed (the reference's Python
  lists are unbounded).
"""

from __future__ import annotations

import os
from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import AntiEntropyProtocol, ConstantDelay, CreateModelMode, \
    Delay, MessageType, Topology, sample_peers
from ..handlers.base import BaseHandler, ModelState, PeerModel
from ..telemetry import (
    PHASE_EVAL,
    PHASE_RECEIVE_MERGE,
    PHASE_REPLY,
    PHASE_SEND,
    PHASE_TRAIN,
    FailureCounts,
    ProbeAccum,
    ProbeConfig,
    emit_event,
)
from ..telemetry.cost import (
    PERF_STAT_KEYS,
    CostReport,
    PerfConfig,
    mfu_estimate,
)
from ..telemetry.health import (
    HEALTH_STAT_KEYS,
    HealthCarry,
    SentinelConfig,
    health_event_row,
    health_round_stats,
    nonfinite_total,
)
from ..telemetry.probes import (
    PROBE_STAT_KEYS,
    consensus_stats,
    param_layer_names,
    probe_event_row,
    probe_stats_from_accum,
    sq_param_distance,
)
from .events import SimulationEventSender
from .faults import (
    CHAOS_PROBE_KEYS,
    ChaosConfig,
    build_fault_schedule,
    chaos_round_stats,
)
from .report import SimulationReport

# Purpose tags for PRNG key folding (one stream per (round, purpose)).
# Engine-internal derived tags stay below 9000; variant subclasses must use
# tags >= 9000 to avoid stream collisions.
_K_PHASE, _K_PEER, _K_DROP, _K_DELAY, _K_ONLINE, _K_CALL, _K_EXTRA, \
    _K_REPLY_DELAY, _K_REPLY_DROP, _K_EVAL, _K_TOKEN, _K_FIRE = range(12)

PROTO_TO_MSG = {
    AntiEntropyProtocol.PUSH: MessageType.PUSH,
    AntiEntropyProtocol.PULL: MessageType.PULL,
    AntiEntropyProtocol.PUSH_PULL: MessageType.PUSH_PULL,
}

# The vmapped-batch axis name bound by every seed/tenant-batched round
# program (run_repetitions, the service megabatch): the compact/wide
# delivery dispatch reduces its predicate over this axis so the lax.cond
# stays batch-uniform (see GossipSimulator._slot_live_count).
BATCH_AXIS = "gossipy_batch"


_HOST_CALLBACKS_SUPPORTED: Optional[bool] = None


def host_callbacks_supported() -> bool:
    """Whether the active backend can run ``io_callback`` (probed once).

    Some PJRT backends (e.g. the tunneled single-chip runtime) do not
    implement host send/recv: unordered callbacks raise UNIMPLEMENTED and
    ordered ones HANG — so live event receivers must fall back to post-run
    replay there rather than deadlock the run. Live emission uses
    ``ordered=True``, so that exact mode is probed: first unordered
    in-process (the fast-failing signature), then ordered in a DISPOSABLE
    SUBPROCESS — a hung ordered program then dies with the child instead of
    squatting on the parent's device from an abandoned watchdog thread
    (which, on a single-stream backend, could stall the replay fallback
    that follows).
    """
    global _HOST_CALLBACKS_SUPPORTED
    if _HOST_CALLBACKS_SUPPORTED is None:
        def probe_unordered():
            def fn(x):
                jax.experimental.io_callback(lambda _: None, None, x,
                                             ordered=False)
                return x
            jax.block_until_ready(jax.jit(fn)(jnp.int32(0)))

        try:
            probe_unordered()
        except Exception:
            _HOST_CALLBACKS_SUPPORTED = False
            return False
        import subprocess
        import sys
        code = (
            "import jax, jax.numpy as jnp, jax.experimental\n"
            "def fn(x):\n"
            "    jax.experimental.io_callback(lambda _: None, None, x,\n"
            "                                 ordered=True)\n"
            "    return x\n"
            "jax.block_until_ready(jax.jit(fn)(jnp.int32(0)))\n"
            "print('BACKEND=' + jax.default_backend())\n")
        try:
            proc = subprocess.run([sys.executable, "-c", code], timeout=60,
                                  capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            _HOST_CALLBACKS_SUPPORTED = False
            return False
        if (proc.returncode == 0
                and f"BACKEND={jax.default_backend()}" in proc.stdout):
            _HOST_CALLBACKS_SUPPORTED = True
        elif proc.returncode == 0:
            # The child probed a DIFFERENT backend than the parent holds
            # (exclusive-device runtimes lock the chip to one process and
            # jax falls back to CPU in the child) — its answer is
            # meaningless here. Fall back to the in-process watchdog
            # thread: same answer source as the parent's device, with the
            # residual abandoned-thread risk confined to this rare case.
            _HOST_CALLBACKS_SUPPORTED = _ordered_probe_in_thread()
        else:
            # Child failed outright: either unsupported ordered callbacks
            # (the common tunneled-runtime case) or it could not attach to
            # the device at all. Distinguish via the child's backend print:
            # no backend line means it died before/at init -> in-process
            # fallback; a backend line means the probe itself failed.
            if "BACKEND=" in proc.stdout:
                _HOST_CALLBACKS_SUPPORTED = False
            else:
                _HOST_CALLBACKS_SUPPORTED = _ordered_probe_in_thread()
    return _HOST_CALLBACKS_SUPPORTED


def _ordered_probe_in_thread() -> bool:
    """In-process ordered-callback probe with a watchdog timeout.

    Used only when a subprocess probe cannot speak for the parent's
    backend (exclusive-device runtimes). A hang abandons a daemon thread
    that may still hold device state — acceptable as a last resort; the
    subprocess path is preferred exactly to avoid this.
    """
    import threading
    done = threading.Event()

    def run():
        try:
            def fn(x):
                jax.experimental.io_callback(lambda _: None, None, x,
                                             ordered=True)
                return x
            jax.block_until_ready(jax.jit(fn)(jnp.int32(0)))
            done.set()
        except Exception:
            pass  # leaves done unset -> unsupported

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=30.0)
    return done.is_set()


def select_nodes(mask: jax.Array, a, b):
    """Leafwise ``mask ? a : b`` where ``mask`` is a [N] node mask and the
    leaves carry a leading node axis (scalar leaves pass through unmasked
    broadcast)."""
    def sel(x, y):
        m = mask.reshape((-1,) + (1,) * (x.ndim - 1)) if x.ndim else mask
        return jnp.where(m, x, y)
    return jax.tree.map(sel, a, b)


class Mailbox(NamedTuple):
    """Ring-buffer mailbox: [D, N, K] int32 metadata per message slot."""

    sender: jax.Array      # sending node id, -1 = empty slot
    send_round: jax.Array  # round whose snapshot carries the payload
    msg_type: jax.Array    # MessageType value
    extra: jax.Array       # protocol-specific payload (partition id, seed, ...)

    @staticmethod
    def empty(depth: int, n: int, k: int) -> "Mailbox":
        shape = (depth, n, k)
        return Mailbox(
            sender=jnp.full(shape, -1, dtype=jnp.int32),
            send_round=jnp.zeros(shape, dtype=jnp.int32),
            msg_type=jnp.zeros(shape, dtype=jnp.int32),
            extra=jnp.zeros(shape, dtype=jnp.int32),
        )

    def clear_cell(self, b: jax.Array) -> "Mailbox":
        return Mailbox(
            sender=self.sender.at[b].set(-1),
            send_round=self.send_round.at[b].set(0),
            msg_type=self.msg_type.at[b].set(0),
            extra=self.extra.at[b].set(0),
        )


class SimState(NamedTuple):
    """Full simulator state carried through the round scan."""

    model: ModelState        # stacked [N, ...]
    phase: jax.Array         # [N] per-node timing (offset or period)
    history_params: Any      # pytree [D, N, ...] round-start snapshots
                             # (stored in the simulator's history_dtype
                             # wire format; fp32 by default)
    history_ages: jax.Array  # [D, N(, P)] snapshot ages
    mailbox: Mailbox         # push/pull traffic
    reply_box: Mailbox       # REPLY traffic (reference rep_queues)
    round: jax.Array         # int32 current round
    aux: Any = ()            # variant-specific node state (token balances,
                             # neighbor caches, PENS counters, ...) with
                             # leading node axis on every leaf
    history_scale: Any = ()  # int8 wire format only: pytree matching
                             # history_params with [D, N] f32 symmetric
                             # dequant scales per (round-slot, node, leaf);
                             # () for float32/bfloat16 rings


def _rank_within_group(key_arr: jax.Array) -> jax.Array:
    """For each element, its 0-based rank among equal values of ``key_arr``."""
    n = key_arr.shape[0]
    order = jnp.argsort(key_arr, stable=True)
    sorted_key = key_arr[order]
    pos = jnp.arange(n)
    is_start = jnp.concatenate([jnp.ones(1, bool), sorted_key[1:] != sorted_key[:-1]])
    group_start = jax.lax.associative_scan(jnp.maximum, jnp.where(is_start, pos, 0))
    rank_sorted = pos - group_start
    return jnp.zeros(n, dtype=jnp.int32).at[order].set(rank_sorted.astype(jnp.int32))


class MemoryBudgetExceeded(RuntimeError):
    """Predicted device-memory footprint exceeds the accelerator budget.

    Raised by :meth:`GossipSimulator.check_memory_budget` BEFORE any
    compile/launch is paid, so a run that would die with an opaque
    accelerator rc=1 (the 50k materialized ladder crash,
    ``degrade_reason: accel_run_rc_1`` in BASELINE.md) instead names the
    predicted bytes, the limit, and the dominant budget term. Carries
    ``predicted_bytes``, ``limit_bytes``, ``dominant_term`` and the full
    ``budget`` dict for forensics.
    """

    def __init__(self, predicted_bytes: int, limit_bytes: int,
                 dominant_term: str, budget: dict):
        self.predicted_bytes = int(predicted_bytes)
        self.limit_bytes = int(limit_bytes)
        self.dominant_term = dominant_term
        self.budget = budget
        super().__init__(
            f"memory budget refused: predicted "
            f"{predicted_bytes / 2**30:.2f} GB exceeds the "
            f"{limit_bytes / 2**30:.2f} GB limit; dominant term "
            f"{dominant_term} = "
            f"{(budget.get(dominant_term) or 0) / 2**30:.2f} GB — "
            "shrink N/history depth, or switch to cohort mode "
            "(simulation/cohort.py) where per-round cost is C-shaped")


class GossipSimulator(SimulationEventSender):
    """Vanilla gossip simulator (reference GossipSimulator, simul.py:273-503).

    Parameters
    ----------
    handler : BaseHandler
        Model handler (closed over by the jitted round program).
    topology : Topology
        Static P2P network.
    data : dict
        Stacked arrays from ``DataDispatcher.stacked()``: ``xtr/ytr/mtr`` and
        optionally ``xte/yte/mte`` and ``x_eval/y_eval``.
    delta : int
        Round length in time units (reference simul.py:300).
    protocol : AntiEntropyProtocol
    drop_prob, online_prob : float
        Message loss / node availability Bernoulli rates (simul.py:403-428).
    delay : Delay
        Message latency model.
    sampling_eval : float
        If > 0, evaluate a random node subset each round (simul.py:433-436).
    eval_every : int
        Evaluate every n-th round (default 1 = per round, the reference's
        behavior). Evaluation is often the dominant per-round cost for CNN
        configs (every node forwards the whole eval set); skipped rounds
        report NaN metrics, which the report omits.
    sync : bool
        Sync nodes fire at a fixed offset each round; async nodes have a
        ~N(delta, delta/10) period (reference node.py:79,111-125).
    mailbox_slots, reply_slots : int
        Static per-(round, receiver) message capacity; overflow counts as
        failed (the reference's Python lists are unbounded).
        ``mailbox_slots=None`` (default) derives the capacity from the
        topology at construction: the smallest K whose Poisson tail at the
        worst-case expected fan-in keeps per-node-round loss under 1e-3
        (floor 6 — ~0.003% loss at degree-20 uniform fan-in — cap 64, with
        the undersized warning if the cap binds). Hub-heavy topologies (BA
        stars) are thereby correct by default instead of warned-at. Empty
        slots are skipped at runtime, so unused capacity is cheap but not
        free; pass an explicit int to pin it.
    max_fires_per_round : int | None
        Static cap on how many times an async node can fire inside one
        round window (reference node.py:111-125 fires at every multiple of
        the node's period). ``None`` = 1 for sync simulations (exact), 2
        for async (covers periods ~N(delta, delta/10)).
    message_size : int | None
        Payload size in scalars for delay/size accounting; defaults to the
        handler's model parameter count.
    fused_merge : bool
        Use the pallas fused gather+merge kernel (:mod:`gossipy_tpu.ops`) in
        the deliver phase instead of gather-then-blend. Only valid for
        MERGE_UPDATE handlers whose merge is the uniform parameter average
        (``handler.uniform_avg_merge``); numerically equivalent up to fp
        reassociation.
    compact_deliver : bool | int | None
        Compact each mailbox slot's active receivers into a small gathered
        batch before the merge+train pass instead of running the pass over
        the full population under a validity mask. At Poisson(~1) fan-in
        only ~63% of nodes occupy slot 0 and ~26% occupy slot 1 (slots fill
        in arrival order, so slot ``k`` holds each receiver's ``k``-th
        message of the round), yet every occupied slot pays a full
        [N]-wide vmapped ``handler.call`` — the dominant term of the round
        at CNN scale and the core of the measured 0.39% MFU (round-4
        verdict #1). With compaction, slots beyond the first run at a
        static capacity derived from the topology's worst-case fan-in
        (``P(arrivals >= 2)`` binomial quantile); a slot whose live count
        exceeds the capacity falls back to the full-width pass via
        ``lax.cond`` at runtime, so results are independent of the setting
        (same per-node PRNG streams; equal up to fp layout). ``None``
        (default) auto-enables for populations >= 48 when the receive
        pipeline is the base one (variants overriding ``_apply_receive``
        run unfused full-width; ``_decode_extra`` overrides are fine — the
        decoded arg is gathered — provided they are elementwise, which all
        in-tree ones are). An int pins the capacity explicitly.
        Under a seed/tenant vmap (:meth:`run_repetitions`, the service
        megabatch) the compact/wide dispatch predicate is reduced across
        the batch axis (``lax.pmax``) before the ``lax.cond`` so it stays
        batch-uniform — a vmapped cond predicate would otherwise execute
        both branches, ADDING the compact pass to every wide one. The
        whole batch takes the compact pass only when every lane fits.
    history_dtype : str
        Wire/storage format of the params-history ring — what a message's
        payload snapshot is stored (and therefore gathered) as:
        ``"float32"`` (default; bit-identical to storing the params
        directly), ``"bfloat16"`` (plain cast, 2x smaller ring and deliver
        gather), or ``"int8"`` (symmetric per-(round-slot, node, leaf)
        scales in a small [D, N]-per-leaf sidecar, quantize-on-snapshot /
        dequantize-on-gather, ~4x smaller). The ring is the dominant
        persistent state term (``memory_budget()["history_ring_bytes"]``)
        and the deliver phase's HBM traffic, so reduced formats raise the
        max population / ring depth on a fixed chip; they also model real
        gossip wire compression. Merge math always runs in fp32 — only the
        stored snapshot is low-precision.
    probes : ProbeConfig | bool | None
        Opt-in gossip-dynamics probes computed INSIDE the jitted round
        program (:mod:`gossipy_tpu.telemetry.probes`): consensus distance
        (mean/max L2 from the population-mean params + per-layer
        breakdown), merge-staleness distribution (mean/max + clamped
        histogram of ``round − send_round`` over accepted messages), and
        realized mixing (per-node accepted-merge counts, merge-delta vs
        train-delta norms). ``None`` (default) traces the exact same
        program as before the feature; ``True`` enables all probes; a
        :class:`~gossipy_tpu.telemetry.ProbeConfig` picks a subset. Probe
        arrays land in the :class:`SimulationReport` (``probe_*``), stream
        through the ``update_probes`` observer event (live path included)
        and are stamped into the run manifest. The merge/train delta
        decomposition is exact only for the base receive pipeline under
        MERGE_UPDATE (recomputing the handler's merge as a pure probe);
        variants with custom receive behavior report NaN deltas while the
        other probes stay live.
    sentinels : SentinelConfig | bool | None
        Opt-in numerics sentinels computed INSIDE the jitted round
        program (:mod:`gossipy_tpu.telemetry.health`): per-leaf
        non-finite counts on params / the round's param delta / the
        evaluated metric rows (plus the first mailbox slot whose
        delivery introduced a non-finite value), per-node divergence
        flags (param norm exceeding a configurable multiple of its own
        EMA, tracked across rounds in the scan carry), the round-delta
        norm with its running high-water mark, and the run-level
        mailbox-saturation watermark — summarized in a per-round
        ``health_trip`` flag. ``None`` (default) traces the exact same
        program as before the feature; ``True`` enables all sentinels; a
        :class:`~gossipy_tpu.telemetry.SentinelConfig` picks a subset.
        Health arrays land in the report (``health_*``), stream through
        the ``update_health`` observer event (live runs also emit a
        ``sentinel_trip`` telemetry event from inside the program the
        moment a round trips) and are stamped into the run manifest.
        Pair with :class:`~gossipy_tpu.telemetry.FlightRecorder` to
        capture a deterministically replayable repro bundle on anomaly.
    chaos : ChaosConfig | dict | None
        Opt-in scheduled fault injection (:mod:`.faults`): correlated
        outage episodes (node groups forced fully offline — no sends, no
        receives — for contiguous round windows), network partitions and
        edge churn (per-round edge-alive masks over the static base
        adjacency, so compiled shapes never change), and
        piecewise-constant ``drop_prob`` / delay-scale spikes. The
        declarative config compiles at construction into a shape-static
        :class:`~gossipy_tpu.simulation.faults.FaultSchedule` the jitted
        round program indexes by the traced absolute round number.
        Delivery failures on forced-offline receivers get their own
        ``"chaos"`` failure cause (the legacy ``failed`` total stays the
        exact cause sum); with consensus probes also enabled, the round
        stats gain the partition-recovery vitals
        (``chaos_component_gap`` / ``chaos_within_mean`` /
        ``chaos_active_components``). ``None`` (default) traces the
        exact same program as before the feature. Partitions/churn sever
        links at SEND time; in-flight messages still drain. Variants
        overriding ``_select_peers`` (PENS) cannot take edge faults and
        raise at construction.
    cohort : CohortConfig | int | dict | None
        Opt-in sampled active-cohort mode (:mod:`.cohort`): ``topology``
        names the NOMINAL population of size N (or a
        :class:`~gossipy_tpu.simulation.cohort.NominalTopology` size
        stand-in), the full population lives as a host-resident
        :class:`~gossipy_tpu.simulation.cohort.CohortPool`
        (:meth:`init_cohort_pool`), and each round materializes only a
        sampled cohort of C nodes — gather, run the standard jitted
        round at shape [C, ...], scatter back — so per-round cost
        decouples from N and nominal 10M populations are simulable at
        the cost of C. ``None`` (default) traces the exact same program
        as before the feature (gate-enforced identity pair). Mutually
        exclusive with ``chaos``; base GossipSimulator only. See
        docs/scale.md for semantics + the bias caveats vs
        full-population gossip.
    """

    # Out-of-tree subclasses that override ``_decode_extra`` or
    # ``_receive_rows`` must declare compaction safety explicitly (the
    # row-aligned/elementwise contract documented on those hooks) by
    # setting ``_compact_safe = True`` before compact delivery auto-enables
    # for them. In-tree variants set it; the base pipeline needs no flag.
    _compact_safe: bool = False

    # Name of the vmapped batch axis when the round program is being traced
    # under a seed/tenant vmap (run_repetitions, the service megabatch), or
    # None for a plain single-simulation trace. A ``lax.cond`` whose
    # predicate is batched executes BOTH branches, so the compact/wide
    # delivery dispatch reduces its slot-overflow predicate across this
    # axis (``lax.pmax``) to stay batch-uniform: the whole batch takes the
    # compact pass only when EVERY lane's live count fits the capacity
    # (conservative and semantics-preserving — the wide pass is always
    # correct). Set only for the duration of a batched trace.
    _batch_axis_name: Optional[str] = None

    _HISTORY_DTYPES = ("float32", "bfloat16", "int8")

    def __init__(self,
                 handler: BaseHandler,
                 topology: Topology,
                 data: dict,
                 delta: int = 100,
                 protocol: AntiEntropyProtocol = AntiEntropyProtocol.PUSH,
                 drop_prob: float = 0.0,
                 online_prob: float = 1.0,
                 delay: Delay = ConstantDelay(0),
                 sampling_eval: float = 0.0,
                 eval_every: int = 1,
                 sync: bool = True,
                 mailbox_slots: Optional[int] = None,
                 reply_slots: int = 2,
                 message_size: Optional[int] = None,
                 fused_merge: Union[bool, str] = False,
                 compact_deliver: Optional[bool] = None,
                 mesh=None,
                 max_fires_per_round: Optional[int] = None,
                 history_dtype: str = "float32",
                 probes: Union[None, bool, ProbeConfig] = None,
                 sentinels: Union[None, bool, SentinelConfig] = None,
                 chaos: Union[None, dict, ChaosConfig] = None,
                 perf: Union[None, bool, PerfConfig] = None,
                 metrics: Union[None, bool] = None,
                 cohort=None,
                 tracing=None,
                 ledger=None):
        assert 0 <= drop_prob < 1 and 0 < online_prob <= 1
        if history_dtype not in self._HISTORY_DTYPES:
            raise ValueError(
                f"unknown history_dtype {history_dtype!r}; options: "
                + ", ".join(self._HISTORY_DTYPES))
        self.history_dtype = history_dtype
        # Sampled active-cohort mode (simulation.cohort): None = strictly
        # no cohort code anywhere near the trace (the default round
        # program is byte-identical to the pre-feature one — the
        # engine/cohort-off identity pair in analysis/hlo.py enforces
        # it). When set, ``topology`` names the NOMINAL population (a
        # real graph, or a NominalTopology size stand-in for resample
        # mode) and is swapped here for the C-node inner round topology
        # the rest of construction sizes against; the full population
        # lives in a host-resident CohortPool (init_cohort_pool) and
        # start() drives gather -> [C]-round -> scatter segments.
        self.nominal_topology = None
        self.nominal_n = int(topology.num_nodes)
        from .cohort import CohortConfig
        self.cohort = CohortConfig.coerce(cohort)
        # Live disk-backed pool store (CohortConfig.pool_dir), owned by
        # init_cohort_pool/load — None for RAM pools and non-cohort runs.
        self._pool_store = None
        if self.cohort is not None:
            if chaos is not None:
                raise ValueError(
                    "cohort mode and chaos scheduling are mutually "
                    "exclusive (fault schedules are nominal-population-"
                    "indexed; the active cohort rotates)")
            from .cohort import setup_cohort
            topology = setup_cohort(self, topology)
        self.handler = handler
        self.topology = topology
        self.n_nodes = topology.num_nodes
        self.delta = int(delta)
        self.protocol = protocol
        self.drop_prob = float(drop_prob)
        self.online_prob = float(online_prob)
        self.delay = delay
        self.sampling_eval = float(sampling_eval)
        self.eval_every = int(eval_every)
        assert self.eval_every >= 1
        self.sync = sync
        if max_fires_per_round is None:
            max_fires_per_round = 1 if sync else 2
        self.F = int(max_fires_per_round)
        assert self.F >= 1
        self._lam_max_cache: Optional[float] = None
        if mailbox_slots is None:
            self.K = self._derive_mailbox_slots(self._lam_max())
        else:
            self.K = int(mailbox_slots)
        self.Kr = int(reply_slots)
        self._warn_if_mailbox_undersized()

        self.data = {k: jnp.asarray(v) for k, v in data.items()}
        self.has_local_test = "xte" in data
        self.has_global_eval = "x_eval" in data
        self._warn_if_eval_memory_large()
        self._message_size = message_size
        self._metric_names: Optional[list[str]] = None
        self._jit_cache: dict = {}

        # fused_merge: False | "multi" (the default spelling of True: ONE
        # multi-slot kernel launch + ONE vmapped update drains the whole
        # mailbox cell) | "per_slot" (legacy: one launch + one update per
        # occupied slot — interleaved per-slot semantics, kept for A/B
        # measurement and strict multi-arrival parity with the unfused
        # path).
        if fused_merge is True:
            fused_merge = "multi"
        elif fused_merge and fused_merge not in ("multi", "per_slot"):
            raise ValueError(
                f"unknown fused_merge mode {fused_merge!r}; options: "
                "False, True/'multi', 'per_slot'")
        self.fused_merge = fused_merge
        if self.fused_merge:
            # The fused kernel replaces the whole gather->decode->apply slot
            # pipeline; any variant customizing one of those hooks would be
            # silently bypassed.
            hooks = ["_apply_receive", "_receive_rows", "_gather_peer",
                     "_decode_extra"]
            if self.fused_merge == "multi":
                # The single-pass form additionally collapses the slot loop:
                # per-slot hooks and per-slot reply payloads would observe a
                # state ordering that no longer exists.
                hooks += ["_post_receive_slot", "_reply_extra"]
            for hook in hooks:
                assert getattr(type(self), hook) is getattr(GossipSimulator, hook), \
                    f"fused_merge requires the base receive path ({hook} is " \
                    f"overridden by {type(self).__name__})"
            assert getattr(handler, "uniform_avg_merge", False), \
                "fused_merge requires a uniform-average merge handler"
            assert getattr(handler, "merge_peer_weight", None) is not None, \
                "fused_merge requires the handler to declare its blend " \
                "coefficient (merge_peer_weight)"
            assert handler.mode == CreateModelMode.MERGE_UPDATE, \
                "fused_merge only fuses the MERGE_UPDATE path"
        # Mesh-sharded fused deliver: the multi-slot kernel runs inside a
        # shard_map over the mesh's node axis (parallel.collectives ring),
        # so the merge+update math executes on each replica's shard instead
        # of replicated. Placement derives from the rule registry's
        # primitives — no hand-placed specs (tests/test_rules.py AST test).
        self.mesh = mesh
        if mesh is not None:
            assert self.fused_merge == "multi", \
                "GossipSimulator(mesh=) shards the single-pass fused " \
                "deliver; pass fused_merge=True/'multi'"
            from ..parallel import _node_axis_entry
            from ..parallel.collectives import _axis_size
            self._fused_ring_axis = _node_axis_entry(mesh, None)
            assert self.n_nodes % _axis_size(mesh, self._fused_ring_axis) == 0, \
                "node count must divide the mesh's node axes for the " \
                "sharded fused deliver"

        # Compaction re-routes the gather->decode->apply slot pipeline
        # through [cap]-shaped sub-batches; like fused_merge it is only
        # valid when the pipeline pieces are the base ones. Supported
        # customization points under compaction: _decode_extra (the
        # decoded arg is gathered) and _receive_rows (row-aligned by
        # contract) — but because that row-aligned/elementwise contract
        # cannot be verified mechanically, a subclass overriding either
        # must DECLARE safety via the ``_compact_safe`` class attribute
        # before the auto default enables compaction (every in-tree
        # override does). _gather_peer / _apply_receive overrides may read
        # full-width positional state and disable it outright.
        base_receive = all(
            getattr(type(self), hook) is getattr(GossipSimulator, hook)
            for hook in ("_apply_receive", "_gather_peer"))
        extra_base = all(
            getattr(type(self), hook) is getattr(GossipSimulator, hook)
            for hook in ("_decode_extra", "_receive_rows"))
        compact_ok = base_receive and (extra_base or type(self)._compact_safe)
        if compact_deliver is None:
            # K == 1 means a single slot-0 pass whose typical occupancy
            # (~1-e^-lam of the population) exceeds any useful capacity —
            # and covers All2All, which pins one slot and never reads it.
            compact_deliver = (compact_ok and not self.fused_merge
                               and self.n_nodes >= 48 and self.K > 1)
        elif compact_deliver:
            assert base_receive, \
                "compact_deliver requires the base _apply_receive/" \
                f"_gather_peer (overridden by {type(self).__name__}); " \
                "pass compact_deliver=False or None"
            assert extra_base or type(self)._compact_safe, \
                f"{type(self).__name__} overrides _decode_extra/" \
                "_receive_rows without declaring _compact_safe = True; " \
                "compaction gathers those hooks' inputs row-wise and is " \
                "only correct for row-aligned/elementwise overrides — set " \
                "the attribute after checking the contract, or pass " \
                "compact_deliver=False"
            assert self.fused_merge != "per_slot", \
                "compact_deliver composes with the single-pass fused " \
                "deliver (fused_merge=True/'multi') but not the legacy " \
                "per-slot fused path"
            assert self.mesh is None, \
                "compact_deliver gathers a [cap] row subset, which the " \
                "mesh-sharded fused deliver cannot re-shard; use one or " \
                "the other"
        if compact_deliver and not isinstance(compact_deliver, bool):
            # Explicit integer capacity (tests / tuning); overflow still
            # falls back to the full-width pass, so ANY positive value is
            # correct. Reject nonsense here — a negative cap would only
            # surface as a deep lax shape error at first trace.
            if int(compact_deliver) < 1:
                raise ValueError(
                    "compact_deliver capacity must be >= 1, got "
                    f"{compact_deliver} (use False/None to disable)")
            self._compact_cap: Optional[int] = min(int(compact_deliver),
                                                   self.n_nodes)
        elif compact_deliver and self.K == 1:
            # A single slot's typical occupancy exceeds any derived cap:
            # the pass would pay the per-slot argsort+cond and never take
            # the compact branch. Explicit True here is a no-op request.
            import warnings
            warnings.warn("compact_deliver=True has no effect with "
                          "mailbox_slots=1 (slot 0 always overflows the "
                          "derived capacity); disabled. Pass an explicit "
                          "integer capacity to force it.")
            self._compact_cap = None
        else:
            self._compact_cap = (
                self._derive_compact_cap() if compact_deliver
                else None)

        # Gossip-dynamics probes: None = strictly no probe code in the
        # trace (the default round program is byte-identical to the
        # pre-feature one). The merge/train-delta decomposition recomputes
        # the handler's merge as a pure probe, which is only exact when the
        # receive pipeline is the base MERGE_UPDATE one — custom receive
        # variants (PassThrough's accept draw, CacheNeigh's parking, PENS
        # phase 1) report NaN deltas instead of a wrong number.
        self.probes: Optional[ProbeConfig] = ProbeConfig.coerce(probes)
        # Numerics sentinels: None = strictly no sentinel code in the
        # trace (same discipline as probes — the default round program is
        # byte-identical to the pre-feature one). The per-round vitals
        # are computed by the scan body AFTER ``_round`` (so every
        # variant's round program is covered without re-implementation);
        # the cross-round EMA/high-water state rides the scan carry.
        self.sentinels: Optional[SentinelConfig] = \
            SentinelConfig.coerce(sentinels)
        # Cross-run sentinel state: the divergence EMA and the high-water
        # marks PERSIST across consecutive start() calls on this
        # simulator (chunked drivers — CheckpointManager, FlightRecorder
        # — must not re-seed the EMA at every chunk boundary, or a jump
        # on a chunk's first round is invisible). init_nodes resets it.
        self._health_carry: Optional[HealthCarry] = None
        self._probe_delta_ok = (
            self.probes is not None and self.probes.mixing
            and self.handler.mode == CreateModelMode.MERGE_UPDATE
            and all(getattr(type(self), hook)
                    is getattr(GossipSimulator, hook)
                    for hook in ("_apply_receive", "_receive_rows")))
        # Scheduled fault injection (simulation.faults): None = strictly
        # no chaos code in the trace (same discipline as probes and
        # sentinels — the default round program is byte-identical to the
        # pre-feature one). The declarative config compiles here into a
        # shape-static schedule the round program indexes by the traced
        # absolute round; the static facts that pin the TRACE (component
        # count, edge-mask form) live on the simulator, the per-round
        # VALUES live in ``chaos_schedule`` — which the service scheduler
        # rebinds per tenant lane, like data and the fault rates.
        # Performance observability (telemetry.cost): None = no perf
        # collection at all; PerfConfig = host-side-only cost/memory/
        # timing capture. Unlike probes/sentinels/chaos this layer NEVER
        # touches the trace — perf on and off compile byte-identical HLO
        # (gate-enforced) — so "opt-in" here gates host work (an AOT
        # compile detour, one block_until_ready per start() call), not
        # program content.
        self.perf: Optional[PerfConfig] = PerfConfig.coerce(perf)
        self._cost_reports: list = []
        self._perf_last: Optional[dict] = None
        # SLO metrics feed (telemetry.metrics): like perf, this layer is
        # host-side ONLY — nothing traced reads it, metrics on and off
        # compile byte-identical HLO (gate pair engine/metrics-on). When
        # enabled, every finished start() segment increments the
        # process registry's engine_rounds/sent/failed-by-cause counters
        # (sourced from the FailureCounts arrays the report carries) and
        # the JSONL event stream's per-round rows gain a cumulative
        # ``metrics`` block (schema v7).
        self.metrics_enabled: bool = bool(metrics)
        self._metrics_base = {"rounds": 0, "sent": 0, "failed": 0}
        # Host-side span tracing (telemetry.tracing): like perf and
        # metrics, host-side ONLY — tracing on and off compile
        # byte-identical HLO (gate pair engine/tracing-on) and tracelint's
        # trace-in-trace rule proves nothing traced can reach the tracer.
        # None/False = no tracer; True = the process-default tracer
        # (installed on demand, so engine + service + checkpoint spans
        # share one timeline); a Tracer instance = explicit sink.
        # Note: a live tracer adds ONE block_until_ready per start()
        # segment (the run span must close at execution end, not at async
        # dispatch) — the same host-sync the perf timing layer does.
        if tracing is None or tracing is False:
            self.tracer = None
        elif tracing is True:
            from ..telemetry.tracing import ensure_tracer
            self.tracer = ensure_tracer()
        else:
            self.tracer = tracing
        # Run-ledger feed (telemetry.ledger): host-side ONLY like perf/
        # metrics/tracing — ledger on and off compile byte-identical HLO
        # (gate pair engine/ledger-on) and tracelint's ledger-in-trace
        # rule proves nothing traced can reach it. None consults the
        # GOSSIPY_TPU_LEDGER env var (unset = off), False is strictly
        # off, a path string / RunLedger instance is explicit. Every
        # finished start() segment appends one digest row (run id shared
        # across a chunked run's segments); appends are best-effort — a
        # ledger problem must never take down a finished run.
        from ..telemetry.ledger import resolve_ledger
        self.ledger = resolve_ledger(ledger)
        self._ledger_run_id: Optional[str] = None
        self.chaos: Optional[ChaosConfig] = ChaosConfig.coerce(chaos)
        self.chaos_schedule = None
        self._chaos_edge_form: Optional[str] = None
        self._chaos_ncomp = 1
        if self.chaos is not None:
            sched_np = build_fault_schedule(self.chaos, topology,
                                            self.drop_prob)
            self.chaos_schedule = jax.tree.map(jnp.asarray, sched_np)
            self._chaos_ncomp = self.chaos.max_components()
            if self.chaos.has_edge_faults():
                if type(self)._select_peers is not \
                        GossipSimulator._select_peers and \
                        type(self)._round is GossipSimulator._round:
                    raise ValueError(
                        f"{type(self).__name__} overrides _select_peers; "
                        "chaos partitions/churn mask the BASE uniform "
                        "peer sampling and would be silently bypassed — "
                        "use outage/spike faults only, or drop chaos")
                if isinstance(sched_np.edge_masks, np.ndarray):
                    self._chaos_edge_form = "dense"
                else:
                    self._chaos_edge_form = "slot"
                    from .nodes import build_neighbor_table
                    self._chaos_nbr_table = jnp.asarray(
                        build_neighbor_table(topology))

    # -- setup -------------------------------------------------------------

    def _lam_max(self) -> float:
        """Worst-case expected fan-in, computed at most once per simulator —
        the scan is O(E) (or an [N, N] matvec on dense topologies) and all
        consumers (slot derivation, compaction capacity, undersized
        warning) share it. Subclasses whose round never reads the mailbox
        (All2All) pin ``mailbox_slots`` and no-op the warning, skipping
        the scan entirely."""
        if self._lam_max_cache is None:
            self._lam_max_cache = float(self._lam_vector().max()) \
                if self.n_nodes else 0.0
        return self._lam_max_cache

    def _lam_vector(self) -> np.ndarray:
        """Cached :meth:`_expected_fanin_vector` — both consumers (mailbox
        bound via max, compaction capacity via the sum of per-node tails)
        run at construction and must not pay the O(E)/matvec scan twice."""
        if getattr(self, "_lam_vec_cache", None) is None:
            self._lam_vec_cache = self._expected_fanin_vector()
        return self._lam_vec_cache

    def _expected_fanin_vector(self) -> np.ndarray:
        """Per-node expected same-round fan-in under uniform peer sampling:
        ``lam_i = sum_{j in N(i)} F / deg_j`` (delays spreading arrivals
        across rounds make this an upper-ish estimate; replies add ~the
        same again for PUSH_PULL). Max drives the mailbox bound; the full
        vector drives the compaction capacity — on hub topologies the max
        (the hub) says nothing about how many NODES see multi-arrivals."""
        if self.cohort is not None:
            # The inner cohort round samples peers uniformly over the
            # active cohort (or its induced subgraph, whose fan-in is
            # bounded by the same draw): expected fan-in is exactly F
            # per node, with no O(N) nominal-topology scan.
            return np.full(self.n_nodes, float(self.F))
        if self.n_nodes == 0:
            return np.zeros(0)
        deg = np.maximum(np.asarray(self.topology.degrees, dtype=np.float64), 1.0)
        inv = self.F / deg  # per-sender hit probability on each out-neighbor
        try:
            adj = self.topology.adjacency
        except AttributeError:  # SparseTopology refuses dense materialization
            adj = None
        if adj is not None:
            # Fan-in of i = sum over SENDERS j (adj[j, i]) of F/deg_j — a
            # column sum (adjacency rows are out-neighbors; directed
            # adjacencies are allowed).
            return np.asarray(inv @ adj, dtype=np.float64)
        # CSR rows are out-neighbor lists: scatter each sender row's
        # F/deg into its targets.
        lam = np.zeros(self.n_nodes)
        degrees = np.asarray(self.topology.degrees)
        if degrees.sum():
            np.add.at(lam, self.topology.indices, np.repeat(inv, degrees))
        return lam

    @staticmethod
    def _poisson_tail(lam: float, k: int) -> float:
        """P(Poisson(lam) > k) = 1 - sum_{x<=k} e^-lam lam^x / x!.

        Computed in log space (k <= _SLOT_CAP, so the loop is tiny): the
        naive cumprod overflows to inf*0 = NaN around lam ~ 1e6 — a star
        hub at the populations this engine targets — and a NaN here would
        silently pin the derived mailbox at the floor AND suppress the
        undersized warning.
        """
        if lam <= 0.0:
            return 0.0
        import math
        logs = [-lam + x * math.log(lam) - math.lgamma(x + 1)
                for x in range(k + 1)]
        m = max(logs)
        cdf = math.exp(m) * sum(math.exp(l - m) for l in logs)
        return min(max(1.0 - cdf, 0.0), 1.0)

    _SLOT_FLOOR = 6    # ~0.003% loss at degree-20 uniform fan-in
    _SLOT_CAP = 64     # mailbox metadata stays O(N*K); cap binding warns

    def _derive_mailbox_slots(self, lam_max: float) -> int:
        """Smallest K with per-node-round overflow ``P(Poisson(lam) > K)``
        under 1e-3, floored/capped (hub topologies become correct by
        default; a hub hotter than the cap still warns)."""
        k = self._SLOT_FLOOR
        while k < self._SLOT_CAP and self._poisson_tail(lam_max, k) > 1e-3:
            k += 1
        return k

    def _derive_compact_cap(self) -> Optional[int]:
        """Static receiver capacity for the compacted slot pass.

        Sized for slots >= 1 (the waste-dominated ones): the number of
        nodes with a second same-round arrival is a sum of independent
        per-node indicators with ``p2_i = P(Poisson(lam_i) >= 2)`` at each
        node's OWN expected fan-in; take mean + 3 sigma + 4, round up to a
        multiple of 8 (tidy vector lanes). Per-node (not worst-case)
        probabilities matter on hub topologies: a BA hub's lam is huge but
        it is ONE node — sizing from the max would disable compaction for
        the whole population. Slot 0 (~``sum(1-e^-lam_i)`` nodes)
        intentionally overflows the capacity and takes the full-width
        pass. Returns None when the capacity would not beat the full pass
        (compaction then stays off)."""
        n = self.n_nodes
        # The slot pass's LIVE count sees only messages that survived the
        # drop draw (never scattered) and landed on an online receiver —
        # both static rates, priced in with their actual runtime shapes:
        # drops are per-MESSAGE (Poisson thinning of the arrival
        # intensity), while online is sampled once per RECEIVER-round and
        # gates all of a node's slots at once (a Bernoulli factor on the
        # node's live indicator, NOT a thinning of lam). The mailbox
        # bound deliberately prices neither (staying conservative there
        # costs slots, not semantics).
        lam = self._lam_vector() * (1.0 - self.drop_prob)
        # 1 - e^-lam (1 + lam), elementwise and vectorized (the loop-free
        # float64 form is stable here: no cumprod, no division).
        p2 = np.clip(-np.expm1(-lam) - lam * np.exp(-lam), 0.0, 1.0)
        p2 *= self.online_prob
        cap = p2.sum() + 3.0 * float(np.sqrt((p2 * (1.0 - p2)).sum())) + 4.0
        cap = int(-(-cap // 8) * 8)
        cap = max(cap, 8)
        if cap >= 0.75 * n:
            return None
        return cap

    def _warn_if_mailbox_undersized(self) -> None:
        """Warn when the K-slot mailbox will drop a material message
        fraction — a lowered explicit ``mailbox_slots``, or a derived one
        whose cap binds (hub fan-in beyond ``_SLOT_CAP``). Overflowed
        messages are honestly counted as "failed", but the user should hear
        about it up front.
        """
        lam_max = self._lam_max()
        if lam_max <= 0.0:
            return
        p_over = self._poisson_tail(lam_max, self.K)
        if p_over > 1e-3:
            import warnings
            emit_event("mailbox_undersized", {
                "mailbox_slots": self.K,
                "lam_max": lam_max,
                "p_overflow_per_node_round": p_over,
                "n_nodes": self.n_nodes,
                "simulator": type(self).__name__,
            })
            warnings.warn(
                f"mailbox_slots={self.K} may overflow on this topology: "
                f"worst-case expected same-round fan-in {lam_max:.1f} gives "
                f"~{p_over:.1%} per-node-round message loss (counted as "
                "'failed'). Raise mailbox_slots to silence.")

    def _n_eval_nodes(self) -> int:
        """How many nodes an evaluation pass materializes (the static
        ``sampling_eval`` subset size, or the full population). Shared by
        ``_eval_phase`` and the construction-time memory estimate so the
        two cannot drift."""
        if self.sampling_eval > 0:
            return max(int(self.n_nodes * self.sampling_eval), 1)
        return self.n_nodes

    def _eval_peak_bytes(self) -> int:
        """Transient peak of the global-evaluation pass: scores + the
        paired AUC-sort operands, ~3 [eval-nodes, eval-samples] f32
        buffers. The ONE formula behind both the construction-time warning
        and :meth:`memory_budget`, so the two cannot drift."""
        if not self.has_global_eval:
            return 0
        return 3 * self._n_eval_nodes() * int(self.data["x_eval"].shape[0]) * 4

    def _warn_if_eval_memory_large(self) -> None:
        """Warn when the global-evaluation score tensor will be huge.

        Global eval materializes ``[eval-nodes, eval-samples, ...]``
        intermediates (scores + the AUC sort); at 50k nodes an uncapped 20%
        eval split is a ~16 GB tensor — OOM on a single chip, discovered
        the hard way by ``bench.py --scale``. Estimate the peak and point
        at the two knobs (``sampling_eval``, a smaller eval set) before the
        user pays a compile to find out.
        """
        if not self.has_global_eval:
            return
        n_eval_nodes = self._n_eval_nodes()
        n_samples = int(self.data["x_eval"].shape[0])
        est_bytes = self._eval_peak_bytes()
        if est_bytes > 2 << 30:
            import warnings
            emit_event("eval_memory_large", {
                "eval_peak_bytes": est_bytes,
                "n_eval_nodes": n_eval_nodes,
                "n_eval_samples": n_samples,
                "sampling_eval": self.sampling_eval,
                "simulator": type(self).__name__,
            })
            warnings.warn(
                f"global evaluation materializes ~[{n_eval_nodes} nodes x "
                f"{n_samples} samples] intermediates "
                f"(~{est_bytes / 2**30:.1f} GB) — likely OOM on one chip. "
                "Use sampling_eval= to evaluate a node subset and/or a "
                "smaller eval split.")

    def memory_budget(self) -> dict:
        """Construction-time device-memory budget (bytes) for the big state
        terms, before any compile is paid (round-4 verdict #3: the 50k-node
        on-TPU crash needed a paper budget — this is it, callable).

        Covers the N-scaled persistent state (model+optimizer, the [D, N]
        params-history ring + age ring, mailbox/reply metadata, stacked
        data, variant aux state — CacheNeigh's parked [N, max_deg] model
        slots are ~degree x the model term and would dominate) and the
        transient eval peak (the term :meth:`_warn_if_eval_memory_large`
        warns about). Excludes XLA compilation workspace and fusion
        temporaries — the budget is a floor, not a ceiling, but at the
        scales where it is small (50k nodes => ~0.2 GB) a crash is NOT
        memory, and at the scales where a term explodes the offender is
        named. ``bench.py --scale`` prints it in the phase stamps so a
        dead run's last words include the expected footprint.
        """
        n = self.n_nodes
        leaf_bytes = lambda tree: sum(
            int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
            for l in jax.tree_util.tree_leaves(tree))
        # Build a shape-only model to count params+opt without device work.
        st = jax.eval_shape(self.handler.init, jax.random.PRNGKey(0))
        per_node_model = leaf_bytes(st)
        D = self._history_depth(self._model_size(jax.tree.map(
            lambda l: jax.ShapeDtypeStruct((1,) + l.shape, l.dtype),
            st.params)))
        # The ring stores snapshots in the history_dtype wire format; the
        # int8 sidecar (one f32 scale per (round-slot, node, leaf)) is part
        # of the ring's footprint and included in its term (and reported
        # separately under a non-``_bytes`` key so the total doesn't count
        # it twice).
        n_scalars, n_leaves = self._history_param_counts()
        sidecar = (4 * D * n * n_leaves
                   if self.history_dtype == "int8" else 0)
        if self.history_dtype == "float32":
            # Identity storage: the ring carries the params' OWN dtypes
            # (which need not be fp32 for exotic models).
            ring_bytes = D * n * leaf_bytes(st.params)
        else:
            ring_bytes = D * n * n_scalars * self._wire_itemsize() + sidecar
        stacked = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct((n,) + l.shape, l.dtype), st)
        try:
            aux_b = leaf_bytes(jax.eval_shape(
                self._init_aux, stacked, jax.random.PRNGKey(0)))
        except Exception:  # a variant's aux init may resist tracing
            aux_b = None
        ages = st.n_updates
        mailbox_b = 4 * 4 * D * n * self.K   # 4 int32 fields
        reply_b = 4 * 4 * D * n * self.Kr
        data_b = leaf_bytes(self.data)
        eval_b = self._eval_peak_bytes()
        out = {
            "model_and_opt_bytes": per_node_model * n,
            "history_ring_bytes": ring_bytes,
            "history_ring_sidecar": sidecar,
            "history_dtype": self.history_dtype,
            "history_ages_bytes": D * n * leaf_bytes(ages),
            "history_depth": D,
            "aux_bytes": aux_b,
            "mailbox_bytes": mailbox_b,
            "reply_box_bytes": reply_b,
            "data_bytes": data_b,
            "eval_peak_bytes": eval_b,
        }
        out["total_bytes"] = sum(v for k, v in out.items()
                                 if k.endswith("_bytes") and v is not None)
        if self.cohort is not None:
            # Cohort-aware accounting: the keys above price the ACTIVE
            # [C]-shaped round (n == C here); the pool prices the nominal
            # population's durable state, host-resident — deliberately
            # named without the ``_bytes`` suffix so the device total
            # stays the active-round budget. ``materialized_prediction``
            # is what the N-scaled active terms would cost fully
            # materialized (the ladder's pool-vs-materialized column).
            from .cohort import pool_bytes
            n_scaled = sum(
                out[k] or 0 for k in
                ("model_and_opt_bytes", "history_ring_bytes",
                 "history_ages_bytes", "aux_bytes", "mailbox_bytes",
                 "reply_box_bytes") if out.get(k) is not None)
            out["cohort_size"] = self.n_nodes
            out["nominal_n"] = self.nominal_n
            out["cohort_pool_resident"] = pool_bytes(self)
            out["cohort_active_total"] = out["total_bytes"]
            out["cohort_materialized_prediction"] = (
                int(n_scaled * (self.nominal_n / max(self.n_nodes, 1)))
                + (out.get("data_bytes") or 0)
                + (out.get("eval_peak_bytes") or 0))
            out["cohort_pool_disk_backed"] = bool(self.cohort.pool_dir)
        return out

    def check_memory_budget(self, limit_bytes: Optional[int] = None
                            ) -> dict:
        """Predict-and-refuse: raise :class:`MemoryBudgetExceeded` when
        :meth:`memory_budget`'s device total will not fit, BEFORE any
        compile or launch is paid. Returns the budget dict when it fits
        (or when no limit is discoverable).

        Limit resolution, first hit wins: the explicit ``limit_bytes``
        argument; the ``GOSSIPY_TPU_MEMORY_LIMIT`` env var (bytes — the
        CI/test hook); the default device's own
        ``memory_stats()["bytes_limit"]`` (TPU/GPU; CPU backends report
        none and the check passes). The budget total is a floor (no XLA
        workspace/fusion temporaries), so refusal is conservative:
        anything refused here was certainly going to die louder later.
        """
        budget = self.memory_budget()
        limit = limit_bytes
        if limit is None:
            env = os.environ.get("GOSSIPY_TPU_MEMORY_LIMIT")
            if env:
                limit = int(float(env))
        if limit is None:
            try:
                stats = jax.devices()[0].memory_stats()
                limit = (stats or {}).get("bytes_limit")
            except Exception:
                limit = None
        if limit is None:
            return budget
        predicted = int(budget["total_bytes"])
        if predicted > int(limit):
            terms = {k: v for k, v in budget.items()
                     if k.endswith("_bytes") and k != "total_bytes"
                     and v is not None}
            dominant = max(terms, key=terms.get) if terms else "total_bytes"
            raise MemoryBudgetExceeded(predicted, int(limit), dominant,
                                       budget)
        return budget

    def _local_data(self):
        return (self.data["xtr"], self.data["ytr"], self.data["mtr"])

    # -- history wire format -------------------------------------------------

    def _wire_itemsize(self) -> int:
        """Bytes per stored history scalar under the configured format."""
        return {"float32": 4, "bfloat16": 2, "int8": 1}[self.history_dtype]

    def _encode_history_rows(self, params):
        """Encode a params pytree (leaves [..., N, *leaf]) into the history
        wire format. Returns ``(stored, scales)``: ``stored`` has the same
        treedef with wire-dtype leaves; ``scales`` is the matching pytree of
        per-row f32 scales for int8 (leaf shape = leaf.shape minus the
        trailing feature dims), or ``()`` otherwise. float32 is the
        identity — the default path stays bit-identical to storing params
        directly."""
        if self.history_dtype == "float32":
            return params, ()
        if self.history_dtype == "bfloat16":
            return jax.tree.map(lambda l: l.astype(jnp.bfloat16), params), ()

        # int8: symmetric per-(node-row, leaf) scale over the trailing
        # (feature) axes. A leaf arrives as [N, *feat] from _snapshot or
        # [N, S, *feat]... — the convention here is ONE leading row axis:
        # callers reshape/park per row, so reduce over axes >= 1.
        def amax_scale(l):
            red = tuple(range(1, l.ndim))
            amax = jnp.max(jnp.abs(l), axis=red) if red else jnp.abs(l)
            # Zero rows (fresh zero-init leaves) get scale 1: q = 0 either
            # way, and the dequant multiply stays finite.
            return jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)

        def quant(l, s):
            sb = s.reshape(s.shape + (1,) * (l.ndim - s.ndim))
            q = jnp.round(l.astype(jnp.float32) / sb)
            return jnp.clip(q, -127, 127).astype(jnp.int8)

        scales = jax.tree.map(amax_scale, params)
        return jax.tree.map(quant, params, scales), scales

    def _decode_history_rows(self, stored, scales):
        """Inverse of :meth:`_encode_history_rows` (fp32 out). ``scales``
        leaf shapes must broadcast against the stored leaves' leading
        axes."""
        if self.history_dtype == "float32":
            return stored
        if self.history_dtype == "bfloat16":
            return jax.tree.map(lambda l: l.astype(jnp.float32), stored)
        return jax.tree.map(
            lambda q, s: q.astype(jnp.float32)
            * s.reshape(s.shape + (1,) * (q.ndim - s.ndim)),
            stored, scales)

    def _wire_roundtrip(self, params):
        """Encode-then-decode a params pytree through the wire format: what
        a RECEIVER sees of these params after transport. Identity for fp32;
        the quantization noise model for bf16/int8 (All2All's broadcast
        merge uses this — it has no history gather to decode through)."""
        stored, scales = self._encode_history_rows(params)
        return self._decode_history_rows(stored, scales)

    def _history_param_counts(self) -> tuple[int, int]:
        """(per-node param scalar count, leaf count) from a shape-only
        handler init — shared by :meth:`memory_budget` and
        :meth:`wire_bytes_per_message` so the two cannot drift."""
        st = jax.eval_shape(self.handler.init, jax.random.PRNGKey(0))
        leaves = jax.tree_util.tree_leaves(st.params)
        return (sum(int(np.prod(l.shape)) for l in leaves), len(leaves))

    def wire_bytes_per_message(self) -> int:
        """Bytes one model-carrying message moves under the configured wire
        format: the quantized payload plus, for int8, one f32 scale per
        parameter leaf. The report's ``size`` column stays in scalars (the
        reference's unit); this is the bytes view of the same traffic —
        ``bench.py`` stamps ``sent/round * wire_bytes_per_message()`` as
        bytes-moved-per-round."""
        n_scalars, n_leaves = self._history_param_counts()
        sidecar = 4 * n_leaves if self.history_dtype == "int8" else 0
        return n_scalars * self._wire_itemsize() + sidecar

    def _model_size(self, params) -> int:
        if self._message_size is not None:
            return self._message_size
        if hasattr(self.handler, "get_size"):
            return int(self.handler.get_size())
        return sum(int(np.prod(l.shape[1:]))  # leading axis = node
                   for l in jax.tree_util.tree_leaves(params))

    def _history_depth(self, size: int) -> int:
        """Ring depth: enough rounds to cover the worst-case in-flight delay
        for a message of ``size`` scalars (including the worst scheduled
        chaos delay spike, whose scale multiplies every sampled delay)."""
        max_d = self.delay.max_delay(size)
        if self.chaos is not None:
            import math
            max_d = int(math.ceil(max_d * self.chaos.max_delay_scale()))
        # send offset <= delta-1, plus delay, plus one reply delay leg.
        return max(2, (self.delta - 1 + 2 * max_d) // self.delta + 2)

    def init_nodes(self, key: jax.Array, local_train: bool = True,
                   common_init: bool = False) -> SimState:
        """Initialize every node's model (+ one local pre-training pass, the
        reference's ``init_model`` behavior, node.py:82-94).

        ``common_init=True`` gives every node the SAME initial weights (the
        FedAvg-standard choice; the reference re-rolls ``init_weights`` per
        node, node.py:92). For deep models this matters: averaging
        differently-initialized CNNs cancels co-adapted features
        (permutation symmetry), and with small per-node shards local
        training never recovers — a 100-node CIFAR run stays at chance
        without it. The local pre-training pass still diversifies nodes.
        """
        if self.cohort is not None:
            raise ValueError(
                "cohort mode keeps the population in a resident pool — "
                "use init_cohort_pool() and start(pool, ...) instead of "
                "init_nodes()")
        n = self.n_nodes
        self._health_carry = None  # fresh population, fresh sentinel EMA
        k_init, k_phase, k_up = jax.random.split(key, 3)
        if common_init:
            one = self.handler.init(k_init)
            model = jax.tree.map(
                lambda l: jnp.broadcast_to(l[None], (n,) + l.shape), one)
        else:
            model = jax.vmap(self.handler.init)(jax.random.split(k_init, n))
        if local_train:
            model = jax.jit(jax.vmap(self.handler.update))(
                model, self._local_data(), jax.random.split(k_up, n))
        if self.sync:
            phase = jax.random.randint(k_phase, (n,), 0, self.delta, dtype=jnp.int32)
        else:
            raw = self.delta + (self.delta / 10.0) * jax.random.normal(k_phase, (n,))
            phase = jnp.maximum(raw.astype(jnp.int32), 1)

        D = self._history_depth(self._model_size(model.params))
        stored, scales = self._encode_history_rows(model.params)
        bcast = lambda l: jnp.broadcast_to(l[None], (D,) + l.shape).copy()
        hist_p = jax.tree.map(bcast, stored)
        hist_s = (jax.tree.map(bcast, scales)
                  if self.history_dtype == "int8" else ())
        hist_a = jnp.broadcast_to(model.n_updates[None],
                                  (D,) + model.n_updates.shape).copy()
        return SimState(
            model=model,
            phase=phase,
            history_params=hist_p,
            history_ages=hist_a,
            mailbox=Mailbox.empty(D, n, self.K),
            reply_box=Mailbox.empty(D, n, self.Kr),
            round=jnp.int32(0),
            aux=self._init_aux(model, key),
            history_scale=hist_s,
        )

    def init_cohort_pool(self, key: jax.Array, common_init: bool = False,
                         local_train: bool = False,
                         block: Optional[int] = None):
        """Cohort-mode population init: the resident
        :class:`~gossipy_tpu.simulation.cohort.CohortPool` of nominal
        size N (host numpy, built in device blocks — see
        :func:`gossipy_tpu.simulation.cohort.init_cohort_pool` for the
        ``local_train`` default's bias note)."""
        if self.cohort is None:
            raise ValueError("init_cohort_pool requires cohort=; use "
                             "init_nodes() for materialized populations")
        from .cohort import init_cohort_pool
        return init_cohort_pool(self, key, common_init=common_init,
                                local_train=local_train, block=block)

    def _init_aux(self, model: ModelState, key: jax.Array):
        """Variant-specific per-node state (token balances, caches, ...)."""
        return ()

    # -- per-round pieces ---------------------------------------------------

    def _round_key(self, base_key: jax.Array, r: jax.Array, purpose: int):
        return jax.random.fold_in(jax.random.fold_in(base_key, r), purpose)

    def _fire_mask(self, state: SimState, r: jax.Array, f: int = 0):
        """Which nodes perform their ``f``-th send of this round + its time
        offset within the round.

        Sync: every node fires once at its fixed offset (node.py:111-125).
        Async: a node fires at EVERY multiple of its period inside the round
        window [r*delta, (r+1)*delta) (capped at ``max_fires_per_round``
        sub-fires). Note every async node fires at t=0 of round 0 — faithful
        to the reference, whose time loop starts at t=0 (simul.py:384-389)
        where ``t % period == 0`` holds for all nodes.
        """
        if self.sync:
            if f > 0:
                return jnp.zeros(self.n_nodes, dtype=bool), state.phase
            return jnp.ones(self.n_nodes, dtype=bool), state.phase
        period = state.phase
        lo = r * self.delta
        hi = (r + 1) * self.delta
        first = ((lo + period - 1) // period) * period  # first multiple >= lo
        t_f = first + f * period
        fires = t_f < hi
        return fires, jnp.clip(t_f - lo, 0, self.delta - 1).astype(jnp.int32)

    def _scatter_messages(self, box: Mailbox, active, dr, recv, sender_ids,
                          send_round, msg_type, extra, r, slots_cap):
        """Allocate slots and scatter message metadata into ``box``.

        Returns (box, n_overflow). Slot = existing occupancy of the target
        cell + rank among this batch's messages for the same cell.
        """
        D = box.sender.shape[0]
        n = box.sender.shape[1]
        b = (r + dr) % D
        cell_key = jnp.where(active, b * n + recv, jnp.int32(D * n + 7))
        rank = _rank_within_group(cell_key)
        occ = (box.sender >= 0).sum(axis=2)  # [D, N]
        slot = occ[b, jnp.clip(recv, 0, n - 1)] + rank
        ok = active & (slot < slots_cap)
        n_overflow = (active & (slot >= slots_cap)).sum()
        # Invalid writes get an out-of-range slot -> dropped by scatter mode.
        slot = jnp.where(ok, slot, slots_cap)
        recv_c = jnp.clip(recv, 0, n - 1)
        box = Mailbox(
            sender=box.sender.at[b, recv_c, slot].set(sender_ids, mode="drop"),
            send_round=box.send_round.at[b, recv_c, slot].set(send_round, mode="drop"),
            msg_type=box.msg_type.at[b, recv_c, slot].set(msg_type, mode="drop"),
            extra=box.extra.at[b, recv_c, slot].set(extra, mode="drop"),
        )
        return box, n_overflow

    def _send_extra(self, key: jax.Array, state: SimState) -> jax.Array:
        """Protocol-specific int32 payload per sender (overridden by node
        variants: partition ids, sample seeds, degrees...)."""
        return jnp.zeros(self.n_nodes, dtype=jnp.int32)

    def _select_peers(self, state: SimState, base_key, r) -> jax.Array:
        """One peer per node (overridden e.g. by PENS peer selection).
        With chaos partitions/churn scheduled, the draw runs over the
        round's alive-edge mask instead of the frozen adjacency. In
        cohort mode with ``peer_mode="induced"`` the draw runs over the
        cohort-local neighbor table riding ``state.aux`` (the induced
        subgraph is per-cohort DATA, not a trace constant)."""
        key = self._round_key(base_key, r, _K_PEER)
        if self.cohort is not None and self.cohort.peer_mode == "induced":
            from .cohort import induced_peers
            return induced_peers(self, state, key)
        if self.chaos is not None and self._chaos_edge_form is not None:
            return self._chaos_masked_peers(key, r)
        return self.topology.sample_peers(key)

    def _send_gate(self, state: SimState, active, peers, base_key, r):
        """Hook gating sends (token-account flow control, PENS selection
        bookkeeping). Returns the new active mask and (possibly updated)
        state."""
        return active, state

    def _pre_send(self, state: SimState, base_key, r) -> SimState:
        """Hook before the round snapshot (CacheNeigh merges its parked
        neighbor model here so the outgoing snapshot includes it)."""
        return state

    def _send_phase(self, state: SimState, base_key, r):
        n = self.n_nodes
        size = self._model_size(state.model.params)
        if self.protocol == AntiEntropyProtocol.PULL:
            size = 1  # PULL requests carry no model (core.py:163-164)
        msg_type = PROTO_TO_MSG[self.protocol]

        n_sent = jnp.int32(0)
        fails = self._fc_zeros()
        # Sub-fires: async nodes whose period fits multiple times in the
        # round window send once per multiple (all from the round-start
        # snapshot). F is 1 for sync simulations, so f=0 reproduces the
        # single-fire path with an unmodified PRNG stream.
        for f in range(self.F):
            def key_f(purpose):
                k = self._round_key(base_key, r, purpose)
                return jax.random.fold_in(k, f) if f > 0 else k

            # Peer-selection/gate hooks derive their own purposes from a
            # base key; sub-fires > 0 get a distinct base via _K_FIRE.
            fire_base = base_key if f == 0 else key_f(_K_FIRE)
            fires, offset = self._fire_mask(state, r, f)
            if self.chaos is not None:
                # A forced-offline node neither sends nor receives (a
                # crashed process does neither) — unlike the independent
                # online draw, which only gates receipt.
                fires = fires & ~self._chaos_forced_offline(r)
            peers = self._select_peers(state, fire_base, r)
            active = fires & (peers >= 0)
            active, state = self._send_gate(state, active, peers, fire_base, r)

            dropped = jax.random.bernoulli(
                key_f(_K_DROP), self._chaos_drop_prob(r), (n,))
            delays = self._chaos_scale_delays(
                self.delay.sample(key_f(_K_DELAY), (n,), size), r)
            dr = (offset + delays) // self.delta

            extra = self._send_extra(key_f(_K_EXTRA), state)

            n_sent += active.sum()
            fails = fails._replace(drop=fails.drop + (active & dropped).sum())
            live = active & ~dropped
            box, n_overflow = self._scatter_messages(
                state.mailbox, live, dr, peers, jnp.arange(n, dtype=jnp.int32),
                jnp.broadcast_to(r.astype(jnp.int32), (n,)),
                jnp.full((n,), int(msg_type), dtype=jnp.int32),
                extra, r, self.K)
            fails = fails._replace(overflow=fails.overflow + n_overflow)
            state = state._replace(mailbox=box)
        return state, n_sent, fails, n_sent * size

    def _gather_peer(self, state: SimState, send_round, sender):
        """Fetch the snapshot a message carries: history[send_round % D][sender],
        dequantized from the ring's wire format back to fp32 (the merge math
        never sees the storage dtype)."""
        D = state.history_ages.shape[0]
        b = send_round % D
        s = jnp.clip(sender, 0, self.n_nodes - 1)
        params = jax.tree.map(lambda h: h[b, s], state.history_params)
        if self.history_dtype != "float32":
            scales = (jax.tree.map(lambda sc: sc[b, s], state.history_scale)
                      if self.history_dtype == "int8" else ())
            params = self._decode_history_rows(params, scales)
        ages = state.history_ages[b, s]
        return PeerModel(params, ages)

    def _slot_live_count(self, valid) -> jax.Array:
        """The live-receiver count the compact/wide dispatch compares to
        the static capacity. Under a seed/tenant vmap
        (``_batch_axis_name`` set) the count is maximized across the batch
        axis so the resulting ``lax.cond`` predicate is batch-uniform —
        the cond stays a real cond (one branch executes) instead of being
        lowered to a both-branches select. Conservative per lane: a lane
        that fits takes the wide pass when a co-lane overflows, which is
        always correct (compaction never changes results)."""
        live = valid.sum()
        if self._batch_axis_name is not None:
            live = jax.lax.pmax(live, self._batch_axis_name)
        return live

    def _receive_slot_apply(self, state: SimState, send_round, sender, extra,
                            valid, call_key) -> SimState:
        """Process one mailbox slot: fetch the senders' snapshots and apply
        the handler's receive behavior (gather + blend, the compacted
        small-batch pass, or the fused pallas path when enabled)."""
        if self.fused_merge:
            return self._fused_receive(state, send_round, sender, valid,
                                       call_key)
        if self._compact_cap is not None:
            # Runtime dispatch: the compacted pass is only semantics-
            # preserving when every live receiver fits the static capacity;
            # an overflowing slot (typically slot 0) takes the full-width
            # pass. Both branches live in the compiled program once.
            return jax.lax.cond(
                self._slot_live_count(valid) <= self._compact_cap,
                lambda st: self._apply_receive_compact(
                    st, send_round, sender, extra, valid, call_key),
                lambda st: self._apply_receive_wide(
                    st, send_round, sender, extra, valid, call_key),
                state)
        return self._apply_receive_wide(state, send_round, sender, extra,
                                        valid, call_key)

    def _apply_receive_wide(self, state: SimState, send_round, sender, extra,
                            valid, call_key) -> SimState:
        peer = self._gather_peer(state, send_round, sender)
        return self._apply_receive(state, peer, extra, valid, call_key)

    def _apply_receive_compact(self, state: SimState, send_round, sender,
                               extra, valid, call_key) -> SimState:
        """The base receive pipeline over a gathered [cap] batch of the
        slot's live receivers instead of the full masked [N] population.

        Per-node PRNG streams are preserved (the same ``split(key, N)``
        table is built and the live rows gathered), so a run produces the
        same trajectories with compaction on or off up to fp layout. Only
        valid behind the ``valid.sum() <= cap`` cond in
        :meth:`_receive_slot_apply`: the stable valid-first argsort then
        guarantees the first ``cap`` positions contain every live receiver.
        """
        cap = self._compact_cap
        n = self.n_nodes
        order = jnp.argsort(jnp.where(valid, 0, 1), stable=True)
        idx = jax.lax.slice_in_dim(order, 0, cap)
        sub_valid = valid[idx]
        peer = self._gather_peer(state, send_round[idx], sender[idx])
        take = lambda l: l[idx] if getattr(l, "ndim", 0) else l
        sub_model = jax.tree.map(take, state.model)
        data = jax.tree.map(take, self._local_data())
        keys = jax.random.split(call_key, n)[idx]
        extra_arg = self._decode_extra(extra)
        if extra_arg is not None:
            extra_arg = jax.tree.map(take, extra_arg)
        new_sub = self._receive_rows(sub_model, peer, data, keys, extra_arg,
                                     idx)
        new_sub = select_nodes(sub_valid, new_sub, sub_model)
        model = jax.tree.map(
            lambda full, part: (full.at[idx].set(part)
                                if getattr(full, "ndim", 0) else full),
            state.model, new_sub)
        return state._replace(model=model)

    def _receive_rows(self, models: ModelState, peer: PeerModel, data,
                      keys, extra_arg, node_ids) -> ModelState:
        """The per-row receive computation (one mailbox slot's live rows).

        Every argument is ROW-ALIGNED: the full population for the wide
        pass, a gathered subset for the compacted pass; ``node_ids`` maps
        rows back to node indices (``arange(N)`` when wide). Variants that
        customize receive behavior should override THIS (not
        ``_apply_receive``) to stay compaction-compatible — the contract
        is: read per-node state by ``node_ids`` (never positionally by
        row), and derive any extra randomness from the per-row ``keys``
        (e.g. ``fold_in(keys[i], tag)``), never from a population-shaped
        draw.
        """
        with jax.named_scope(PHASE_TRAIN):
            return jax.vmap(
                self.handler.call,
                in_axes=(0, 0, 0, 0, 0 if extra_arg is not None else None)
                )(models, peer, data, keys, extra_arg)

    def _apply_receive(self, state: SimState, peer: PeerModel, extra, valid,
                       call_key) -> SimState:
        """Full-width ``_receive_rows`` masked by ``valid`` (one slot)."""
        data = self._local_data()
        keys = jax.random.split(call_key, self.n_nodes)
        extra_arg = self._decode_extra(extra)
        new_model = self._receive_rows(state.model, peer, data, keys,
                                       extra_arg,
                                       jnp.arange(self.n_nodes))
        return state._replace(model=select_nodes(valid, new_model, state.model))

    def _fused_receive(self, state: SimState, send_round, sender, valid,
                       call_key) -> SimState:
        """MERGE_UPDATE via the pallas fused gather+merge kernel: the peer
        snapshot is blended into the receiver's params during the gather
        itself (one HBM pass; see gossipy_tpu/ops/merge.py), then the
        standard vmapped local update runs. Produces the same results as the
        unfused path up to fp reassociation (same PRNG streams)."""
        from ..ops import gather_merge_pytree
        n = self.n_nodes
        D = state.history_ages.shape[0]
        s = jnp.clip(sender, 0, n - 1)
        flat_idx = ((send_round % D) * n + s).astype(jnp.int32)
        # The handler DECLARED this blend coefficient (construction
        # asserts merge_peer_weight alongside uniform_avg_merge) — 0.5 for
        # the uniform average, never a silent kernel-side default.
        wp = float(self.handler.merge_peer_weight)
        w_peer = jnp.where(valid, wp, 0.0).astype(jnp.float32)
        w_self = 1.0 - w_peer
        # Quantized rings dequantize INSIDE the kernel (bf16: widen the DMA'd
        # block; int8: scalar-prefetched per-row scales) — the fp32 peer copy
        # still never materializes in HBM.
        scales = (state.history_scale if self.history_dtype == "int8"
                  else None)
        merged_params = gather_merge_pytree(
            state.model.params, state.history_params, flat_idx, w_self,
            w_peer, scales=scales)
        peer_ages = state.history_ages[send_round % D, s]
        merged = ModelState(merged_params, state.model.opt_state,
                            jnp.maximum(state.model.n_updates, peer_ages))
        keys = jax.random.split(call_key, n)
        with jax.named_scope(PHASE_TRAIN):
            updated = jax.vmap(self.handler.update)(merged, self._local_data(),
                                                    keys)
        return state._replace(model=select_nodes(valid, updated, state.model))

    # -- single-pass fused deliver (fused_merge="multi") --------------------

    def _fused_multi_tables(self, state: SimState, sr_t, sender_t, apply_t):
        """The [rows, K] kernel tables for one mailbox cell: flat ring
        indices, per-slot blend weights (``(1, 0)`` for empty slots — the
        kernel hard-masks zero-weight slots), and the peer ages. ``rows``
        is N for the wide pass, the gathered [cap] subset under
        compaction (the ring index space stays the full [D*N])."""
        n = self.n_nodes
        D = state.history_ages.shape[0]
        s = jnp.clip(sender_t, 0, n - 1)
        flat_idx = ((sr_t % D) * n + s).astype(jnp.int32)
        wp = float(self.handler.merge_peer_weight)
        w_peer = jnp.where(apply_t, wp, 0.0).astype(jnp.float32)
        w_self = 1.0 - w_peer
        peer_ages = state.history_ages[sr_t % D, s]
        return flat_idx, w_self, w_peer, peer_ages

    def _fused_slot_keys(self, base_key, r, purposes, apply_t):
        """Per-node key for the ONE fused update: build the same per-slot
        ``split(key, N)`` tables the per-slot path draws from, then select
        each node's FIRST live slot's key — so wherever fan-in <= 1 the
        update consumes bit-identical PRNG streams to the per-slot path."""
        n = self.n_nodes
        tabs = jnp.stack([
            jax.random.split(self._round_key(base_key, r, p), n)
            for p in purposes])
        first_k = jnp.argmax(apply_t, axis=1)
        return tabs[first_k, jnp.arange(n)]

    def _fused_multi_merge_update(self, model: ModelState, history_params,
                                  history_scale, flat_idx, w_self, w_peer,
                                  peer_ages, apply_t, keys, row_valid,
                                  data) -> ModelState:
        """One kernel launch + one vmapped update over ``rows`` receivers:
        the compound left-to-right K-slot blend, age = max over the live
        peers, then ``handler.update`` once per receiver with >= 1 live
        message."""
        scales = history_scale if self.history_dtype == "int8" else None
        if self.mesh is not None:
            from ..parallel.collectives import sharded_gather_merge_multi
            merged_params = sharded_gather_merge_multi(
                model.params, history_params, flat_idx, w_self, w_peer,
                self.mesh, scales=scales, axis_name=self._fused_ring_axis)
        else:
            from ..ops import gather_merge_multi_pytree
            merged_params = gather_merge_multi_pytree(
                model.params, history_params, flat_idx, w_self, w_peer,
                scales=scales)
        ages = jnp.maximum(model.n_updates,
                           jnp.where(apply_t, peer_ages, 0).max(axis=1))
        merged = ModelState(merged_params, model.opt_state, ages)
        with jax.named_scope(PHASE_TRAIN):
            updated = jax.vmap(self.handler.update)(merged, data, keys)
        return select_nodes(row_valid, updated, model)

    def _fused_multi_apply(self, state: SimState, sr_t, sender_t, apply_t,
                           keys, any_msg) -> SimState:
        flat_idx, w_self, w_peer, peer_ages = self._fused_multi_tables(
            state, sr_t, sender_t, apply_t)
        model = self._fused_multi_merge_update(
            state.model, state.history_params, state.history_scale,
            flat_idx, w_self, w_peer, peer_ages, apply_t, keys, any_msg,
            self._local_data())
        return state._replace(model=model)

    def _fused_multi_apply_compact(self, state: SimState, sr_t, sender_t,
                                   apply_t, keys, any_msg) -> SimState:
        """The fused single pass over the [cap] gathered live receivers
        (same stable valid-first argsort + scatter-back contract as
        :meth:`_apply_receive_compact`; only reachable behind the
        ``live <= cap`` cond)."""
        cap = self._compact_cap
        order = jnp.argsort(jnp.where(any_msg, 0, 1), stable=True)
        idx = jax.lax.slice_in_dim(order, 0, cap)
        take = lambda l: l[idx] if getattr(l, "ndim", 0) else l
        flat_idx, w_self, w_peer, peer_ages = self._fused_multi_tables(
            state, sr_t[idx], sender_t[idx], apply_t[idx])
        sub_model = jax.tree.map(take, state.model)
        new_sub = self._fused_multi_merge_update(
            sub_model, state.history_params, state.history_scale, flat_idx,
            w_self, w_peer, peer_ages, apply_t[idx], keys[idx],
            any_msg[idx], jax.tree.map(take, self._local_data()))
        model = jax.tree.map(
            lambda full, part: (full.at[idx].set(part)
                                if getattr(full, "ndim", 0) else full),
            state.model, new_sub)
        return state._replace(model=model)

    def _fused_multi_dispatch(self, state: SimState, sr_t, sender_t,
                              apply_t, keys):
        """Runtime wide/compact dispatch around the single fused pass.
        Returns ``(state, n_compact, n_wide)`` where the path counters
        attribute the cell's occupied-slot count to whichever branch ran
        (the per-slot loop's per-slot tallies, summed)."""
        any_msg = apply_t.any(axis=1)
        has_any = any_msg.any()
        occ_slots = apply_t.any(axis=0).sum().astype(jnp.int32)

        def deliver(st):
            if self._compact_cap is None:
                return self._fused_multi_apply(st, sr_t, sender_t, apply_t,
                                               keys, any_msg)
            return jax.lax.cond(
                self._slot_live_count(any_msg) <= self._compact_cap,
                lambda s2: self._fused_multi_apply_compact(
                    s2, sr_t, sender_t, apply_t, keys, any_msg),
                lambda s2: self._fused_multi_apply(
                    s2, sr_t, sender_t, apply_t, keys, any_msg),
                st)

        state = jax.lax.cond(has_any, deliver, lambda st: st, state)
        if self._compact_cap is None:
            return state, jnp.int32(0), \
                jnp.where(has_any, occ_slots, jnp.int32(0))
        took_compact = has_any & (
            self._slot_live_count(any_msg) <= self._compact_cap)
        return (state,
                jnp.where(took_compact, occ_slots, jnp.int32(0)),
                jnp.where(has_any & ~took_compact, occ_slots, jnp.int32(0)))

    def _fused_multi_probe(self, pa: "ProbeAccum", pre_state: SimState,
                           post_state: SimState, sr_t, sender_t, apply_t,
                           r) -> "ProbeAccum":
        """Per-slot probe accounting recomputed from the [N, K] tables:
        accepted counts and staleness fold slot-by-slot (bit-equal to the
        per-slot loop); the merge/train delta decomposition measures the
        COMPOUND merge (what this path actually applied), recomputed as a
        pure jnp probe so it adds no kernel launch."""
        def pbody(k, pa):
            return pa.record_slot(apply_t[:, k], r - sr_t[:, k])

        pa = jax.lax.fori_loop(0, sr_t.shape[1], pbody, pa)
        if not self._probe_delta_ok:
            return pa
        any_msg = apply_t.any(axis=1)

        def deltas():
            from ..ops.merge import gather_merge_multi_reference_pytree
            flat_idx, w_self, w_peer, _ = self._fused_multi_tables(
                pre_state, sr_t, sender_t, apply_t)
            scales = (pre_state.history_scale
                      if self.history_dtype == "int8" else None)
            merged = gather_merge_multi_reference_pytree(
                pre_state.model.params, pre_state.history_params, flat_idx,
                w_self, w_peer, scales=scales)
            merged_p = select_nodes(any_msg, merged, pre_state.model.params)
            return (sq_param_distance(merged_p, pre_state.model.params),
                    sq_param_distance(post_state.model.params, merged_p))

        m_sq, t_sq = jax.lax.cond(
            any_msg.any(), deltas,
            lambda: (jnp.float32(0), jnp.float32(0)))
        return pa._replace(merge_sq=pa.merge_sq + m_sq,
                           train_sq=pa.train_sq + t_sq)

    def _fused_deliver_all(self, state: SimState, base_key, r, online,
                           forced, b, size):
        """Single-pass fused deliver: hoist the cell's K-slot mailbox
        metadata into [N, K] tables, drain every slot with ONE multi-slot
        kernel launch + ONE vmapped ``handler.update``, and recompute the
        per-slot accounting (failure causes, accepted counts, staleness
        histogram, sentinel first-bad-slot, reply traffic) from the same
        tables.

        Semantics vs the per-slot paths: a receiver with m > 1 live
        messages applies the compound left-to-right blend of all m
        snapshots and trains ONCE (the per-slot paths interleave m
        merge+train passes). Rounds with fan-in <= 1 everywhere match the
        unfused path up to fp reassociation; the integer accounting is
        bit-equal regardless of fan-in (it depends only on the mailbox
        tables). Returns ``(state, fails, n_sent_replies,
        reply_size_total, n_compact, n_wide, pa, first_bad)``.
        """
        n = self.n_nodes
        box = state.mailbox
        sender_t = box.sender[b]
        sr_t = box.send_round[b]
        ty_t = box.msg_type[b]
        occupied_t = sender_t >= 0
        valid_t = occupied_t & online[:, None]
        carries_t = ((ty_t == MessageType.PUSH)
                     | (ty_t == MessageType.PUSH_PULL)
                     | (ty_t == MessageType.REPLY))
        apply_t = valid_t & carries_t

        fails = self._fc_zeros()
        if self.chaos is not None:
            fails = fails.add_chaos((occupied_t & forced[:, None]).sum())
            fails = fails._replace(
                offline=fails.offline
                + (occupied_t & ~forced[:, None] & ~online[:, None]).sum())
        else:
            fails = fails._replace(
                offline=fails.offline + (occupied_t & ~online[:, None]).sum())

        keys = self._fused_slot_keys(
            base_key, r, [_K_CALL * 101 + k for k in range(self.K)],
            apply_t)
        probes_on = self._probe_slots_on()
        pre_state = state if probes_on else None
        state, n_compact, n_wide = self._fused_multi_dispatch(
            state, sr_t, sender_t, apply_t, keys)

        pa = None
        if probes_on:
            pa = self._fused_multi_probe(self._probe_zero_accum(), pre_state,
                                         state, sr_t, sender_t, apply_t, r)
        first_bad = None
        if self._health_slots_on():
            # Blame resolution is phase-level here: a non-finite outcome
            # names the FIRST occupied slot (the compound pass has no
            # per-slot intermediate states to bisect). Clean rounds are
            # bit-equal to the per-slot accumulator (-1).
            occ_k = apply_t.any(axis=0)

            def _scan_bad():
                bad = nonfinite_total(state.model.params) > 0
                return jnp.where(bad, jnp.argmax(occ_k).astype(jnp.int32),
                                 jnp.int32(-1))

            first_bad = jax.lax.cond(occ_k.any(), _scan_bad,
                                     lambda: jnp.int32(-1))

        n_sent_replies = jnp.int32(0)
        reply_size_total = jnp.int32(0)
        if self._replies_possible():
            # Reply traffic is metadata-only (no model math), so the slot
            # loop survives as a pure scatter loop with the SAME key
            # purposes — the reply box contents stay bit-identical to the
            # per-slot path's.
            def rbody(k, carry):
                rbox, fails, nsr, rst = carry
                sender = jnp.take(sender_t, k, axis=1)
                ty = jnp.take(ty_t, k, axis=1)
                valid = jnp.take(valid_t, k, axis=1)
                wants_reply = (ty == MessageType.PULL) | \
                              (ty == MessageType.PUSH_PULL)
                reply_needed = valid & wants_reply
                rkey = self._round_key(base_key, r, _K_REPLY_DELAY * 101 + k)
                rdrop = jax.random.bernoulli(
                    self._round_key(base_key, r, _K_REPLY_DROP * 101 + k),
                    self._chaos_drop_prob(r), (n,))
                rdelay = self._chaos_scale_delays(
                    self.delay.sample(rkey, (n,), size), r)
                rdr = rdelay // self.delta
                nsr += reply_needed.sum()
                rst += reply_needed.sum() * size
                fails = fails._replace(
                    drop=fails.drop + (reply_needed & rdrop).sum())
                live = reply_needed & ~rdrop
                rbox, n_overflow = self._scatter_messages(
                    rbox, live, rdr, sender, jnp.arange(n, dtype=jnp.int32),
                    jnp.broadcast_to(r.astype(jnp.int32), (n,)),
                    jnp.full((n,), int(MessageType.REPLY), dtype=jnp.int32),
                    self._reply_extra(
                        self._round_key(base_key, r,
                                        (_K_EXTRA + 31) * 101 + k),
                        state), r, self.Kr)
                fails = fails._replace(
                    overflow=fails.overflow + n_overflow)
                return rbox, fails, nsr, rst

            rbox, fails, n_sent_replies, reply_size_total = \
                jax.lax.fori_loop(
                    0, self.K, rbody,
                    (state.reply_box, fails, n_sent_replies,
                     reply_size_total))
            state = state._replace(reply_box=rbox)

        return (state, fails, n_sent_replies, reply_size_total, n_compact,
                n_wide, pa, first_bad)

    def _decode_extra(self, extra: jax.Array):
        """Map the int32 wire field to the handler's ``extra`` argument.
        Base protocol carries nothing."""
        return None

    def _delivery_path_counts(self, apply_mask):
        """(compact, wide) 0/1 indicators for one occupied slot's delivery,
        mirroring :meth:`_receive_slot_apply`'s runtime dispatch predicate
        exactly (the cond itself cannot thread a counter out, so the
        indicator is recomputed from the same inputs)."""
        occupied_slot = apply_mask.any()
        if self._compact_cap is None:
            return jnp.int32(0), occupied_slot.astype(jnp.int32)
        took_compact = occupied_slot & \
            (self._slot_live_count(apply_mask) <= self._compact_cap)
        return (took_compact.astype(jnp.int32),
                (occupied_slot & ~took_compact).astype(jnp.int32))

    # -- sentinels (opt-in; see telemetry.health) ---------------------------

    def _health_slots_on(self) -> bool:
        """Static: whether the deliver slot loop carries the sentinel
        first-bad-slot accumulator (non-finite sentinel enabled)."""
        return self.sentinels is not None and self.sentinels.nonfinite

    def _health_zero_carry(self) -> HealthCarry:
        return HealthCarry.zeros(self.n_nodes)

    def _health_round(self, hc: HealthCarry, pre_params,
                      state: SimState, stats: dict
                      ) -> tuple[HealthCarry, dict]:
        """One round's sentinel vitals (traced). Runs in the scan body
        AFTER ``_round``, over the round-start params kept from before
        the call — so every engine/variant round program is covered by
        the same code path."""
        return health_round_stats(
            self.sentinels, hc, pre_params, state.model.params,
            stats.get("local"), stats.get("global"),
            mailbox_hwm=stats.get("mailbox_hwm"))

    def _emit_trip_live(self, state: SimState, stats: dict) -> None:
        """Host notification the moment a sentinel trips (live runs): an
        unordered ``io_callback`` behind a ``lax.cond``, so healthy
        rounds pay nothing and a tripped round lands a ``sentinel_trip``
        telemetry event while the program is still running — a wedged
        run's last words include the verdict."""
        nf = stats.get("health_nonfinite_params")
        nf_total = nf.sum() if nf is not None else jnp.int32(0)

        def cb(rnd, nft):
            emit_event("sentinel_trip", {
                "round": int(rnd), "nonfinite_params": int(nft),
                "simulator": type(self).__name__})

        def fire():
            jax.experimental.io_callback(cb, None, state.round, nf_total,
                                         ordered=False)
            return jnp.int32(0)

        jax.lax.cond(stats["health_trip"] > 0, fire,
                     lambda: jnp.int32(0))

    # -- chaos (opt-in; see simulation.faults) ------------------------------

    def _fc_zeros(self) -> FailureCounts:
        """Zero failure counters matching this simulator's cause set: the
        fourth (``chaos``) counter leaf exists only when chaos is
        configured, so chaos-free scan carries keep the pre-feature
        pytree structure (and HLO)."""
        return FailureCounts.zeros(chaos_on=self.chaos is not None)

    def _chaos_t(self, r):
        """Clamped schedule row for the traced absolute round ``r``
        (rounds at/after the horizon read the trailing baseline row)."""
        return jnp.clip(r, 0, self.chaos_schedule.rows - 1)

    def _chaos_forced_offline(self, r) -> jax.Array:
        """[N] bool: nodes a scheduled outage forces fully offline at
        round ``r`` (no sends, no receives)."""
        return self.chaos_schedule.forced_offline[self._chaos_t(r)]

    def _chaos_drop_prob(self, r):
        """The round's message drop rate: the static base rate, or the
        schedule's per-round (possibly spiked) traced scalar."""
        if self.chaos is None:
            return self.drop_prob
        return self.chaos_schedule.drop_prob[self._chaos_t(r)]

    def _chaos_scale_delays(self, delays: jax.Array, r) -> jax.Array:
        """Apply the round's scheduled delay-scale spike (identity trace
        when chaos is off)."""
        if self.chaos is None:
            return delays
        s = self.chaos_schedule.delay_scale[self._chaos_t(r)]
        return jnp.floor(delays.astype(jnp.float32) * s).astype(jnp.int32)

    def _chaos_masked_peers(self, key: jax.Array, r) -> jax.Array:
        """Uniform peer draw over the round's ALIVE adjacency (base
        adjacency AND the scheduled partition/churn edge mask). Dense
        topologies mask the [N, N] categorical; sparse ones draw over
        the padded neighbor-slot table with the O(E) per-edge mask
        gathered for this round. Nodes whose every edge is dead get peer
        -1 (their send is skipped, like isolated nodes)."""
        sched = self.chaos_schedule
        m = sched.mask_idx[self._chaos_t(r)]
        if self._chaos_edge_form == "dense":
            adj = self.topology.adjacency_dev & sched.edge_masks[m]
            return sample_peers(key, adj)
        nbr = self._chaos_nbr_table
        alive = sched.slot_masks[m] & (nbr >= 0)
        logits = jnp.where(alive, 0.0, -jnp.inf)
        slot = jax.random.categorical(key, logits, axis=-1)
        has = alive.any(axis=-1)
        peers = nbr[jnp.arange(self.n_nodes),
                    jnp.clip(slot, 0, nbr.shape[1] - 1)]
        return jnp.where(has, peers, -1).astype(jnp.int32)

    def _chaos_probes_on(self) -> bool:
        """Static: whether the round emits the partition-recovery vitals
        (chaos scheduled AND consensus probes enabled — the gap/mixing
        math is consensus-style)."""
        return (self.chaos is not None and self.probes is not None
                and self.probes.consensus)

    def _chaos_stats(self, state: SimState, r) -> dict:
        comp = self.chaos_schedule.component_id[self._chaos_t(r)]
        return chaos_round_stats(state.model.params, comp,
                                 self._chaos_ncomp)

    # -- probes (opt-in; see telemetry.probes) ------------------------------

    def _probe_slots_on(self) -> bool:
        """Static: whether the deliver/reply slot loops carry a probe
        accumulator (staleness or mixing probes enabled)."""
        return self.probes is not None and (self.probes.staleness
                                            or self.probes.mixing)

    def _probe_zero_accum(self) -> ProbeAccum:
        return ProbeAccum.zeros(self.n_nodes,
                                self.probes.staleness_buckets)

    def _probe_slot_update(self, pa: ProbeAccum, state: SimState,
                           pre_model: ModelState, send_round, sender, extra,
                           apply_mask, r) -> ProbeAccum:
        """Fold one slot's accepted merges into the probe accumulator:
        staleness/counts always; the merge-vs-train delta decomposition
        when it is exact for this simulator (``_probe_delta_ok``). The
        deltas recompute the handler's merge as a PURE probe over the same
        peer gather — deterministic, so it equals what ``handler.call``
        merged regardless of which delivery path (wide/compact/fused) ran.
        ``state`` is the post-receive state (its history ring — the gather
        source — is not touched by receives); ``pre_model`` the slot's
        pre-receive model."""
        pa = pa.record_slot(apply_mask, r - send_round)
        if not self._probe_delta_ok:
            return pa

        def deltas():
            peer = self._gather_peer(state, send_round, sender)
            extra_arg = self._decode_extra(extra)
            merged = jax.vmap(
                self.handler.merge,
                in_axes=(0, 0, 0 if extra_arg is not None else None))(
                pre_model, peer, extra_arg)
            merged_p = select_nodes(apply_mask, merged.params,
                                    pre_model.params)
            return (sq_param_distance(merged_p, pre_model.params),
                    sq_param_distance(state.model.params, merged_p))

        m_sq, t_sq = jax.lax.cond(
            apply_mask.any(), deltas,
            lambda: (jnp.float32(0), jnp.float32(0)))
        return pa._replace(merge_sq=pa.merge_sq + m_sq,
                           train_sq=pa.train_sq + t_sq)

    def _probe_round_stats(self, state: SimState,
                           pa: Optional[ProbeAccum]) -> dict:
        """The round's ``probe_*`` stats entries (traced), from the final
        round state and the slot-loop accumulator."""
        cfg = self.probes
        out: dict = {}
        if cfg.consensus:
            cm, cx, cl = consensus_stats(state.model.params)
            out["probe_consensus_mean"] = cm
            out["probe_consensus_max"] = cx
            out["probe_consensus_per_layer"] = cl
        if pa is not None:
            out.update(probe_stats_from_accum(cfg, pa,
                                              self._probe_delta_ok))
        return out

    def _probe_expected_fanin(self) -> np.ndarray:
        """Host-side [N] expected ACCEPTED merges per node per round, the
        comparison baseline for ``probe_accepted_per_node``: the
        topology's expected fan-in thinned by the drop and online rates
        (both gate acceptance). Variants with different traffic shapes
        (broadcast mixing) override."""
        return (self._lam_vector() * (1.0 - self.drop_prob)
                * self.online_prob)

    def _probe_layer_names(self) -> list[str]:
        """Leaf names matching ``probe_consensus_per_layer`` columns
        (shape-only handler init; host-side)."""
        st = jax.eval_shape(self.handler.init, jax.random.PRNGKey(0))
        return param_layer_names(st.params)

    def _deliver_phase(self, state: SimState, base_key, r):
        n = self.n_nodes
        D = state.history_ages.shape[0]
        b = r % D
        online = jax.random.bernoulli(
            self._round_key(base_key, r, _K_ONLINE), self.online_prob, (n,))
        if self.chaos is not None:
            forced = self._chaos_forced_offline(r)
            online = online & ~forced
        size = self._model_size(state.model.params)
        # Mailbox occupancy high-water mark of the cell being drained: the
        # fullest receiver's slot count this round (a per-round headroom
        # gauge against self.K — the traced counterpart of the
        # construction-time undersized warning).
        hwm = (state.mailbox.sender[b] >= 0).sum(axis=1).max() \
            .astype(jnp.int32)

        probes_on = self._probe_slots_on()
        health_on = self._health_slots_on()

        if self.fused_merge == "multi":
            # Single-pass fused deliver: no slot loop at all — one kernel
            # launch + one vmapped update drains every slot (the K full
            # [N, F] params read+write round-trips of the per-slot paths
            # collapse to one).
            state, fails, n_sent_replies, reply_size_total, n_compact, \
                n_wide, pa, first_bad = self._fused_deliver_all(
                    state, base_key, r, online,
                    forced if self.chaos is not None else None, b, size)
            state = state._replace(mailbox=state.mailbox.clear_cell(b))
            state, ex_sent, ex_fails, ex_size = \
                self._post_deliver(state, base_key, r)
            diag = {"mailbox_hwm": hwm, "compact_slots": n_compact,
                    "wide_slots": n_wide}
            if probes_on:
                diag["probe_accum"] = pa
            if health_on:
                diag["first_bad_slot"] = first_bad
            return state, n_sent_replies + ex_sent, fails + ex_fails, \
                reply_size_total + ex_size, diag

        # One fori_loop iteration per mailbox slot: the compiled program
        # contains ONE copy of the merge+train graph regardless of K (an
        # unrolled loop multiplies HLO size and compile time by K — minutes
        # for CNN configs). Slot index k is TRACED: it feeds fold_in key
        # derivation, dynamic slot reads, and the _post_receive_slot hook —
        # subclass hooks must treat k as an array, not a Python int.

        def slot_body(k, carry):
            state, fails, n_sent_replies, reply_size_total, \
                n_compact, n_wide = carry[:6]
            tail = list(carry[6:])
            pa = tail.pop(0) if probes_on else None
            first_bad = tail.pop(0) if health_on else None
            sender = jnp.take(state.mailbox.sender[b], k, axis=1)
            sr = jnp.take(state.mailbox.send_round[b], k, axis=1)
            ty = jnp.take(state.mailbox.msg_type[b], k, axis=1)
            extra = jnp.take(state.mailbox.extra[b], k, axis=1)
            occupied = sender >= 0
            valid = occupied & online
            if self.chaos is not None:
                # Forced-offline receivers get the scheduled-fault cause;
                # the random availability draw keeps "offline". Mutually
                # exclusive per message, so the cause sum stays exact.
                fails = fails.add_chaos((occupied & forced).sum())
                fails = fails._replace(
                    offline=fails.offline
                    + (occupied & ~forced & ~online).sum())
            else:
                fails = fails._replace(
                    offline=fails.offline + (occupied & ~online).sum())

            carries_model = (ty == MessageType.PUSH) | \
                            (ty == MessageType.PUSH_PULL) | \
                            (ty == MessageType.REPLY)
            apply_mask = valid & carries_model
            call_key = self._round_key(base_key, r, _K_CALL * 101 + k)
            dc, dw = self._delivery_path_counts(apply_mask)
            n_compact += dc
            n_wide += dw
            if probes_on:
                pre_model = state.model
            # Higher slots are empty most rounds (at most ~1 push per
            # receiver per round in the base protocol); a cond lets the
            # compiled program skip the whole merge+train pass for an
            # unoccupied slot at runtime instead of masking it out.
            state = jax.lax.cond(
                apply_mask.any(),
                lambda st: self._receive_slot_apply(st, sr, sender, extra,
                                                    apply_mask, call_key),
                lambda st: st,
                state)
            if probes_on:
                pa = self._probe_slot_update(pa, state, pre_model, sr,
                                             sender, extra, apply_mask, r)
            if health_on:
                # Sentinel accumulator: the first slot whose delivery left
                # a non-finite value in the model params (-1 = clean), so
                # a post-mortem can name the offending mailbox slot. The
                # isfinite reduction runs behind a cond — only for slots
                # that actually delivered something while no earlier slot
                # has tripped — so the common all-clean round pays it for
                # ~1 occupied slot, not all K.
                def _scan_bad(fb):
                    bad = nonfinite_total(state.model.params) > 0
                    return jnp.where(bad, jnp.asarray(k, jnp.int32), fb)

                first_bad = jax.lax.cond(
                    apply_mask.any() & (first_bad < 0),
                    _scan_bad, lambda fb: fb, first_bad)

            if self._replies_possible():
                wants_reply = (ty == MessageType.PULL) | (ty == MessageType.PUSH_PULL)
                reply_needed = valid & wants_reply
                rkey = self._round_key(base_key, r, _K_REPLY_DELAY * 101 + k)
                rdrop = jax.random.bernoulli(
                    self._round_key(base_key, r, _K_REPLY_DROP * 101 + k),
                    self._chaos_drop_prob(r), (n,))
                rdelay = self._chaos_scale_delays(
                    self.delay.sample(rkey, (n,), size), r)
                rdr = rdelay // self.delta
                n_sent_replies += reply_needed.sum()
                reply_size_total += reply_needed.sum() * size
                fails = fails._replace(
                    drop=fails.drop + (reply_needed & rdrop).sum())
                live = reply_needed & ~rdrop
                rbox, n_overflow = self._scatter_messages(
                    state.reply_box, live, rdr, sender,
                    jnp.arange(n, dtype=jnp.int32),
                    jnp.broadcast_to(r.astype(jnp.int32), (n,)),
                    jnp.full((n,), int(MessageType.REPLY), dtype=jnp.int32),
                    self._reply_extra(
                        self._round_key(base_key, r, (_K_EXTRA + 31) * 101 + k),
                        state), r, self.Kr)
                fails = fails._replace(overflow=fails.overflow + n_overflow)
                state = state._replace(reply_box=rbox)

            state = self._post_receive_slot(state, valid, ty, sender, sr,
                                            extra, base_key, r, k)
            out = (state, fails, n_sent_replies, reply_size_total,
                   n_compact, n_wide)
            if probes_on:
                out = out + (pa,)
            if health_on:
                out = out + (first_bad,)
            return out

        init = (state, self._fc_zeros(), jnp.int32(0), jnp.int32(0),
                jnp.int32(0), jnp.int32(0))
        if probes_on:
            init = init + (self._probe_zero_accum(),)
        if health_on:
            init = init + (jnp.int32(-1),)
        carry = jax.lax.fori_loop(0, self.K, slot_body, init)
        state, fails, n_sent_replies, reply_size_total, n_compact, n_wide = \
            carry[:6]

        state = state._replace(mailbox=state.mailbox.clear_cell(b))
        state, ex_sent, ex_fails, ex_size = self._post_deliver(state, base_key, r)
        diag = {"mailbox_hwm": hwm, "compact_slots": n_compact,
                "wide_slots": n_wide}
        if probes_on:
            diag["probe_accum"] = carry[6]
        if health_on:
            diag["first_bad_slot"] = carry[6 + (1 if probes_on else 0)]
        return state, n_sent_replies + ex_sent, fails + ex_fails, \
            reply_size_total + ex_size, diag

    def _post_receive_slot(self, state: SimState, valid, ty, sender,
                           send_round, extra, base_key, r, k) -> SimState:
        """Hook after each mailbox slot is processed (token reactions...).

        ``send_round`` is the [N] round each slot message was SENT in — the
        history cell carrying its payload snapshot (differs from ``r`` for
        delayed messages). ``k`` is the TRACED slot index (the deliver phase
        rolls slots into a ``fori_loop``): use it in array arithmetic /
        ``fold_in``, never as a Python int.
        """
        return state

    def _post_deliver(self, state: SimState, base_key, r):
        """Hook after the deliver phase; may emit extra messages. Returns
        ``(state, n_sent, fails, total_size)`` where ``fails`` is a
        :class:`~gossipy_tpu.telemetry.FailureCounts` (per-cause traced
        counters — overriding variants attribute their losses to
        drop/offline/overflow rather than one opaque sum)."""
        return state, jnp.int32(0), FailureCounts.zeros(), jnp.int32(0)

    def _reply_extra(self, key: jax.Array, state: SimState) -> jax.Array:
        return jnp.zeros(self.n_nodes, dtype=jnp.int32)

    def _replies_possible(self) -> bool:
        """Static: PUSH-only simulations never generate replies, so the whole
        reply pipeline (Kr masked update passes per round) is elided at trace
        time."""
        return self.protocol != AntiEntropyProtocol.PUSH

    def _reply_phase(self, state: SimState, base_key, r):
        probes_on = self._probe_slots_on()
        if not self._replies_possible():
            diag = {"compact_slots": jnp.int32(0), "wide_slots": jnp.int32(0)}
            if probes_on:
                diag["probe_accum"] = self._probe_zero_accum()
            return state, self._fc_zeros(), diag
        n = self.n_nodes
        D = state.history_ages.shape[0]
        b = r % D
        online = jax.random.bernoulli(
            self._round_key(base_key, r, _K_ONLINE * 7 + 3), self.online_prob, (n,))
        if self.chaos is not None:
            forced = self._chaos_forced_offline(r)
            online = online & ~forced

        if self.fused_merge == "multi":
            # Same single-pass hoist as the deliver phase, over the reply
            # box's Kr slots (REPLY messages always carry models, so the
            # apply mask is just occupied & online).
            sender_t = state.reply_box.sender[b]
            sr_t = state.reply_box.send_round[b]
            occupied_t = sender_t >= 0
            apply_t = occupied_t & online[:, None]
            fails = self._fc_zeros()
            if self.chaos is not None:
                fails = fails.add_chaos((occupied_t & forced[:, None]).sum())
                fails = fails._replace(
                    offline=fails.offline
                    + (occupied_t & ~forced[:, None]
                       & ~online[:, None]).sum())
            else:
                fails = fails._replace(
                    offline=fails.offline
                    + (occupied_t & ~online[:, None]).sum())
            keys = self._fused_slot_keys(
                base_key, r,
                [(_K_CALL + 53) * 101 + k for k in range(self.Kr)], apply_t)
            pre_state = state if probes_on else None
            state, n_compact, n_wide = self._fused_multi_dispatch(
                state, sr_t, sender_t, apply_t, keys)
            diag = {"compact_slots": n_compact, "wide_slots": n_wide}
            if probes_on:
                diag["probe_accum"] = self._fused_multi_probe(
                    self._probe_zero_accum(), pre_state, state, sr_t,
                    sender_t, apply_t, r)
            state = state._replace(reply_box=state.reply_box.clear_cell(b))
            return state, fails, diag

        def slot_body(k, carry):
            if probes_on:
                state, fails, n_compact, n_wide, pa = carry
            else:
                state, fails, n_compact, n_wide = carry
                pa = None
            sender = jnp.take(state.reply_box.sender[b], k, axis=1)
            occupied = sender >= 0
            valid = occupied & online
            if self.chaos is not None:
                fails = fails.add_chaos((occupied & forced).sum())
                fails = fails._replace(
                    offline=fails.offline
                    + (occupied & ~forced & ~online).sum())
            else:
                fails = fails._replace(
                    offline=fails.offline + (occupied & ~online).sum())
            sr_k = jnp.take(state.reply_box.send_round[b], k, axis=1)
            extra_k = jnp.take(state.reply_box.extra[b], k, axis=1)
            call_key = self._round_key(base_key, r, (_K_CALL + 53) * 101 + k)
            dc, dw = self._delivery_path_counts(valid)
            n_compact += dc
            n_wide += dw
            if probes_on:
                pre_model = state.model
            state = jax.lax.cond(
                valid.any(),
                lambda st: self._receive_slot_apply(st, sr_k, sender, extra_k,
                                                    valid, call_key),
                lambda st: st,
                state)
            if probes_on:
                pa = self._probe_slot_update(pa, state, pre_model, sr_k,
                                             sender, extra_k, valid, r)
            out = (state, fails, n_compact, n_wide)
            return out + ((pa,) if probes_on else ())

        init = (state, self._fc_zeros(), jnp.int32(0), jnp.int32(0))
        if probes_on:
            init = init + (self._probe_zero_accum(),)
        carry = jax.lax.fori_loop(0, self.Kr, slot_body, init)
        state, fails, n_compact, n_wide = carry[:4]
        state = state._replace(reply_box=state.reply_box.clear_cell(b))
        diag = {"compact_slots": n_compact, "wide_slots": n_wide}
        if probes_on:
            diag["probe_accum"] = carry[4]
        return state, fails, diag

    # -- evaluation ---------------------------------------------------------

    def _metric_keys(self) -> list[str]:
        if self._metric_names is None:
            if self.has_local_test:
                d = (self.data["xte"][0], self.data["yte"][0], self.data["mte"][0])
            else:
                d = (self.data["xtr"][0], self.data["ytr"][0], self.data["mtr"][0])
            st = self.handler.init(jax.random.PRNGKey(0))
            self._metric_names = sorted(
                jax.eval_shape(lambda s: self.handler.evaluate(s, d), st).keys())
        return self._metric_names

    def _maybe_eval(self, state: SimState, base_key, r, last_round=None):
        """``_eval_phase`` gated by ``eval_every`` (skipped rounds: NaN rows,
        which the report drops). The run's final round always evaluates so
        "final accuracy" reflects the fully-trained model. The cond skips
        the whole vmapped eval computation at runtime."""
        if self.eval_every == 1:
            return self._eval_phase(state, base_key, r)
        due = (r + 1) % self.eval_every == 0
        if last_round is not None:
            due = due | (r == last_round)
        nan = jnp.full((len(self._metric_keys()),), jnp.nan, dtype=jnp.float32)
        return jax.lax.cond(
            due,
            lambda st: self._eval_phase(st, base_key, r),
            lambda st: (nan, nan),
            state)

    def _eval_phase(self, state: SimState, base_key, r):
        names = self._metric_keys()
        nan = jnp.full((len(names),), jnp.nan, dtype=jnp.float32)
        n = self.n_nodes

        # With sampling_eval the node subset is GATHERED (static size n_pick),
        # so only n_pick forward passes run — the point of the feature
        # (reference simul.py:433-436).
        if self.sampling_eval > 0:
            k_eval = self._round_key(base_key, r, _K_EVAL)
            n_pick = self._n_eval_nodes()
            idx = jax.random.permutation(k_eval, n)[:n_pick]
            model = jax.tree.map(lambda l: l[idx], state.model)
        else:
            idx = jnp.arange(n)
            model = state.model

        def mean_metrics(res, node_mask):
            vals = jnp.stack([res[k] for k in names], axis=-1)  # [n_pick, M]
            w = node_mask.astype(jnp.float32)
            tot = w.sum()
            return jnp.where(tot > 0,
                             (vals * w[:, None]).sum(0) / jnp.maximum(tot, 1.0),
                             nan)

        local = nan
        if self.has_local_test:
            d = (self.data["xte"][idx], self.data["yte"][idx], self.data["mte"][idx])
            res = jax.vmap(self.handler.evaluate)(model, d)
            has_test = self.data["mte"][idx].sum(axis=1) > 0  # node.py:227-238
            local = mean_metrics(res, has_test)

        glob = nan
        if self.has_global_eval:
            xe, ye = self.data["x_eval"], self.data["y_eval"]
            me = jnp.ones(xe.shape[0], dtype=jnp.float32)
            res = jax.vmap(lambda m: self.handler.evaluate(m, (xe, ye, me)))(model)
            glob = mean_metrics(res, jnp.ones(idx.shape[0], dtype=bool))
        return local, glob

    # -- the round program --------------------------------------------------

    def _snapshot(self, state: SimState, r):
        D = state.history_ages.shape[0]
        b = r % D
        stored, scales = self._encode_history_rows(state.model.params)
        hist_p = jax.tree.map(lambda h, p: h.at[b].set(p),
                              state.history_params, stored)
        hist_a = state.history_ages.at[b].set(state.model.n_updates)
        state = state._replace(history_params=hist_p, history_ages=hist_a)
        if self.history_dtype == "int8":
            state = state._replace(history_scale=jax.tree.map(
                lambda h, s: h.at[b].set(s), state.history_scale, scales))
        return state

    def _round(self, state: SimState, base_key: jax.Array, last_round=None):
        r = state.round
        # Phase scopes (telemetry.scopes): the names land in the compiled
        # HLO's op metadata and in XProf traces captured via profile_dir=,
        # so a trace shows named phases instead of one opaque scan body.
        # The train scope nests inside receive_merge/reply around the
        # vmapped handler pass (_receive_rows / _fused_receive).
        with jax.named_scope(PHASE_SEND):
            state = self._pre_send(state, base_key, r)
            state = self._snapshot(state, r)
            state, n_sent, fail_s, size_s = self._send_phase(state, base_key, r)
        with jax.named_scope(PHASE_RECEIVE_MERGE):
            state, n_replies, fail_d, size_r, diag = \
                self._deliver_phase(state, base_key, r)
        with jax.named_scope(PHASE_REPLY):
            state, fail_r, reply_diag = self._reply_phase(state, base_key, r)
        with jax.named_scope(PHASE_EVAL):
            local, glob = self._maybe_eval(state, base_key, r, last_round)
        state = state._replace(round=r + 1)
        fails = fail_s + fail_d + fail_r
        stats = {
            "sent": n_sent + n_replies,
            # Legacy total, kept bit-for-bit equal to the cause sum (the
            # causes are mutually exclusive integer tallies).
            "failed": fails.total(),
            "failed_drop": fails.drop,
            "failed_offline": fails.offline,
            "failed_overflow": fails.overflow,
            "mailbox_hwm": diag["mailbox_hwm"],
            "compact_slots": diag["compact_slots"]
                + reply_diag["compact_slots"],
            "wide_slots": diag["wide_slots"] + reply_diag["wide_slots"],
            "size": size_s + size_r,
            "local": local,
            "global": glob,
        }
        if self.chaos is not None:
            stats["failed_chaos"] = fails.chaos
            if self._chaos_probes_on():
                stats.update(self._chaos_stats(state, r))
        if self.probes is not None:
            pa = None
            if self._probe_slots_on():
                pa = diag["probe_accum"] + reply_diag["probe_accum"]
            stats.update(self._probe_round_stats(state, pa))
        if self._health_slots_on():
            # The round-level vitals are appended by the scan body
            # (_health_round); the slot-resolved accumulator can only
            # come from inside the deliver loop, so it rides here.
            stats["health_first_bad_slot"] = diag["first_bad_slot"]
        return state, stats

    # -- public API ---------------------------------------------------------

    def _emit_live(self, state: SimState, stats: dict) -> None:
        """Ordered host callback notifying live receivers at a round boundary
        (the only point a jitted run touches the host; SURVEY §5). Each
        callback also stamps a host wall-clock sample into
        ``_live_round_times`` — the basis for the report's per-round timing
        and rounds/sec EMA when the run is live."""
        names = self._metric_keys()
        # Probe, health and chaos values ride the same ordered callback
        # (fixed key order so the host side can rebuild the dicts from
        # positional operands).
        from .faults import chaos_event_row
        probe_keys = [k for k in PROBE_STAT_KEYS if k in stats]
        health_keys = [k for k in HEALTH_STAT_KEYS if k in stats]
        chaos_keys = [k for k in ("failed_chaos",) + CHAOS_PROBE_KEYS
                      if k in stats]

        def cb(rnd, sent, failed, drop, offline, overflow, size, local,
               glob, *extra_vals):
            import time as _time
            times = getattr(self, "_live_round_times", None)
            if times is not None:
                times.append(_time.perf_counter())
            causes = {"drop": int(drop), "offline": int(offline),
                      "overflow": int(overflow)}
            probes = probe_event_row(
                dict(zip(probe_keys, extra_vals[:len(probe_keys)])))
            off = len(probe_keys)
            health = health_event_row(
                dict(zip(health_keys, extra_vals[off:off
                                                 + len(health_keys)])))
            off += len(health_keys)
            chaos_vals = dict(zip(chaos_keys, extra_vals[off:]))
            if "failed_chaos" in chaos_vals:
                causes["chaos"] = int(chaos_vals["failed_chaos"])
            chaos = chaos_event_row(chaos_vals)

            def row(vals):
                if np.all(np.isnan(vals)):
                    return None
                return {k: float(v) for k, v in zip(names, vals)}
            self._notify_round(int(rnd), int(sent), int(failed), int(size),
                               row(local), row(glob), live_only=True,
                               causes=causes, probes=probes, health=health,
                               chaos=chaos)

        jax.experimental.io_callback(
            cb, None, state.round, stats["sent"], stats["failed"],
            stats["failed_drop"], stats["failed_offline"],
            stats["failed_overflow"], stats["size"], stats["local"],
            stats["global"], *[stats[k] for k in probe_keys],
            *[stats[k] for k in health_keys],
            *[stats[k] for k in chaos_keys], ordered=True)

    def _cache_salt(self):
        """Extra jit-cache key component for variants whose trace depends on
        mutable static config (e.g. the PENS phase)."""
        return 0

    # Wall time of the most recent cold ``start()`` dispatch (trace +
    # compile); None until a run has compiled. Read by RunManifest.
    last_compile_seconds: Optional[float] = None

    def run_manifest(self, extra: Optional[dict] = None):
        """The once-per-run :class:`~gossipy_tpu.telemetry.RunManifest` for
        this simulator: config snapshot, backend/mesh/library versions,
        git rev, :meth:`memory_budget`, the last cold-compile wall
        time and (with ``perf=`` on) the :meth:`perf_summary` block.
        Host-side only — safe to call before or after a run."""
        from ..telemetry import RunManifest
        return RunManifest.from_simulator(self, extra=extra)

    # -- performance observability (telemetry.cost; host-side only) ---------

    def _record_cost(self, compiled, label: str,
                     n_rounds: Optional[int] = None) -> None:
        """Bank XLA's cost/memory analysis of one freshly compiled round
        program (perf ``cost`` facility). Best-effort: a capture failure
        must never take down a compile."""
        try:
            self._cost_reports.append(
                CostReport.from_compiled(compiled, label=label,
                                         n_rounds=n_rounds))
        except Exception:
            pass

    def _perf_flops_per_round(self) -> Optional[float]:
        """Per-round FLOPs from the latest banked program (XLA counts a
        scan body once regardless of trip count, so a program's count IS
        its per-round count)."""
        for cr in reversed(self._cost_reports):
            if cr.flops is not None:
                return cr.flops
        return None

    def _attach_perf_stats(self, stats: dict, n_rounds: int,
                           exec_seconds: float, cold: bool) -> dict:
        """Stamp the run's host-measured timing into the stats dict as
        per-round ``perf_*`` rows (uniform within this start() segment —
        a scanned program has no per-round host boundary; chunked
        drivers get per-chunk resolution) and remember the summary for
        :meth:`perf_summary`."""
        import jax as _jax
        per_round_s = exec_seconds / max(n_rounds, 1)
        flops_pr = self._perf_flops_per_round()
        try:
            kind = _jax.devices()[0].device_kind
        except Exception:
            kind = None
        mfu = mfu_estimate(flops_pr, per_round_s, kind)
        stats["perf_round_ms"] = np.full((n_rounds,), per_round_s * 1e3,
                                         np.float64)
        stats["perf_mfu_est"] = np.full(
            (n_rounds,), np.nan if mfu is None else mfu, np.float32)
        self._perf_last = {
            "rounds": n_rounds,
            "seconds": exec_seconds,
            "ms_per_round": per_round_s * 1e3,
            "mfu_est": mfu,
            "flops_per_round": flops_pr,
            # A cold NON-AOT dispatch folds compile time into the
            # measurement; the AOT perf path compiles before the timer.
            "cold": bool(cold),
        }
        return stats

    def _feed_metrics(self, stats: dict, report, n_rounds: int) -> dict:
        """Host-side SLO-metrics feed for one finished segment
        (``metrics=True``): increment the process registry's engine
        counters from the report's per-cause FailureCounts arrays, and
        attach per-round CUMULATIVE counter rows (engine-lifetime, so
        chunked drivers keep monotone counters across start() calls)
        for the JSONL v7 ``metrics`` field. Never called from a traced
        region — the metrics-in-trace lint rule and the
        engine/metrics-on HLO identity pair both enforce that."""
        from ..telemetry.metrics import observe_engine_run
        sent = np.asarray(report.sent_per_round, np.int64)
        failed = np.asarray(report.failed_per_round, np.int64)
        if report.failed_per_cause is not None:
            by_cause = {c: float(np.asarray(a).sum())
                        for c, a in report.failed_per_cause.items()}
        else:
            by_cause = {"all": float(failed.sum())}
        observe_engine_run(type(self).__name__, n_rounds,
                           float(sent.sum()), by_cause)
        base = self._metrics_base
        sent_cum = base["sent"] + np.cumsum(sent)
        failed_cum = base["failed"] + np.cumsum(failed)
        stats["metrics_rows"] = [
            {"rounds_total": base["rounds"] + i + 1,
             "sent_total": int(sent_cum[i]),
             "failed_total": int(failed_cum[i])}
            for i in range(n_rounds)]
        base["rounds"] += n_rounds
        base["sent"] = int(sent_cum[-1]) if n_rounds else base["sent"]
        base["failed"] = int(failed_cum[-1]) if n_rounds else base["failed"]
        return stats

    def perf_summary(self) -> Optional[dict]:
        """The manifest/verdict ``perf`` block (None when ``perf=`` is
        off): banked program costs, the analytic cross-check, the last
        run's timing/MFU, and the peak-table context. Every field is
        null-safe — a CPU run reports real FLOPs/bytes with a null MFU
        (unknown peak) rather than inventing one."""
        if self.perf is None:
            return None
        from ..telemetry.cost import analytic_round_cost, peak_flops
        try:
            kind = jax.devices()[0].device_kind
        except Exception:
            kind = None
        latest = None
        for cr in reversed(self._cost_reports):
            if cr.flops is not None or cr.peak_bytes is not None:
                latest = cr
                break
        analytic = None
        if self.perf.analytic:
            try:
                analytic = analytic_round_cost(self)
            except Exception:
                analytic = None
        hbm_candidates = [cr.peak_bytes for cr in self._cost_reports
                          if cr.peak_bytes is not None]
        out: dict = {
            "config": self.perf.to_dict(),
            "device_kind": kind,
            "peak_flops": peak_flops(kind),
            "compile_count": len(self._cost_reports),
            "flops_per_round_xla": latest.flops if latest else None,
            "bytes_per_round_xla": (latest.bytes_accessed
                                    if latest else None),
            "hbm_peak_bytes": max(hbm_candidates, default=None),
            "analytic": analytic,
            "last_run": self._perf_last,
            "programs": [cr.to_dict() for cr in self._cost_reports],
        }
        if analytic and latest and latest.flops:
            out["analytic_vs_xla_flops_ratio"] = float(
                analytic["flops_per_round"] / latest.flops)
        return out

    # -- persistence (API parity with reference simul.py:460-494) -----------

    def save(self, path: str, state: SimState,
             key: Optional[jax.Array] = None) -> str:
        """Checkpoint a simulation state (reference ``GossipSimulator.save``
        dill-dumps the whole simulator + CACHE; here the state pytree IS the
        whole world — see gossipy_tpu/checkpoint.py).

        Disk-backed cohort pools (``CohortConfig.pool_dir``) checkpoint
        as hole-preserving file copies of the store directory
        (:func:`~gossipy_tpu.simulation.cohort.save_pool_store`) —
        O(written rows), never O(nominal N)."""
        if self.cohort is not None:
            from .cohort import is_mmap_pool, save_pool_store
            if is_mmap_pool(state):
                return save_pool_store(self, state, path, key=key)
        from ..checkpoint import save_checkpoint
        return save_checkpoint(path, state, key=key)

    def load(self, path: str, key: Optional[jax.Array] = None, mesh=None):
        """Restore ``(state, key)`` saved by :meth:`save`. The simulator
        itself is reconstructed from code + config (unlike the reference's
        pickled object graph), so call this on a simulator built with the
        same configuration. Pass ``mesh`` to restore a checkpoint from a
        sharded run directly INTO the mesh's node-axis shardings (restores
        go to the template's placement, not the file-recorded one).

        In cohort mode the checkpoint unit is the resident
        :class:`~gossipy_tpu.simulation.cohort.CohortPool` (host numpy;
        ``mesh`` does not apply) and the template is a cheap zero-filled
        pool — restores stay O(pool bytes), never O(init compute)."""
        from ..checkpoint import restore_checkpoint
        if self.cohort is not None:
            from .cohort import (is_pool_store_dir, load_pool_checkpoint,
                                 pool_template)
            if is_pool_store_dir(path):
                # Disk-backed pool checkpoint: file copies into a work
                # directory, memmaps opened there — never materialized.
                return load_pool_checkpoint(self, path)
            return restore_checkpoint(path, pool_template(self), key)
        template = self.init_nodes(jax.random.PRNGKey(0), local_train=False)
        if mesh is not None:
            from ..parallel import shard_state
            template = shard_state(template, mesh)
        return restore_checkpoint(path, template, key)

    def _make_run(self, n_rounds: int, live: bool):
        """The ``n_rounds``-round scan as a pure (state, key, data) ->
        (state, stats) function — the unit :meth:`start` jits and
        :meth:`lower_start` AOT-lowers.

        ``data`` is an explicit ARGUMENT, not a closure capture: on a
        multi-controller cluster (``parallel.init_distributed``) the stacked
        data spans processes, and jit forbids closing over arrays with
        non-addressable shards. Inside the trace ``self.data`` is rebound to
        the traced values so every helper reads the argument.
        """
        sentinels_on = self.sentinels is not None

        def scan_rounds(state, key, hc):
            last = state.round + n_rounds - 1

            def body(carry, _):
                if sentinels_on:
                    st, c = carry
                    pre_params = st.model.params
                else:
                    st, c = carry, None
                st, stats = self._round(st, key, last)
                if sentinels_on:
                    c, hstats = self._health_round(c, pre_params, st,
                                                   stats)
                    stats.update(hstats)
                if live:
                    self._emit_live(st, stats)
                    if sentinels_on:
                        self._emit_trip_live(st, stats)
                return ((st, c) if sentinels_on else st), stats

            init = (state, hc) if sentinels_on else state
            final, stats = jax.lax.scan(body, init, None, length=n_rounds)
            return final, stats

        if sentinels_on:
            # The health carry crosses the jit boundary: consecutive
            # start() calls continue the divergence EMA instead of
            # re-seeding it every segment (see __init__).
            def run(state, key, data, hc):
                saved = self.data
                self.data = data
                try:
                    (state, hc), stats = scan_rounds(state, key, hc)
                    return state, hc, stats
                finally:
                    self.data = saved
        else:
            def run(state, key, data):
                saved = self.data
                self.data = data
                try:
                    return scan_rounds(state, key, None)
                finally:
                    self.data = saved
        return run

    def lower_start(self, state: SimState, n_rounds: int = 100,
                    key: Optional[jax.Array] = None):
        """AOT-lower the ``n_rounds`` scan program for this state's shapes.

        ``.compile()`` on the result exposes XLA's own ``cost_analysis()``
        (FLOPs, bytes accessed) and ``as_text()`` (HLO) — the basis for the
        MFU numbers in ``bench.py --mfu`` and docs/performance.md. The
        reference has no analogue (its rounds are Python loops; SURVEY §5
        tracing/profiling).
        """
        if self.cohort is not None:
            raise ValueError("cohort mode is segment-driven; lower the "
                             "inner round program via a cohort=None twin "
                             "at n_nodes=C instead")
        if key is None:
            key = jax.random.PRNGKey(42)
        args = (state, key, self.data)
        if self.sentinels is not None:
            args = args + (self._health_zero_carry(),)
        return jax.jit(self._make_run(n_rounds, live=False)).lower(*args)

    def start(self, state: SimState, n_rounds: int = 100,
              key: Optional[jax.Array] = None,
              profile_dir: Optional[str] = None,
              donate_state: bool = True,
              mesh=None) -> tuple[SimState, SimulationReport]:
        """Run ``n_rounds`` rounds (reference simul.py:366-458) as one
        ``lax.scan``; returns the final state and a report.

        ``profile_dir`` wraps the run in a ``jax.profiler`` trace (SURVEY §5:
        the reference has no tracing; per-round hooks attach via the event
        stream, see :mod:`gossipy_tpu.simulation.events`).

        ``donate_state`` (default True) donates the input state pytree to
        the compiled program (``donate_argnums``): XLA aliases the output
        state's buffers onto the input's, so the params-history ring — the
        dominant persistent term — is not double-buffered across the call.
        The donated input is INVALIDATED; pass ``donate_state=False`` when
        you reuse the same initial state for several runs (A/B comparisons,
        warmup-then-measure).

        In cohort mode ``state`` is the resident :class:`~gossipy_tpu.
        simulation.cohort.CohortPool` and the call is the host-driven
        gather -> [C]-round -> scatter segment loop (``profile_dir`` /
        ``donate_state`` do not apply there: segments donate their own
        freshly-built state). ``mesh`` (cohort mode only) shards the
        [C]-wide active state and data across the mesh's node axis via
        the ``parallel/rules.py`` registry.
        """
        if self.cohort is not None:
            from .cohort import cohort_start
            out = cohort_start(self, state, n_rounds, key, mesh=mesh)
            self._ledger_append(out[1], n_rounds, None)
            return out
        if mesh is not None:
            raise ValueError(
                "start(mesh=) is the cohort-mode sharded-round path; "
                "for materialized populations place the state up front "
                "with parallel.shard_state(state, mesh)")
        if key is None:
            key = jax.random.PRNGKey(42)

        live = self.has_live_receivers()
        live_fallback = live and not host_callbacks_supported()
        if live_fallback:
            import warnings
            warnings.warn(
                "this backend does not support host callbacks "
                "(io_callback); live event receivers fall back to post-run "
                "replay — all events still arrive, just not during the run")
            live = False
        first_round = int(np.asarray(state.round))
        cache_k = ("start", n_rounds, self._cache_salt(), live,
                   bool(donate_state))
        cold = cache_k not in self._jit_cache

        import time as _time

        from ..telemetry import tracing as _tracing
        tr = self.tracer
        args = (state, key, self.data)
        if self.sentinels is not None:
            hc_in = (self._health_carry if self._health_carry is not None
                     else self._health_zero_carry())
            args = args + (hc_in,)
        compile_recorded = False
        # The whole segment is one trace "run window" (round_start/rounds
        # args are what scripts/trace_report.py keys its critical-path and
        # host_blocked/overlap reduction on).
        with _tracing.span("engine.start", cat="engine", tracer=tr,
                           round_start=first_round, rounds=n_rounds,
                           cold=cold):
            if cold:
                fn = jax.jit(self._make_run(n_rounds, live),
                             donate_argnums=(0,) if donate_state else ())
                if self.perf is not None and self.perf.cost:
                    # AOT detour: compile the SAME program explicitly so
                    # XLA's own cost_analysis/memory_analysis can be banked
                    # at compile time (telemetry.cost.CostReport). Falls
                    # back to plain dispatch-jit if the backend resists
                    # AOT. The span handle is the ONE timing source: it
                    # feeds both last_compile_seconds and the trace.
                    sp_c = _tracing.span("engine.compile", cat="engine",
                                         tracer=tr,
                                         program=f"start[{n_rounds}r]")
                    with sp_c:
                        try:
                            compiled = fn.lower(*args).compile()
                        except Exception as e:
                            compiled, compile_err = None, e
                    if compiled is None:
                        import warnings
                        warnings.warn(
                            "perf cost capture: AOT compile failed "
                            f"({compile_err!r}); falling back to dispatch "
                            "jit (no CostReport for this program)")
                        self._jit_cache[cache_k] = fn
                    else:
                        self.last_compile_seconds = sp_c.duration
                        compile_recorded = True
                        self._record_cost(compiled,
                                          label=f"start[{n_rounds}r]"
                                                f"{'/live' if live else ''}",
                                          n_rounds=n_rounds)
                        self._jit_cache[cache_k] = compiled
                else:
                    self._jit_cache[cache_k] = fn

            # Live runs get host wall-clock samples per round boundary (the
            # ordered io_callback already syncs the host there, so the extra
            # perf_counter is free); non-live runs have no per-round host
            # boundary and skip timing rather than invent one.
            self._live_round_times: Optional[list] = [] if live else None
            t_run0 = _time.perf_counter()
            perf_timing = self.perf is not None and self.perf.timing
            # cat="host.wait": the run span is dispatch + completion wait,
            # not host work — trace_report excludes it from host-busy time
            # and the bridged device span below accounts the window.
            sp_run = _tracing.span("engine.run", cat=_tracing.WAIT_CAT,
                                   tracer=tr)
            with sp_run:
                if profile_dir is not None:
                    with jax.profiler.trace(profile_dir):
                        out = self._jit_cache[cache_k](*args)
                        jax.block_until_ready(out[0].model.params)
                else:
                    out = self._jit_cache[cache_k](*args)
                if perf_timing or tr is not None:
                    # ONE host sync per start() call (not per round): the
                    # measured wall time is this segment's whole-run cost,
                    # amortized to ms/round below. On a cold non-AOT
                    # dispatch the measurement would fold compile time in
                    # — flagged via "cold". (A live tracer needs the same
                    # sync: the run span must close at execution end.)
                    jax.block_until_ready(out)
            exec_seconds = sp_run.duration
            if tr is not None:
                # Bridge device time under the run window: per-phase
                # attribution when a profiler trace was captured, else the
                # host-observed execution wait as the device-time proxy.
                phase_ms = None
                if profile_dir is not None:
                    try:
                        from ..telemetry.cost import phase_times_from_trace
                        phase_ms = phase_times_from_trace(profile_dir)
                    except Exception:
                        phase_ms = None
                _tracing.attach_device_spans(tr, sp_run.ts_us,
                                             sp_run.dur_us,
                                             phase_ms=phase_ms,
                                             args={"n_rounds": n_rounds})
            if self.sentinels is not None:
                state, self._health_carry, stats = out
            else:
                state, stats = out
            if cold and not compile_recorded:
                # Wall time of the cold dispatch: tracing + XLA compilation
                # (execution is async-dispatched and largely excluded,
                # except under profile_dir — or a live tracer — where the
                # block_until_ready above folds the run in). Recorded for
                # the RunManifest. (The perf AOT path above already
                # recorded the exact compile wall instead.)
                self.last_compile_seconds = _time.perf_counter() - t_run0
            if perf_timing:
                stats = self._attach_perf_stats(dict(stats), n_rounds,
                                                exec_seconds, cold)
            # Building the report forces the stats device->host transfer,
            # which completes only after the program (including its ordered
            # callbacks) finishes — harvest the live timestamps only after
            # that, or the async dispatch would race the collection.
            with _tracing.span("engine.report", cat="engine", tracer=tr):
                report = self._build_report(stats)
                if self.metrics_enabled:
                    stats = self._feed_metrics(dict(stats), report,
                                               n_rounds)
                live_times, self._live_round_times = \
                    self._live_round_times, None
                self.replay_events(first_round, stats, self._metric_keys(),
                                   include_live=live_fallback)
            if live_times:
                report.attach_wall_clock(t_run0, live_times)
        # Outside the trace window: the digest append is ledger
        # bookkeeping, not run work. exec_seconds only measured the run
        # when something forced the completion sync (perf timing / a
        # live tracer); otherwise it timed the async dispatch only and
        # would fabricate a throughput.
        self._ledger_append(report, n_rounds,
                            exec_seconds if (perf_timing or tr is not None)
                            else None, round_start=first_round)
        return state, report

    def _ledger_append(self, report, n_rounds: int,
                       exec_seconds: Optional[float],
                       round_start: Optional[int] = None) -> Optional[dict]:
        """Append this segment's digest row to the run ledger (telemetry.
        ledger; no-op without one). Host-side, post-run, best-effort —
        never raises into a finished run. Segments of one chunked run
        share the simulator's ledger run id."""
        if self.ledger is None:
            return None
        try:
            from ..telemetry import ledger as _ledger
            metrics: dict = {}
            if exec_seconds and exec_seconds > 0:
                metrics["rounds_per_sec"] = round(n_rounds / exec_seconds,
                                                  3)
            perf_last = getattr(self, "_perf_last", None) or {}
            metrics["mfu_est"] = perf_last.get("mfu_est")
            for name in ("accuracy", "auc", "f1"):
                acc = report.final(name)
                if acc == acc:  # first non-NaN eval metric is headline
                    metrics["final_accuracy"] = acc
                    break
            extra = {"rounds": int(n_rounds)}
            if round_start is not None:
                extra["round_start"] = int(round_start)
            row = _ledger.ingest_manifest(
                self.ledger, self.run_manifest(), kind="engine",
                run_id=self._ledger_run_id, metrics=metrics, extra=extra)
            self._ledger_run_id = row["run_id"]
            return row
        except Exception:
            return None

    def _build_report(self, stats: dict) -> SimulationReport:
        def opt(k):
            return np.asarray(stats[k]) if k in stats else None
        failed_by_cause = None
        if "failed_drop" in stats:
            failed_by_cause = {"drop": np.asarray(stats["failed_drop"]),
                               "offline": np.asarray(stats["failed_offline"]),
                               "overflow": np.asarray(stats["failed_overflow"])}
            if "failed_chaos" in stats:
                failed_by_cause["chaos"] = np.asarray(stats["failed_chaos"])
        extras = {k: opt(k) for k in PROBE_STAT_KEYS if k in stats}
        extras.update({k: opt(k) for k in HEALTH_STAT_KEYS if k in stats})
        extras.update({k: opt(k) for k in CHAOS_PROBE_KEYS if k in stats})
        extras.update({k: opt(k) for k in PERF_STAT_KEYS if k in stats})
        from .cohort import COHORT_STAT_KEYS
        extras.update({k: opt(k) for k in COHORT_STAT_KEYS if k in stats})
        if self.probes is not None:
            if self.probes.consensus:
                extras["probe_layer_names"] = self._probe_layer_names()
            if self.probes.mixing:
                extras["probe_expected_fanin"] = np.asarray(
                    self._probe_expected_fanin(), np.float64)
        if self.sentinels is not None and self.sentinels.nonfinite:
            # Same shape-only leaf naming as the probes' per-layer
            # breakdown: names the columns of the non-finite counts.
            extras["health_layer_names"] = self._probe_layer_names()
        report = SimulationReport(
            metric_names=self._metric_keys(),
            local_evals=np.asarray(stats["local"]) if self.has_local_test else None,
            global_evals=np.asarray(stats["global"]) if self.has_global_eval else None,
            sent=np.asarray(stats["sent"]),
            failed=np.asarray(stats["failed"]),
            total_size=int(np.asarray(stats["size"]).sum()),
            failed_by_cause=failed_by_cause,
            mailbox_hwm=opt("mailbox_hwm"),
            compact_slots=opt("compact_slots"),
            wide_slots=opt("wide_slots"),
            **extras,
        )
        if self.probes is not None:
            self._emit_probe_summary(report)
        return report

    def _emit_probe_summary(self, report: SimulationReport) -> None:
        """One structured telemetry event per built report summarizing the
        run's gossip dynamics (the per-round detail lives in the report
        and the ``update_probes`` event stream)."""
        data: dict = {"simulator": type(self).__name__,
                      "probes": self.probes.to_dict()}
        cm = report.probe_consensus_mean
        if cm is not None and len(cm):
            data["consensus_first"] = float(cm[0])
            data["consensus_last"] = float(cm[-1])
        if report.probe_stale_max is not None and len(report.probe_stale_max):
            data["stale_max"] = int(np.max(report.probe_stale_max))
        acc = report.probe_accepted_per_node
        if acc is not None:
            data["accepted_total"] = int(np.sum(acc))
        emit_event("probes_summary", data)

    def run_repetitions(self, n_rounds: int, keys: jax.Array,
                        local_train: bool = True, common_init: bool = False,
                        ) -> tuple[SimState, list[SimulationReport]]:
        """Run S INDEPENDENT simulations — init + ``n_rounds`` rounds each —
        as ONE compiled program, vmapped over a leading seed axis.

        The reference runs experiment repetitions serially (one Python
        simulation per seed); here the whole repetition batch executes in a
        single XLA program whose per-node math is additionally batched over
        seeds (MXU-friendly). This is what feeds
        :func:`gossipy_tpu.utils.plot_evaluation`'s mean±std curves.

        ``keys``: [S] stacked PRNG keys (e.g. ``jax.random.split(k, S)``).
        Returns the stacked final states (leading seed axis) and one
        :class:`SimulationReport` per seed. Event receivers are not
        supported here (which repetition's events would they see?) — use
        ``start`` per seed when you need the event stream. Single-controller
        only (the seed batch closes over the data; on a multi-host cluster
        run :meth:`start` per seed instead).

        Buffer-donation note: the per-seed states are CREATED inside the
        compiled program (only the [S] key batch crosses the boundary), so
        there is no state pytree to donate here — the scan carry already
        reuses its buffers. :meth:`start` (and PENS's two-segment
        continuation) donate their state arguments instead.
        """
        assert not self._receivers_list(), \
            "run_repetitions does not support event receivers; use start()"
        if self.cohort is not None:
            raise ValueError("cohort mode is host-driven per segment and "
                             "cannot ride the seed vmap; run start() per "
                             "seed against separate pools")

        cache_k = ("reps", n_rounds, bool(local_train), bool(common_init),
                   self._cache_salt())
        cold_reps = cache_k not in self._jit_cache
        if cold_reps:
            def one(key):
                k_init, k_run = jax.random.split(key)
                st = self.init_nodes(k_init, local_train=local_train,
                                     common_init=common_init)
                last = st.round + n_rounds - 1
                sentinels_on = self.sentinels is not None

                def body(carry, _):
                    if sentinels_on:
                        s, hc = carry
                        pre_params = s.model.params
                        s, stats = self._round(s, k_run, last)
                        hc, hstats = self._health_round(hc, pre_params,
                                                        s, stats)
                        stats.update(hstats)
                        return (s, hc), stats
                    s, stats = self._round(carry, k_run, last)
                    return s, stats

                init = ((st, self._health_zero_carry())
                        if sentinels_on else st)
                final, stats = jax.lax.scan(body, init, None,
                                            length=n_rounds)
                return (final[0] if sentinels_on else final), stats
            self._jit_cache[cache_k] = jax.jit(
                jax.vmap(one, axis_name=BATCH_AXIS))

        # The seed vmap binds BATCH_AXIS so the compact/wide dispatch can
        # reduce its slot-overflow predicate across the batch and keep the
        # lax.cond batch-uniform (a batched predicate would execute BOTH
        # branches, adding the compact pass on top of every wide one).
        # The attribute only matters while the first call traces; restored
        # unconditionally so single-simulation start() traces stay plain.
        saved_axis = self._batch_axis_name
        self._batch_axis_name = BATCH_AXIS
        try:
            if cold_reps and self.perf is not None and self.perf.cost:
                # Same AOT cost-capture detour as start(): the seed-batch
                # program's own cost/memory analysis is banked at compile
                # time (traced HERE so the batch-axis pmax sees the axis).
                try:
                    compiled = self._jit_cache[cache_k].lower(
                        keys).compile()
                except Exception:
                    pass  # dispatch jit still runs; no CostReport
                else:
                    self._record_cost(
                        compiled,
                        label=f"run_repetitions[{n_rounds}r"
                              f"x{int(keys.shape[0])}]",
                        n_rounds=n_rounds)
                    self._jit_cache[cache_k] = compiled
            states, stats = self._jit_cache[cache_k](keys)
        finally:
            self._batch_axis_name = saved_axis
        host = jax.tree.map(np.asarray, stats)  # one device->host transfer
        n_reps = host["sent"].shape[0]
        reports = [self._build_report(jax.tree.map(lambda a, i=i: a[i], host))
                   for i in range(n_reps)]
        return states, reports
