"""Node-behavior simulator variants (reference gossipy/node.py:289-785).

The reference specializes node *objects*; here each protocol variant is a
``GossipSimulator`` subclass overriding the engine's trace-time hooks
(payload generation, receive behavior, peer selection). All per-node variant
state lives in ``state.aux`` (leading node axis), so everything stays inside
the jitted round program.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import CreateModelMode
from ..handlers.base import ModelState, PeerModel
from .engine import BATCH_AXIS, GossipSimulator, SimState, select_nodes, \
    _K_PEER
from .report import SimulationReport

# Variant PRNG purpose tags (>= 9000 per the engine's stream-tag contract).
_K_CACHE_POP = 9500    # CacheNeigh: which parked slot to pop
_K_CACHE_MERGE = 9501  # CacheNeigh: merge-update randomness


def build_neighbor_table(topology, reject_duplicates: bool = False) -> np.ndarray:
    """Padded out-neighbor table ``[N, max_deg]`` int32, -1 = unused slot.

    The O(N * max_deg) replacement for dense [N, N] per-peer state: variant
    counters/caches key on the slot position of a peer in its row (CacheNeigh
    model slots, PENS selection counters). Works for both dense and CSR
    topologies.

    ``reject_duplicates`` (opt-in; round-5 advisor): slot-KEYED consumers
    (PENS/CacheNeigh) assume each peer occupies exactly one slot of its
    receiver's row — a multigraph row would double-count slot matches, so
    they pass True and a duplicated CSR neighbor raises. Plain neighbor-LIST
    consumers (the sequential engine's peer sampling) leave it False: there
    a duplicate edge is harmless and keeps the reference's multigraph
    semantics (it just raises that peer's sampling weight). Dense
    adjacencies cannot express duplicates either way (``np.nonzero`` yields
    unique pairs).
    """
    from ..core import SparseTopology
    n = topology.num_nodes
    degrees = np.asarray(topology.degrees)
    max_deg = max(int(degrees.max()) if n else 0, 1)
    nbr_table = np.full((n, max_deg), -1, dtype=np.int32)
    if isinstance(topology, SparseTopology):
        rows = np.repeat(np.arange(n), degrees)
        pos = np.arange(len(topology.indices)) - topology.indptr[rows]
        nbr_table[rows, pos] = topology.indices
    elif n:
        i, j = np.nonzero(np.asarray(topology.adjacency))
        pos = np.arange(len(i)) - np.searchsorted(i, i, side="left")
        nbr_table[i, pos] = j
    if reject_duplicates and isinstance(topology, SparseTopology) and n:
        row_sorted = np.sort(nbr_table, axis=1)
        dup = (row_sorted[:, 1:] >= 0) & (row_sorted[:, 1:] == row_sorted[:, :-1])
        if dup.any():
            bad = int(np.nonzero(dup.any(axis=1))[0][0])
            raise ValueError(
                f"topology row {bad} lists a neighbor more than once; "
                "slot-keyed variant state (PENS/CacheNeigh) requires "
                "duplicate-free neighbor lists — deduplicate the edge list")
    return nbr_table


class PassThroughGossipSimulator(GossipSimulator):
    """Giaretta 2019 pass-through nodes (reference node.py:289-392).

    Messages carry the sender's degree; the receiver merge-updates with
    probability ``min(1, deg_sender / deg_receiver)`` and otherwise adopts
    the received model unmodified (PASS), hiding power-law degree bias.
    """

    # _decode_extra is elementwise and _receive_rows reads per-node state
    # via node_ids / per-row keys only — compaction-safe by the engine
    # contract.
    _compact_safe = True

    def _send_extra(self, key, state):
        return self.topology.degrees_dev.astype(jnp.int32)

    def _reply_extra(self, key, state):
        return self.topology.degrees_dev.astype(jnp.int32)

    def _decode_extra(self, extra):
        return extra  # the sender's degree, raw

    def _receive_rows(self, models, peer, data, keys, extra_arg, node_ids):
        """Row-aligned receive (engine contract: compaction-compatible) —
        per-row accept draw keyed on the row's PRNG stream, receiver
        degree gathered by ``node_ids``."""
        deg_self = jnp.maximum(
            self.topology.degrees_dev[node_ids].astype(jnp.float32), 1.0)
        deg_send = extra_arg.astype(jnp.float32)
        p = jnp.minimum(1.0, deg_send / deg_self)
        accept = jax.vmap(
            lambda k, pi: jax.random.bernoulli(jax.random.fold_in(k, 911),
                                               pi))(keys, p)
        normal = super()._receive_rows(models, peer, data, keys, None,
                                       node_ids)
        # PASS: adopt the received model as-is (node.py:381-386).
        passed = ModelState(peer.params, models.opt_state, peer.n_updates)
        return select_nodes(accept, normal, passed)


class SamplingGossipSimulator(GossipSimulator):
    """Hegedus 2021 sampled-merge exchange (reference node.py:499-562).

    Each message carries a random sample seed; the receiver derives the
    coordinate mask from it and performs a subset merge
    (``SamplingSGDHandler``). The reference ships explicit index sets plus a
    ``sample_size`` float; a PRNG seed is the constant-size equivalent.
    """

    _compact_safe = True  # _decode_extra is an elementwise vmapped fold_in
    _SAMPLE_KEY = 0x5A11

    def _send_extra(self, key, state):
        return jax.random.randint(key, (self.n_nodes,), 0, 2 ** 31 - 1,
                                  dtype=jnp.int32)

    def _reply_extra(self, key, state):
        return jax.random.randint(key, (self.n_nodes,), 0, 2 ** 31 - 1,
                                  dtype=jnp.int32)

    def _decode_extra(self, extra):
        base = jax.random.PRNGKey(self._SAMPLE_KEY)
        return jax.vmap(lambda e: jax.random.fold_in(base, e))(extra)


class PartitioningGossipSimulator(GossipSimulator):
    """Hegedus 2021 partitioned exchange (reference node.py:566-659).

    Every message (and reply) carries a uniformly random partition id; the
    receiver merges only that partition (``PartitionedSGDHandler``).
    """

    _compact_safe = True  # _decode_extra passes the raw partition id through

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        assert hasattr(self.handler, "partition"), \
            "PartitioningGossipSimulator requires a PartitionedSGDHandler"
        self.n_parts = self.handler.partition.n_parts

    def _send_extra(self, key, state):
        return jax.random.randint(key, (self.n_nodes,), 0, self.n_parts,
                                  dtype=jnp.int32)

    def _reply_extra(self, key, state):
        return jax.random.randint(key, (self.n_nodes,), 0, self.n_parts,
                                  dtype=jnp.int32)

    def _decode_extra(self, extra):
        return extra


class CacheNeighGossipSimulator(GossipSimulator):
    """Giaretta 2019 neighbor-cache nodes (reference node.py:395-496).

    One model slot per neighbor: received models are parked (latest wins per
    sender, node.py:480-485); at send time the node pops a RANDOM occupied
    slot, merge-updates with it, then gossips its refreshed model
    (node.py:446-452). The reference's ``random.choice(set(...))`` crash on
    sets (node.py:449, latent bug) is fixed by construction.

    The parked [N, max_deg] model slots — ~degree x the model term, the
    variant's dominant state — are stored in the engine's ``history_dtype``
    wire format (they ARE received wire payloads): bf16/int8 parking cuts
    the cache the same 2-4x as the history ring, with a per-(node, slot,
    leaf) scale sidecar for int8. fp32 keeps today's exact behavior.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # nbr_table[i, s] = neighbor id in slot s of node i (-1 = unused).
        # O(N * max_deg) — the same footprint as the per-neighbor model cache
        # itself, so a SparseTopology CacheNeigh run scales to the node
        # counts the vanilla engine reaches (a dense [N, N] slot_of table,
        # the round-2 design, was the one remaining N^2 object here).
        # Slot-keyed parking requires duplicate-free rows (one slot per
        # peer); plain multigraph consumers pass reject_duplicates=False.
        nbr = build_neighbor_table(self.topology, reject_duplicates=True)
        self.max_deg = nbr.shape[1]
        self.nbr_table = jnp.asarray(nbr)

    def _init_aux(self, model: ModelState, key: jax.Array):
        S = self.max_deg
        wire = {"float32": None, "bfloat16": jnp.bfloat16,
                "int8": jnp.int8}[self.history_dtype]
        cache_params = jax.tree.map(
            lambda l: jnp.zeros((l.shape[0], S) + l.shape[1:],
                                wire or l.dtype),
            model.params)
        aux = {
            "cache_params": cache_params,
            "cache_age": jnp.zeros((self.n_nodes, S) + model.n_updates.shape[1:],
                                   dtype=model.n_updates.dtype),
            "cache_valid": jnp.zeros((self.n_nodes, S), dtype=bool),
        }
        if self.history_dtype == "int8":
            # One f32 dequant scale per (node, slot, leaf); scale 1 on the
            # zero-initialized (never-read) slots keeps dequant finite.
            aux["cache_scale"] = jax.tree.map(
                lambda l: jnp.ones((self.n_nodes, S), jnp.float32),
                model.params)
        return aux

    def _apply_receive(self, state: SimState, peer: PeerModel, extra, valid,
                       call_key) -> SimState:
        # Park the model in the sender's slot instead of merging (node.py:476-485).
        sender = extra  # we smuggle the sender id via extra; see below
        # Slot lookup: position of the sender in the receiver's padded
        # neighbor row — O(max_deg) scan per node, no [N, N] table.
        match = self.nbr_table == sender[:, None]  # [N, max_deg]
        slot = jnp.where(match.any(axis=1),
                         jnp.argmax(match, axis=1), -1).astype(jnp.int32)
        ok = valid & (slot >= 0)
        slot_c = jnp.clip(slot, 0, self.max_deg - 1)
        idx = jnp.arange(self.n_nodes)

        def park(cache, new):
            upd = cache.at[idx, slot_c].set(new)
            return jnp.where(ok.reshape((-1,) + (1,) * (cache.ndim - 1)),
                             upd, cache)

        # Re-encode into the wire format before parking (a no-op for fp32;
        # lossless re-quantization for int8 — the symmetric grid's max maps
        # back to the same scale).
        stored, scales = self._encode_history_rows(peer.params)
        aux = dict(state.aux)
        aux["cache_params"] = jax.tree.map(park, state.aux["cache_params"],
                                           stored)
        if self.history_dtype == "int8":
            aux["cache_scale"] = jax.tree.map(park, state.aux["cache_scale"],
                                              scales)
        aux["cache_age"] = park(state.aux["cache_age"], peer.n_updates)
        aux["cache_valid"] = state.aux["cache_valid"].at[idx, slot_c].set(
            jnp.where(ok, True, state.aux["cache_valid"][idx, slot_c]))
        return state._replace(aux=aux)

    def _send_extra(self, key, state):
        # The engine stores the sender id in the mailbox already, but the
        # receive hook only sees `extra`; mirror the sender id there.
        return jnp.arange(self.n_nodes, dtype=jnp.int32)

    def _reply_extra(self, key, state):
        return jnp.arange(self.n_nodes, dtype=jnp.int32)

    def _pre_send(self, state: SimState, base_key, r) -> SimState:
        """At timeout: pop a random occupied cache slot and merge-update with
        it before snapshotting/sending (node.py:446-452)."""
        fires, _ = self._fire_mask(state, r)
        if self.chaos is not None:
            # A forced-offline node doesn't wake to merge its cache
            # either (matches the send gate in _send_phase).
            fires = fires & ~self._chaos_forced_offline(r)
        valid = state.aux["cache_valid"]  # [N, S]
        any_cached = valid.any(axis=1)
        logits = jnp.where(valid, 0.0, -jnp.inf)
        pick = jax.random.categorical(
            self._round_key(base_key, r, _K_CACHE_POP), logits, axis=-1)
        pick_c = jnp.clip(pick, 0, self.max_deg - 1)
        idx = jnp.arange(self.n_nodes)
        popped = jax.tree.map(lambda c: c[idx, pick_c],
                              state.aux["cache_params"])
        pop_scales = (jax.tree.map(lambda s: s[idx, pick_c],
                                   state.aux["cache_scale"])
                      if self.history_dtype == "int8" else ())
        cached = PeerModel(self._decode_history_rows(popped, pop_scales),
                           state.aux["cache_age"][idx, pick_c])
        do = fires & any_cached
        keys = jax.random.split(self._round_key(base_key, r, _K_CACHE_MERGE),
                                self.n_nodes)
        merged = jax.vmap(self.handler.call, in_axes=(0, 0, 0, 0, None))(
            state.model, cached, self._local_data(), keys, None)
        model = select_nodes(do, merged, state.model)
        aux = dict(state.aux)
        aux["cache_valid"] = valid.at[idx, pick_c].set(
            jnp.where(do, False, valid[idx, pick_c]))
        return state._replace(model=model, aux=aux)


class PENSGossipSimulator(GossipSimulator):
    """Onoszko 2021 PENS / DAC peer selection (reference node.py:663-785).

    Phase 1 (first ``step1_rounds``): received models are scored by accuracy
    on the receiver's LOCAL TRAIN data and buffered; once ``n_sampled``
    models are buffered, the best ``m_top`` are merged (uniform average with
    the local model) + trained, and their senders' counters increment.
    Phase 2: a node gossips only with neighbors whose selection rate beats
    ``m_top / n_sampled`` (node.py:726-749). PUSH only; handler mode must be
    MERGE_UPDATE (node.py:713-714).

    The phase switch is static, so :meth:`start` runs two scans (one per
    phase) — each phase compiles to its own specialized program.
    """

    def __init__(self, *args, n_sampled: int = 10, m_top: int = 2,
                 step1_rounds: int = 200, **kwargs):
        super().__init__(*args, **kwargs)
        assert self.handler.mode == CreateModelMode.MERGE_UPDATE, \
            "PENSNode can only be used with MERGE_UPDATE mode."  # node.py:713-714
        max_senders = int(self.topology.degrees.max()) if self.n_nodes else 0
        if n_sampled > max_senders:
            import warnings
            warnings.warn(
                f"PENS n_sampled={n_sampled} exceeds the max in-degree "
                f"({max_senders}): the sender-keyed phase-1 buffer can never "
                "fill, so no node will merge or train in step 1 (the "
                "reference has the same degeneracy, node.py:777-783). "
                f"Consider n_sampled <= {max_senders}.")
        self.n_sampled = int(n_sampled)
        self.m_top = int(m_top)
        self.step1_rounds = int(step1_rounds)
        self._step = 1
        # Selection counters key on the padded out-neighbor table (the
        # CacheNeigh pattern): O(N * max_deg) instead of the dense [N, N]
        # the reference's per-peer dicts imply (node.py:718-721) — PENS now
        # scales to the same populations as the rest of the engine. Senders
        # outside a node's out-neighbor row are dropped from the counters by
        # construction, which also guarantees phase 2 never selects a
        # non-neighbor (on a directed graph a dense counter could).
        # Slot-keyed counters require duplicate-free rows.
        nbr = build_neighbor_table(self.topology, reject_duplicates=True)
        self.max_deg = nbr.shape[1]
        self.nbr_table = jnp.asarray(nbr)

    def _init_aux(self, model: ModelState, key: jax.Array):
        n, S, Dg = self.n_nodes, self.n_sampled, self.max_deg
        cache_params = jax.tree.map(
            lambda l: jnp.zeros((l.shape[0], S) + l.shape[1:], l.dtype),
            model.params)
        return {
            "selected": jnp.zeros((n, Dg), dtype=jnp.int32),
            "neigh_counter": jnp.zeros((n, Dg), dtype=jnp.int32),
            "cache_params": cache_params,
            "cache_loss": jnp.full((n, S), jnp.inf, dtype=jnp.float32),
            "cache_sender": jnp.full((n, S), -1, dtype=jnp.int32),
            "cache_count": jnp.zeros((n,), dtype=jnp.int32),
            "best": jnp.zeros((n, Dg), dtype=bool),
        }

    # -- peer selection -----------------------------------------------------

    def _slot_of(self, peers: jax.Array) -> jax.Array:
        """Slot position of each node's ``peers[i]`` in its neighbor row
        (-1 when not an out-neighbor); [N] -> [N]."""
        match = self.nbr_table == peers[:, None]  # [N, max_deg]
        return jnp.where(match.any(axis=1),
                         jnp.argmax(match, axis=1), -1).astype(jnp.int32)

    def _select_peers(self, state: SimState, base_key, r):
        key = self._round_key(base_key, r, _K_PEER)
        if self._step == 1:
            return self.topology.sample_peers(key)
        best = state.aux["best"]  # [N, max_deg] over neighbor slots
        has_best = best.any(axis=1)
        logits_best = jnp.where(best, 0.0, -jnp.inf)
        pick_slot = jnp.clip(jax.random.categorical(key, logits_best, axis=-1),
                             0, self.max_deg - 1)
        pick_best = self.nbr_table[jnp.arange(self.n_nodes), pick_slot]
        fallback = self.topology.sample_peers(jax.random.fold_in(key, 3))
        return jnp.where(has_best, pick_best, fallback).astype(jnp.int32)

    def _send_gate(self, state: SimState, active, peers, base_key, r):
        if self._step == 1:
            # selected[i, slot(peer)] += 1 at each step-1 pick
            # (node.py:739-744), keyed on the neighbor slot table.
            idx = jnp.arange(self.n_nodes)
            slot = self._slot_of(peers)
            sel = state.aux["selected"].at[idx, jnp.clip(slot, 0, self.max_deg - 1)
                                           ].add((active & (slot >= 0)).astype(jnp.int32))
            aux = dict(state.aux)
            aux["selected"] = sel
            state = state._replace(aux=aux)
        return active, state

    # -- receive ------------------------------------------------------------

    def _apply_receive(self, state: SimState, peer: PeerModel, extra, valid,
                       call_key) -> SimState:
        if self._step == 2:
            return super()._apply_receive(state, peer, extra, valid, call_key)

        n, S = self.n_nodes, self.n_sampled
        idx = jnp.arange(n)
        data = self._local_data()
        # Score the received model on local train data (node.py:775-777).
        acc = jax.vmap(
            lambda pm_params, d: self.handler.evaluate(
                ModelState(pm_params, None, jnp.int32(0)), d)["accuracy"]
        )(peer.params, data)
        loss = -acc

        aux = dict(state.aux)
        count = aux["cache_count"]
        sender_id = jnp.broadcast_to(extra, (n,))
        # The reference keys its buffer by sender, latest model wins
        # (node.py:777: ``self.cache[sender] = ...``): a repeat sender
        # overwrites its slot instead of consuming a new one.
        match = aux["cache_sender"] == sender_id[:, None]  # [N, S]
        exists = match.any(axis=1)
        pos = jnp.where(exists, jnp.argmax(match, axis=1),
                        jnp.clip(count, 0, S - 1))
        ok = valid & (exists | (count < S))

        def put(cache, new):
            upd = cache.at[idx, pos].set(new)
            return jnp.where(ok.reshape((-1,) + (1,) * (cache.ndim - 1)),
                             upd, cache)

        aux["cache_params"] = jax.tree.map(put, aux["cache_params"], peer.params)
        aux["cache_loss"] = put(aux["cache_loss"], loss)
        aux["cache_sender"] = put(aux["cache_sender"], sender_id)
        count = count + (ok & ~exists).astype(jnp.int32)

        # Flush full buffers: merge the m_top best + train (node.py:778-783).
        flush = count >= S
        order = jnp.argsort(aux["cache_loss"], axis=1)  # best (lowest loss) first
        top = order[:, : self.m_top]  # [N, m_top]

        def avg_leaf(self_p, cache_p):
            picked = jnp.take_along_axis(
                cache_p, top.reshape((n, self.m_top) + (1,) * (cache_p.ndim - 2)),
                axis=1)
            return (self_p + picked.sum(axis=1)) / (self.m_top + 1.0)

        merged_params = jax.tree.map(avg_leaf, state.model.params,
                                     aux["cache_params"])
        merged = ModelState(merged_params, state.model.opt_state,
                            state.model.n_updates)
        keys = jax.random.split(call_key, n)
        trained = jax.vmap(self.handler.update)(merged, data, keys)
        model = select_nodes(flush, trained, state.model)

        top_senders = jnp.take_along_axis(aux["cache_sender"], top, axis=1)
        # neigh_counter[i, slot(sender)] += 1 per flushed top model, keyed
        # on the neighbor slot table ([N, max_deg, m_top] match — each
        # sender id appears at most once per row, so the m_top-sum counts).
        match = self.nbr_table[:, :, None] == top_senders[:, None, :]
        hit = match & flush[:, None, None] & (top_senders >= 0)[:, None, :]
        aux["neigh_counter"] = aux["neigh_counter"] + \
            hit.sum(axis=-1).astype(jnp.int32)

        aux["cache_count"] = jnp.where(flush, 0, count)
        aux["cache_loss"] = jnp.where(flush[:, None], jnp.inf, aux["cache_loss"])
        aux["cache_sender"] = jnp.where(flush[:, None], -1, aux["cache_sender"])
        return state._replace(model=model, aux=aux)

    def _send_extra(self, key, state):
        # Receive hooks need the sender id as a payload field.
        return jnp.arange(self.n_nodes, dtype=jnp.int32)

    def _decode_extra(self, extra):
        return None if self._step == 2 else extra

    def _cache_salt(self):
        return self._step

    # -- phase-segmented run -------------------------------------------------

    def _select_neighbors(self, state: SimState) -> SimState:
        """Phase transition (node.py:728-733): best_j iff counter beats the
        base selection rate — per neighbor SLOT ([N, max_deg])."""
        thresh = self.m_top / self.n_sampled
        best = state.aux["neigh_counter"].astype(jnp.float32) > \
            state.aux["selected"].astype(jnp.float32) * thresh
        best = best & (self.nbr_table >= 0)
        aux = dict(state.aux)
        aux["best"] = best
        return state._replace(aux=aux)

    def start(self, state: SimState, n_rounds: int = 100,
              key: Optional[jax.Array] = None, **kwargs):
        if key is None:
            key = jax.random.PRNGKey(42)
        # The phase split follows GLOBAL simulation time (node.py:732-736:
        # ``t // round_len >= step1_rounds``), so continuing a run from a
        # carried state resumes in the right phase.
        round0 = int(np.asarray(state.round))
        r1 = max(0, min(self.step1_rounds - round0, n_rounds))
        reports = []
        if r1 > 0:
            self._step = 1
            state, rep1 = super().start(state, n_rounds=r1, key=key, **kwargs)
            reports.append(rep1)
        if n_rounds - r1 > 0:
            state = self._select_neighbors(state)
            self._step = 2
            state, rep2 = super().start(state, n_rounds=n_rounds - r1,
                                        key=jax.random.fold_in(key, 2),
                                        **kwargs)
            reports.append(rep2)
        if len(reports) == 1:
            return state, reports[0]
        return state, SimulationReport.concatenate(reports)


    def run_repetitions(self, n_rounds: int, keys, local_train: bool = True,
                        common_init: bool = False):
        """Phase-aware multi-seed runs (the base implementation scans all
        ``n_rounds`` in one program, which would never leave phase 1).

        Segment 1 reuses the base vmapped init+scan (``_cache_salt`` keys
        the jit cache by phase); the phase switch (``_select_neighbors``)
        broadcasts over the seed axis since it is a pure per-node function;
        segment 2 continues the stacked states under the phase-2 trace.

        Note: like :meth:`start`, the two-segment split treats round
        ``step1_rounds - 1`` as a segment-final round, which under
        ``eval_every > 1`` forces an evaluation at the phase boundary that
        one continuous ``n_rounds`` scan would skip — report rows can
        differ by that one extra eval row between the two code paths
        (round-4 advisor: accepted, the boundary eval is a feature — the
        phase-1 endpoint is exactly the curve point PENS studies care
        about).
        """
        assert not self._receivers_list(), \
            "run_repetitions does not support event receivers; use start()"
        r1 = max(0, min(self.step1_rounds, n_rounds))
        r2 = n_rounds - r1
        if r2 <= 0:
            self._step = 1
            return super().run_repetitions(n_rounds, keys, local_train,
                                           common_init)
        self._step = 1
        states, reports1 = super().run_repetitions(r1, keys, local_train,
                                                   common_init)
        states = jax.vmap(self._select_neighbors)(states)
        self._step = 2
        cache_k = ("reps_cont", r2, self._cache_salt())
        if cache_k not in self._jit_cache:
            def cont(state, key):
                k_run = jax.random.fold_in(jax.random.split(key)[1], 2)
                last = state.round + r2 - 1

                def body(s, _):
                    return self._round(s, k_run, last)

                return jax.lax.scan(body, state, None, length=r2)
            # Donate the stacked segment-1 states: the [S, D, N, ...]
            # history rings are the dominant term and the inputs are dead
            # after this call (start()'s donation policy, applied here).
            # The vmap binds BATCH_AXIS like every seed-batched round
            # program (base run_repetitions, the service megabatch): PENS
            # itself never compacts (_apply_receive override), but the
            # contract is uniform so a compact-capable subclass of this
            # variant would stay batch-uniform for free.
            self._jit_cache[cache_k] = jax.jit(
                jax.vmap(cont, axis_name=BATCH_AXIS), donate_argnums=(0,))
        saved_axis = self._batch_axis_name
        self._batch_axis_name = BATCH_AXIS
        try:
            states, stats2 = self._jit_cache[cache_k](states, keys)
        finally:
            self._batch_axis_name = saved_axis
        host2 = jax.tree.map(np.asarray, stats2)
        reports = []
        for i, rep1 in enumerate(reports1):
            rep2 = self._build_report(jax.tree.map(lambda a, i=i: a[i], host2))
            reports.append(SimulationReport.concatenate([rep1, rep2]))
        return states, reports


