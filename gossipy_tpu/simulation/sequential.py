# tracelint: disable-file=host-coerce,host-branch,np-in-trace,traced-slice
# This engine is EAGER by design: its Python tick loop runs on concrete
# device values, so host coercion (`int(jax.random.randint(...))`, numpy
# post-processing) is its contract, not a trace bug. The taint rules are
# disabled file-wide because tracelint's call graph cannot distinguish
# `sim.init_nodes(...)` on this class from the jitted engine's (both
# resolve through the same duck-typed call sites in the service
# scheduler). The donate/registry rules stay active.
"""Opt-in high-fidelity sequential engine for small-N verification studies.

The jitted bulk-synchronous engine (:mod:`.engine`) trades three fidelity
corners for compilability (PARITY.md divergence table):

1. per-ROUND observer granularity instead of the reference's per-message
   ``update_message`` events (reference gossipy/simul.py:37-88, notify at
   :401-407);
2. next-round delivery of token reactions instead of same-tick
   (simul.py:631-648 — a zero-delay reaction lands in the queue being
   drained and can cascade within the tick);
3. round-start snapshots instead of in-round sequential state — the
   reference's shuffled per-tick loop lets a node forward a model it
   merged earlier in the same tick (simul.py:389-451).

:class:`SequentialGossipSimulator` closes all three for populations small
enough that an eager event loop is affordable (hundreds of nodes, tens of
rounds): Python tick loop for *scheduling*, jitted single-node JAX calls
for the *math* (the same ``handler.call`` / ``handler.update`` the bulk
engine vmaps — one compile, reused for every event). It is a verification
instrument, not the performance path: use it to audit the bulk engine's
divergences on a config, then run the real study on the bulk engine.

Event-order contract (mirrors the reference tick loop, simul.py:384-451):
per tick ``t`` — (a) the send sweep over a per-round shuffled node order
(each sender snapshots its CURRENT model, including merges earlier in the
same tick); (b) the arrival drain for ``t`` (online check per receiver;
``handler.call``; replies and token reactions scheduled at ``t + delay``,
a zero delay landing back in the drain and cascading); (c) the reply
drain; (d) at round boundaries, evaluation + per-round events. Observers
additionally get a live ``update_single_message(failed, record)`` per
message, the per-message granularity the bulk engine cannot emit.

Documented divergences from the reference loop (deliberate, both
reference bugs): an isolated sender skips its send instead of aborting
the whole sweep (simul.py:398-399 ``break``), and token reactions
originate from the RECEIVER, not whatever node the send sweep last
touched (simul.py:640 reuses the stale loop variable; the bulk engine
fixes the same bug).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import AntiEntropyProtocol, ConstantDelay, CreateModelMode, \
    Delay, MessageType
from ..flow_control import TokenAccount
from ..handlers.base import BaseHandler, ModelState, PeerModel
from ..telemetry.health import (
    HealthCarry,
    SentinelConfig,
    health_round_stats,
)
from ..telemetry.probes import (
    ProbeConfig,
    consensus_stats,
    param_layer_names,
    sq_param_distance,
)
from .engine import PROTO_TO_MSG
from .events import SimulationEventSender
from .faults import (
    ChaosConfig,
    build_fault_schedule,
    chaos_round_stats,
)
from .report import SimulationReport

# Node-behavior variants the sequential engine can replicate eagerly for
# parity studies against the jitted subclasses (simulation.nodes).
SEQ_VARIANTS = ("passthrough", "cache_neigh")


@dataclass
class MessageRecord:
    """Per-message observer payload (the reference's ``Message`` view:
    core.py Message — timestamp/type/sender/receiver/size)."""

    t: int
    round: int
    sender: int
    receiver: int
    msg_type: MessageType
    size: int


@dataclass
class SeqState:
    """Eager-mode simulation state: one ModelState per node."""

    models: List[ModelState]
    phase: np.ndarray                  # [N] sync offset or async period
    balance: Optional[np.ndarray]      # [N] token balances (tokenized only)
    round: int = 0


@dataclass
class _Pending:
    """A scheduled delivery: the payload is the sender-at-send-time view."""

    rec: MessageRecord
    payload: Optional[PeerModel]       # None for PULL requests
    is_reply: bool = False


class SequentialGossipSimulator(SimulationEventSender):
    """Reference-faithful sequential gossip for small N (see module doc).

    Accepts the same core configuration as :class:`.engine.GossipSimulator`
    plus the tokenized options; pass ``token_account`` to enable
    Danner-2018 flow control with SAME-TICK reactive delivery.
    ``utility_fun(receiver_model: ModelState, sender_snapshot: PeerModel)
    -> float`` is the per-message utility (constant 1 default, the shipped
    experiment's choice, reference main_hegedus_2021.py:59).

    ``variant`` replicates a node-behavior subclass eagerly for parity
    studies (the ROADMAP fidelity corner): ``"passthrough"`` (Giaretta
    2019 degree-biased accept-or-adopt,
    :class:`~gossipy_tpu.simulation.PassThroughGossipSimulator`) or
    ``"cache_neigh"`` (one parked model slot per neighbor, popped and
    merged at send time,
    :class:`~gossipy_tpu.simulation.CacheNeighGossipSimulator`). Variant
    randomness (accept draws, cache pops) uses a DEDICATED host RNG so a
    variant run with accept probability pinned at 1 reproduces the
    vanilla trajectory bit-for-bit. Mutually exclusive with
    ``token_account``.

    ``chaos`` applies the same scheduled fault plane as the jitted
    engines (:mod:`.faults`): forced-outage windows (no sends, no
    receives; failures attributed to the ``"chaos"`` cause), per-round
    partition/churn edge masks constraining peer sampling, and
    drop/delay spikes — evaluated eagerly from the same compiled
    :class:`~gossipy_tpu.simulation.faults.FaultSchedule` tables, so
    jitted-vs-sequential chaos parity is testable per fault type.
    """

    def __init__(self,
                 handler: BaseHandler,
                 topology,
                 data: dict,
                 delta: int = 100,
                 protocol: AntiEntropyProtocol = AntiEntropyProtocol.PUSH,
                 drop_prob: float = 0.0,
                 online_prob: float = 1.0,
                 delay: Delay = ConstantDelay(0),
                 sampling_eval: float = 0.0,
                 sync: bool = True,
                 token_account: Optional[TokenAccount] = None,
                 utility_fun: Optional[Callable] = None,
                 probes=None,
                 sentinels=None,
                 variant: Optional[str] = None,
                 chaos=None):
        assert 0 <= drop_prob < 1 and 0 < online_prob <= 1
        if variant is not None and variant not in SEQ_VARIANTS:
            raise ValueError(f"unknown sequential variant {variant!r}; "
                             f"options: {SEQ_VARIANTS}")
        if variant is not None and token_account is not None:
            raise ValueError("variant= and token_account= are mutually "
                             "exclusive (the jitted engines compose them "
                             "via subclassing; the eager parity modes do "
                             "not)")
        self.variant = variant
        self.handler = handler
        self.topology = topology
        self.n_nodes = topology.num_nodes
        if self.n_nodes > 512:
            import warnings
            warnings.warn(
                "SequentialGossipSimulator is an eager verification mode; "
                f"{self.n_nodes} nodes will be slow — use GossipSimulator "
                "for studies at this scale.")
        self.delta = int(delta)
        self.protocol = protocol
        self.drop_prob = float(drop_prob)
        self.online_prob = float(online_prob)
        self.delay = delay
        self.sampling_eval = float(sampling_eval)
        self.sync = sync
        self.account = token_account
        self.utility_fun = utility_fun or (lambda recv, snap: 1.0)

        self.data = {k: np.asarray(v) for k, v in data.items()}
        self.has_local_test = "xte" in data
        self.has_global_eval = "x_eval" in data
        # Per-node out-neighbor lists (host ints; peer sampling is host-side
        # scheduling, like every other random draw in this engine).
        # reject_duplicates stays False: a multigraph row is harmless here —
        # like the reference, a duplicate edge just raises that peer's
        # sampling weight (only SLOT-KEYED variant state needs unique rows;
        # PENS/CacheNeigh opt into the rejection themselves).
        from .nodes import build_neighbor_table
        nbr = build_neighbor_table(topology)
        self._nbrs = [row[row >= 0] for row in nbr]
        if hasattr(handler, "get_size"):
            self._size = int(handler.get_size())
        else:
            # Parameter-count fallback, the bulk engine's _model_size rule
            # (reference Sizeable accounting, gossipy/__init__.py:134-156).
            st = jax.eval_shape(handler.init, jax.random.PRNGKey(0))
            self._size = sum(int(np.prod(l.shape))
                             for l in jax.tree_util.tree_leaves(st.params))
        # One jitted program per single-node op, reused for every event.
        self._jit_call = jax.jit(handler.call)
        self._jit_update = jax.jit(handler.update)
        self._jit_eval_batch = jax.jit(jax.vmap(handler.evaluate))
        # Gossip-dynamics probes (telemetry.probes): the SAME quantities
        # the jitted engine computes in-graph, here accumulated eagerly per
        # message/round — the verification side of probe-parity tests.
        self.probes: Optional[ProbeConfig] = ProbeConfig.coerce(probes)
        self._probe_delta_ok = (
            self.probes is not None and self.probes.mixing
            and handler.mode == CreateModelMode.MERGE_UPDATE
            and variant is None)
        if self._probe_delta_ok:
            self._jit_merge = jax.jit(handler.merge)
        if self.probes is not None:
            self._jit_sqdist = jax.jit(sq_param_distance)
            self._jit_consensus = jax.jit(consensus_stats)
        # Numerics sentinels (telemetry.health): the SAME per-round
        # vitals the jitted engine computes in-graph, here over eagerly
        # stacked round-boundary params — the verification side of the
        # jitted-vs-sequential health parity tests.
        self.sentinels: Optional[SentinelConfig] = \
            SentinelConfig.coerce(sentinels)
        # Cross-run divergence-EMA state, same contract as the jitted
        # engine: persists across start() calls, reset by init_nodes.
        self._health_carry: Optional[HealthCarry] = None
        # Scheduled fault injection: the SAME host-compiled schedule
        # tables the jitted engines index in-graph, consumed eagerly
        # here (numpy; rounds clamp to the trailing baseline row).
        self.chaos: Optional[ChaosConfig] = ChaosConfig.coerce(chaos)
        self._chaos_sched = None
        self._chaos_ncomp = 1
        self._chaos_nbr_cache: dict = {}
        if self.chaos is not None:
            self._chaos_sched = build_fault_schedule(
                self.chaos, topology, self.drop_prob)
            self._chaos_ncomp = self.chaos.max_components()
            self._jit_chaos_stats = jax.jit(
                lambda p, c: chaos_round_stats(p, c, self._chaos_ncomp))
            if self.chaos.has_edge_faults() and isinstance(
                    self._chaos_sched.slot_masks, np.ndarray):
                from .nodes import build_neighbor_table
                self._chaos_nbr_table = build_neighbor_table(topology)

        def eval_global(stacked, xe, ye, me):
            return jax.vmap(lambda m: handler.evaluate(m, (xe, ye, me)))(
                stacked)
        self._jit_eval_global = jax.jit(eval_global)
        self._metric_names: Optional[list] = None
        # Device-resident per-node training shards, sliced once.
        self._node_data_dev = [
            tuple(jnp.asarray(self.data[k][i])
                  for k in ("xtr", "ytr", "mtr"))
            for i in range(self.n_nodes)]
        # The constant global eval set and the stacked local test sets,
        # uploaded once (not per round).
        self._eval_set_dev = None
        if self.has_global_eval:
            xe = jnp.asarray(self.data["x_eval"])
            self._eval_set_dev = (xe, jnp.asarray(self.data["y_eval"]),
                                  jnp.ones(xe.shape[0], jnp.float32))
        self._test_set_dev = None
        if self.has_local_test:
            self._test_set_dev = tuple(jnp.asarray(self.data[k])
                                       for k in ("xte", "yte", "mte"))

    # -- setup -------------------------------------------------------------

    def _node_data(self, i: int):
        return self._node_data_dev[i]

    def init_nodes(self, key: jax.Array, local_train: bool = True,
                   common_init: bool = False) -> SeqState:
        n = self.n_nodes
        self._health_carry = None  # fresh population, fresh sentinel EMA
        k_init, k_phase, k_up = jax.random.split(key, 3)
        models = []
        for i in range(n):
            ki = k_init if common_init else jax.random.fold_in(k_init, i)
            st = self.handler.init(ki)
            if local_train:
                st = self._jit_update(st, self._node_data(i),
                                      jax.random.fold_in(k_up, i))
            models.append(st)
        rng = np.random.default_rng(int(jax.random.randint(
            k_phase, (), 0, 2 ** 31 - 1)))
        if self.sync:
            phase = rng.integers(0, self.delta, size=n)
        else:
            phase = np.maximum(
                (self.delta + (self.delta / 10.0)
                 * rng.standard_normal(n)).astype(np.int64), 1)
        balance = (np.asarray(self.account.init_balance(n)).copy()
                   if self.account is not None else None)
        # cache_neigh variant: one parked PeerModel per (receiver, sender),
        # latest wins — the eager counterpart of the jitted per-neighbor
        # slot cache. Host-side (reset with the population).
        self._cn_cache = [dict() for _ in range(n)]
        return SeqState(models=models, phase=phase, balance=balance)

    def _fires(self, state: SeqState, i: int, t: int) -> bool:
        if self.sync:
            return t % self.delta == int(state.phase[i])
        return t % int(state.phase[i]) == 0

    # -- chaos schedule reads (eager counterparts of the engine's traced
    # -- gathers; rounds clamp to the trailing baseline row) ----------------

    def _chaos_row(self, r: int) -> int:
        return min(int(r), self._chaos_sched.rows - 1)

    def _forced_at(self, r: int):
        return self._chaos_sched.forced_offline[self._chaos_row(r)]

    def _drop_prob_at(self, r: int) -> float:
        if self.chaos is None:
            return self.drop_prob
        return float(self._chaos_sched.drop_prob[self._chaos_row(r)])

    def _delay_scale_at(self, r: int) -> float:
        if self.chaos is None:
            return 1.0
        return float(self._chaos_sched.delay_scale[self._chaos_row(r)])

    def _alive_nbrs(self, i: int, r: int):
        """Node ``i``'s out-neighbors alive at round ``r`` (partition/
        churn edge masks applied; the static list when no edge fault is
        scheduled). Cached per (mask, node)."""
        if self.chaos is None or not self.chaos.has_edge_faults():
            return self._nbrs[i]
        m = int(self._chaos_sched.mask_idx[self._chaos_row(r)])
        if m == 0:
            return self._nbrs[i]
        key = (m, i)
        if key not in self._chaos_nbr_cache:
            sched = self._chaos_sched
            if isinstance(sched.edge_masks, np.ndarray):  # dense topology
                row = np.asarray(self.topology.adjacency[i]) \
                    & sched.edge_masks[m, i]
                self._chaos_nbr_cache[key] = np.where(row)[0]
            else:
                nbr = self._chaos_nbr_table[i]
                alive = sched.slot_masks[m, i] & (nbr >= 0)
                self._chaos_nbr_cache[key] = nbr[alive]
        return self._chaos_nbr_cache[key]

    def _metric_keys(self) -> list:
        if self._metric_names is None:
            d = (jnp.asarray(self.data["xtr"][0]),
                 jnp.asarray(self.data["ytr"][0]),
                 jnp.asarray(self.data["mtr"][0]))
            st = self.handler.init(jax.random.PRNGKey(0))
            self._metric_names = sorted(
                jax.eval_shape(lambda s: self.handler.evaluate(s, d),
                               st).keys())
        return self._metric_names

    # -- the tick loop ------------------------------------------------------

    def start(self, state: SeqState, n_rounds: int = 10,
              key: Optional[jax.Array] = None):
        """Run ``n_rounds * delta`` ticks; returns (state, report)."""
        key = jax.random.PRNGKey(42) if key is None else key
        # The tick loop is RELATIVE to this start() call; the chaos
        # schedule (like the jitted engine's) keys on ABSOLUTE rounds so
        # chunked continuation hits the same fault windows.
        round0 = int(state.round)
        # Split, don't fold: the host-scheduling seed must live in a key
        # space disjoint from next_key()'s fold_in(key, counter) draws.
        k_host, key = jax.random.split(key)
        rng = np.random.default_rng(
            int(jax.random.randint(k_host, (), 0, 2 ** 31 - 1)))
        # Variant randomness (accept draws, cache pops) lives on its OWN
        # stream: a variant whose draws are all no-ops (accept prob 1)
        # then reproduces the vanilla trajectory bit-for-bit.
        var_rng = np.random.default_rng(int(jax.random.randint(
            jax.random.fold_in(k_host, 7), (), 0, 2 ** 31 - 1)))
        names = self._metric_keys()
        n, delta = self.n_nodes, self.delta
        msg_q: dict = {}   # tick -> [_Pending]; mutated mid-drain by
        rep_q: dict = {}   # zero-delay replies/reactions (the reference's
                           # msg_queues/rep_queues DefaultDicts)
        sent_pr = np.zeros(n_rounds, np.int64)
        failed_pr = np.zeros(n_rounds, np.int64)
        # Per-cause breakdown (telemetry.FAILURE_CAUSES), kept column-
        # compatible with the bulk engine's traced counters. Overflow is
        # structurally zero here — the eager queues are unbounded, like the
        # reference's — but the column ships so reports from the two
        # engines stay directly comparable.
        drop_pr = np.zeros(n_rounds, np.int64)
        offline_pr = np.zeros(n_rounds, np.int64)
        overflow_pr = np.zeros(n_rounds, np.int64)
        size_pr = np.zeros(n_rounds, np.int64)
        if self.chaos is not None:
            chaos_pr = np.zeros(n_rounds, np.int64)
            chaos_gap_pr = np.zeros(n_rounds, np.float64)
            chaos_within_pr = np.zeros(n_rounds, np.float64)
            chaos_active_pr = np.zeros(n_rounds, np.int64)
        local_rows = np.full((n_rounds, len(names)), np.nan, np.float32)
        global_rows = np.full((n_rounds, len(names)), np.nan, np.float32)
        # Per-round probe accumulators (same definitions as the jitted
        # engine's traced ProbeAccum; telemetry.probes).
        probes = self.probes
        if probes is not None:
            B = probes.staleness_buckets
            acc_pr = np.zeros((n_rounds, n), np.int64)
            stale_sum_pr = np.zeros(n_rounds, np.int64)
            stale_max_pr = np.zeros(n_rounds, np.int64)
            stale_hist_pr = np.zeros((n_rounds, B), np.int64)
            merge_sq_pr = np.zeros(n_rounds, np.float64)
            train_sq_pr = np.zeros(n_rounds, np.float64)
            n_layers = len(param_layer_names(state.models[0].params))
            cons_mean = np.zeros(n_rounds, np.float64)
            cons_max = np.zeros(n_rounds, np.float64)
            cons_layers = np.zeros((n_rounds, n_layers), np.float64)
        sentinels = self.sentinels
        if sentinels is not None:
            L = len(param_layer_names(state.models[0].params))
            hc = (self._health_carry if self._health_carry is not None
                  else HealthCarry.zeros(n))
            h_nf_params = np.zeros((n_rounds, L), np.int64)
            h_nf_delta = np.zeros((n_rounds, L), np.int64)
            h_nf_metrics = np.zeros(n_rounds, np.int64)
            h_diverged = np.zeros((n_rounds, n), np.int64)
            h_norm_max = np.zeros(n_rounds, np.float64)
            h_delta_norm = np.zeros(n_rounds, np.float64)
            h_delta_hwm = np.zeros(n_rounds, np.float64)
            h_trip = np.zeros(n_rounds, np.int64)
            pre_params = None

            def stack_params():
                return jax.tree.map(lambda *ls: jnp.stack(ls),
                                    *[m.params for m in state.models])
        # ONE monotonically increasing event counter feeds every jax-side
        # draw (handler calls, delay samples): each draw gets a globally
        # unique fold, so no two events — same tick, same sender, or
        # different purposes — can share a stream.
        event_counter = 0

        def next_key():
            nonlocal event_counter
            event_counter += 1
            return jax.random.fold_in(key, event_counter)

        def schedule(rec: MessageRecord, payload, t: int, is_reply=False):
            """Drop/delay a just-sent message; count + notify.

            Replies are NOT counted here: the reference notifies replies
            only at their delivery drain (simul.py:425-429), so a dropped
            or never-delivered reply is never a "sent" message — only a
            failed one.
            """
            r = rec.round
            if not is_reply:
                sent_pr[r] += 1
                size_pr[r] += rec.size
                self._fire_message(False, rec)
            if rng.random() < self._drop_prob_at(round0 + rec.round):
                failed_pr[r] += 1
                drop_pr[r] += 1
                self._fire_message(True, rec)
                return
            d = int(np.asarray(self.delay.sample(next_key(), (1,),
                                                 rec.size))[0])
            d = int(d * self._delay_scale_at(round0 + rec.round))  # spike
            q = rep_q if is_reply else msg_q
            q.setdefault(t + d, []).append(_Pending(rec, payload, is_reply))

        msg_type = PROTO_TO_MSG[self.protocol]
        is_pull = self.protocol == AntiEntropyProtocol.PULL
        send_size = 1 if is_pull else self._size  # PULL requests carry no model

        def send_from(i: int, t: int, r: int):
            if self.variant == "cache_neigh" and self._cn_cache[i]:
                # Pop a random parked neighbor model and merge-update
                # before sending (the jitted _pre_send semantics).
                senders = list(self._cn_cache[i])
                pick = senders[var_rng.integers(len(senders))]
                pm = self._cn_cache[i].pop(pick)
                state.models[i] = self._jit_call(
                    state.models[i], pm, self._node_data(i), next_key(),
                    None)
            nbrs = self._alive_nbrs(i, round0 + r)
            if len(nbrs) == 0:
                return  # isolated node: skip (reference `break` aborts the
                        # whole sweep, simul.py:398-399 — a bug)
            peer = int(nbrs[rng.integers(len(nbrs))])
            payload = None if is_pull \
                else self.handler.peer_view(state.models[i])
            schedule(MessageRecord(t, r, i, peer, msg_type, send_size),
                     payload, t)

        def receive(p: _Pending, t: int, r: int, is_online) -> None:
            i = p.rec.receiver
            if self.chaos is not None and self._forced_at(round0 + r)[i]:
                # Scheduled outage: the receiver is forced offline —
                # the fourth ("chaos") failure cause, like the engine.
                failed_pr[r] += 1
                chaos_pr[r] += 1
                self._fire_message(True, p.rec)
                return
            if not is_online[i]:
                failed_pr[r] += 1
                offline_pr[r] += 1
                self._fire_message(True, p.rec)
                return
            if p.is_reply:
                # Replies count as sent at DELIVERY (reference
                # simul.py:425-429 notifies in the rep_queues drain).
                sent_pr[r] += 1
                size_pr[r] += p.rec.size
                self._fire_message(False, p.rec)
            carries_model = p.payload is not None
            wants_reply = p.rec.msg_type in (MessageType.PULL,
                                             MessageType.PUSH_PULL)
            if carries_model:
                if probes is not None:
                    # Accepted model-carrying merge: staleness in ROUNDS
                    # since the payload's model was captured (0 at zero
                    # delay), clamped into the histogram's last bucket —
                    # identical bookkeeping to ProbeAccum.record_slot.
                    stale = max(r - p.rec.round, 0)
                    acc_pr[r, i] += 1
                    stale_sum_pr[r] += stale
                    stale_max_pr[r] = max(stale_max_pr[r], stale)
                    stale_hist_pr[r, min(stale, B - 1)] += 1
                if self._probe_delta_ok:
                    before = state.models[i]
                    merged = self._jit_merge(before, p.payload)
                    new = self._jit_call(before, p.payload,
                                         self._node_data(i), next_key(),
                                         None)
                    merge_sq_pr[r] += float(self._jit_sqdist(
                        merged.params, before.params))
                    train_sq_pr[r] += float(self._jit_sqdist(
                        new.params, merged.params))
                    state.models[i] = new
                elif self.variant == "passthrough":
                    # Accept (merge+update) with p = min(1, deg_s/deg_r),
                    # else adopt the received model as-is (PASS) — the
                    # jitted PassThrough receive, degrees from the STATIC
                    # topology like the jitted variant's.
                    deg_r = max(int(self.topology.degrees[i]), 1)
                    deg_s = int(self.topology.degrees[p.rec.sender])
                    if var_rng.random() < min(1.0, deg_s / deg_r):
                        state.models[i] = self._jit_call(
                            state.models[i], p.payload, self._node_data(i),
                            next_key(), None)
                    else:
                        state.models[i] = ModelState(
                            p.payload.params, state.models[i].opt_state,
                            p.payload.n_updates)
                elif self.variant == "cache_neigh":
                    # Park instead of merging (latest wins per sender);
                    # popped + merged at the receiver's next send.
                    self._cn_cache[i][p.rec.sender] = p.payload
                else:
                    state.models[i] = self._jit_call(
                        state.models[i], p.payload, self._node_data(i),
                        next_key(), None)
            if wants_reply and not p.is_reply:
                # Reply carries the receiver's CURRENT (possibly just
                # merged) model — the sequential semantics the bulk engine
                # approximates with round-start snapshots.
                rep = MessageRecord(t, r, i, p.rec.sender, MessageType.REPLY,
                                    self._size)
                schedule(rep, self.handler.peer_view(state.models[i]), t,
                         is_reply=True)
            elif (self.account is not None and carries_model
                  and not p.is_reply):  # replies never react (reference
                                        # rep_queues drain has no reaction)
                # Token reaction (same tick; can cascade through the drain).
                util = float(self.utility_fun(state.models[i], p.payload))
                k = int(np.asarray(self.account.reactive(
                    jnp.asarray([state.balance[i]]),
                    jnp.asarray([util], jnp.float32), next_key()))[0])
                if k > 0:
                    # Reference fidelity: ALL reactive sends are emitted
                    # and the balance clamps at zero (simul.py:640-648 +
                    # flow_control sub()); the bulk engine instead caps
                    # sends at the balance — for the in-tree accounts,
                    # whose reactive() never exceeds it, the two agree.
                    state.balance[i] = max(0, int(state.balance[i]) - k)
                    for _ in range(k):
                        send_from(i, t, r)

        for t in range(n_rounds * delta):
            r = t // delta
            if t % delta == 0:
                order = rng.permutation(n)
                if sentinels is not None:
                    pre_params = stack_params()  # round-start snapshot
            # (a) send sweep over the round's shuffled order.
            for i in order:
                if not self._fires(state, int(i), t):
                    continue
                if self.chaos is not None \
                        and self._forced_at(round0 + r)[int(i)]:
                    continue  # scheduled outage: no sends either

                if self.account is not None:
                    p = float(np.asarray(self.account.proactive(
                        jnp.asarray([state.balance[int(i)]])))[0])
                    if rng.random() >= p:
                        state.balance[int(i)] += 1  # bank a token
                        continue
                send_from(int(i), t, r)
            # (b) arrival drain, then (c) reply drain — each reads its LIVE
            # queue list so a zero-delay reply/reaction scheduled mid-drain
            # is delivered this same tick and can cascade (the reference
            # appends to the list it iterates).
            is_online = rng.random(n) <= self.online_prob

            def drain(q):
                pending = q.get(t, [])
                idx = 0
                while idx < len(pending):
                    receive(pending[idx], t, r, is_online)
                    idx += 1
                q.pop(t, None)

            drain(msg_q)
            drain(rep_q)
            # (d) round boundary: evaluate + notify.
            if (t + 1) % delta == 0:
                loc, glob = self._evaluate(state, rng)
                if loc is not None:
                    local_rows[r] = loc
                if glob is not None:
                    global_rows[r] = glob
                if probes is not None and probes.consensus:
                    stacked = jax.tree.map(lambda *ls: jnp.stack(ls),
                                           *state.models)
                    cm, cx, cl = self._jit_consensus(stacked.params)
                    cons_mean[r] = float(cm)
                    cons_max[r] = float(cx)
                    cons_layers[r] = np.asarray(cl)
                if self.chaos is not None and probes is not None \
                        and probes.consensus:
                    sp = jax.tree.map(lambda *ls: jnp.stack(ls),
                                      *[m.params for m in state.models])
                    comp = jnp.asarray(self._chaos_sched.component_id[
                        self._chaos_row(round0 + r)])
                    cs = self._jit_chaos_stats(sp, comp)
                    chaos_gap_pr[r] = float(cs["chaos_component_gap"])
                    chaos_within_pr[r] = float(cs["chaos_within_mean"])
                    chaos_active_pr[r] = int(
                        cs["chaos_active_components"])
                if sentinels is not None:
                    # Same vitals definition as the jitted engine's scan
                    # body (health_round_stats is the shared pure math).
                    hc, hstats = health_round_stats(
                        sentinels, hc, pre_params, stack_params(),
                        jnp.asarray(local_rows[r]),
                        jnp.asarray(global_rows[r]))
                    if sentinels.nonfinite:
                        h_nf_params[r] = np.asarray(
                            hstats["health_nonfinite_params"])
                        h_nf_delta[r] = np.asarray(
                            hstats["health_nonfinite_delta"])
                        h_nf_metrics[r] = int(
                            hstats["health_nonfinite_metrics"])
                    if sentinels.divergence:
                        h_diverged[r] = np.asarray(
                            hstats["health_diverged_per_node"])
                        h_norm_max[r] = float(
                            hstats["health_param_norm_max"])
                    h_delta_norm[r] = float(hstats["health_delta_norm"])
                    h_delta_hwm[r] = float(hstats["health_delta_hwm"])
                    h_trip[r] = int(hstats["health_trip"])
                    self._health_carry = hc
                state.round += 1

        extras: dict = {}
        if probes is not None:
            if probes.consensus:
                extras["probe_consensus_mean"] = cons_mean
                extras["probe_consensus_max"] = cons_max
                extras["probe_consensus_per_layer"] = cons_layers
                extras["probe_layer_names"] = param_layer_names(
                    state.models[0].params)
            if probes.staleness:
                counts = stale_hist_pr.sum(axis=1)
                extras["probe_stale_mean"] = (
                    stale_sum_pr / np.maximum(counts, 1)).astype(np.float64)
                extras["probe_stale_max"] = stale_max_pr
                extras["probe_stale_hist"] = stale_hist_pr
            if probes.mixing:
                extras["probe_accepted_per_node"] = acc_pr
                if self._probe_delta_ok:
                    extras["probe_merge_delta"] = np.sqrt(merge_sq_pr)
                    extras["probe_train_delta"] = np.sqrt(train_sq_pr)
                else:
                    nan_pr = np.full(n_rounds, np.nan)
                    extras["probe_merge_delta"] = nan_pr
                    extras["probe_train_delta"] = nan_pr.copy()
                extras["probe_expected_fanin"] = self._probe_expected_fanin()
        if self.chaos is not None and probes is not None \
                and probes.consensus:
            extras["chaos_component_gap"] = chaos_gap_pr
            extras["chaos_within_mean"] = chaos_within_pr
            extras["chaos_active_components"] = chaos_active_pr
        if sentinels is not None:
            if sentinels.nonfinite:
                extras["health_nonfinite_params"] = h_nf_params
                extras["health_nonfinite_delta"] = h_nf_delta
                extras["health_nonfinite_metrics"] = h_nf_metrics
                extras["health_layer_names"] = param_layer_names(
                    state.models[0].params)
            if sentinels.divergence:
                extras["health_diverged_per_node"] = h_diverged
                extras["health_param_norm_max"] = h_norm_max
            extras["health_delta_norm"] = h_delta_norm
            extras["health_delta_hwm"] = h_delta_hwm
            extras["health_trip"] = h_trip
        causes = {"drop": drop_pr, "offline": offline_pr,
                  "overflow": overflow_pr}
        if self.chaos is not None:
            causes["chaos"] = chaos_pr
        report = SimulationReport(
            metric_names=names,
            local_evals=local_rows if self.has_local_test else None,
            global_evals=global_rows if self.has_global_eval else None,
            sent=sent_pr, failed=failed_pr, total_size=int(size_pr.sum()),
            failed_by_cause=causes,
            **extras)
        self.replay_events(state.round - n_rounds, {
            "sent": sent_pr, "failed": failed_pr,
            "failed_drop": drop_pr, "failed_offline": offline_pr,
            "failed_overflow": overflow_pr, "size": size_pr,
            **({"failed_chaos": chaos_pr} if self.chaos is not None
               else {}),
            "local": local_rows, "global": global_rows,
            # Per-round probe/health arrays ride the same replay so
            # receivers get update_probes/update_health from this engine
            # too (static context excluded).
            **{k: v for k, v in extras.items()
               if k not in ("probe_layer_names", "probe_expected_fanin",
                            "health_layer_names")}},
            names)
        return state, report

    def _probe_expected_fanin(self) -> np.ndarray:
        """[N] expected accepted merges per node per round under this
        engine's uniform neighbor-list sampling (the jitted engine's
        ``_expected_fanin_vector`` semantics), thinned by drop/online."""
        lam = np.zeros(self.n_nodes)
        for j, nb in enumerate(self._nbrs):
            if len(nb):
                np.add.at(lam, np.asarray(nb), 1.0 / len(nb))
        return lam * (1.0 - self.drop_prob) * self.online_prob

    def run_repetitions(self, n_rounds: int, keys,
                        local_train: bool = True,
                        common_init: bool = False):
        """API parity with :meth:`GossipSimulator.run_repetitions`: one run
        per seed. Eager mode has no seed-vmap to exploit, so repetitions
        execute sequentially (this is the verification engine — use the
        bulk engine for multi-seed studies at speed). Returns
        ``(list of final SeqStates, [SimulationReport])``."""
        states, reports = [], []
        for key in keys:
            k_init, k_run = jax.random.split(key)
            st = self.init_nodes(k_init, local_train=local_train,
                                 common_init=common_init)
            st, rep = self.start(st, n_rounds=n_rounds,
                                 key=jax.random.fold_in(k_run, 2))
            states.append(st)
            reports.append(rep)
        return states, reports

    def _fire_message(self, failed: bool, rec: MessageRecord) -> None:
        # update_single_message is a no-op default on the receiver base
        # class (events.py) — call it directly, no feature probing.
        for rx in self._receivers_list():
            rx.update_single_message(failed, rec)

    def _evaluate(self, state: SeqState, rng):
        names = self._metric_keys()
        n = self.n_nodes
        if self.sampling_eval > 0:
            pick = rng.choice(n, max(int(n * self.sampling_eval), 1),
                              replace=False)
        else:
            pick = np.arange(n)
        stacked = jax.tree.map(lambda *ls: jnp.stack(ls),
                               *[state.models[i] for i in pick])
        loc = None
        if self.has_local_test:
            idx = jnp.asarray(pick)
            d = tuple(a[idx] for a in self._test_set_dev)  # device gather
            res = self._jit_eval_batch(stacked, d)
            has_test = self.data["mte"][pick].sum(axis=1) > 0
            if has_test.any():
                vals = np.stack([np.asarray(res[k]) for k in names], -1)
                loc = vals[has_test].mean(0)
        glob = None
        if self.has_global_eval:
            xe, ye, me = self._eval_set_dev
            res = self._jit_eval_global(stacked, xe, ye, me)
            glob = np.stack([np.asarray(res[k]) for k in names], -1).mean(0)
        return loc, glob
