"""Simulation reporting: host-side view over the engine's traced accumulators.

The reference uses an Observer pattern (``SimulationEventReceiver`` /
``SimulationReport``, gossipy/simul.py:37-270) with per-message callbacks.
A jitted engine cannot call back per message, so the engine emits per-round
arrays (message counters, mean metrics) from the scan, and this module wraps
them in an API-compatible report: ``get_evaluation(local)`` returns the
``[(round, {metric: mean})]`` list the reference produces
(simul.py:262-266).

Telemetry extensions beyond the reference's report:

- ``failed_per_cause``: the per-round failure breakdown
  (:data:`~gossipy_tpu.telemetry.FAILURE_CAUSES`: drop / offline /
  overflow) whose per-round sum equals ``failed_per_round`` bit-for-bit.
- ``mailbox_hwm_per_round`` / ``compact_slots_per_round`` /
  ``wide_slots_per_round``: mailbox occupancy high-water mark and the
  compact-vs-wide delivery-path indicator (engine runs only; None from
  engines without a mailbox).
- gossip-dynamics probe arrays (``probe_*``; present when the run was
  started with ``probes=`` — see :mod:`gossipy_tpu.telemetry.probes`):
  consensus distance (mean/max/per-layer), merge-staleness distribution
  (mean/max/histogram), per-node accepted-merge counts and the
  merge-delta vs train-delta norms.
- ``wall_clock_seconds_per_round`` / ``rounds_per_sec_ema``: host timing
  captured through the live io_callback path (None for non-live runs).
- ``to_dict()`` / ``save(path)`` / ``from_dict()`` / ``load(path)``: a
  JSON-able, round-trippable run record (strict JSON: NaN rows → nulls).

Optional per-round arrays are REGISTRY-driven (:data:`PER_ROUND_FIELDS` /
:data:`STATIC_FIELDS`): ``to_dict``, ``from_dict`` and ``concatenate`` all
iterate the registry, so a newly added per-round array can never be
silently dropped by one of them — adding a field is one registry line
(tests assert every array attribute survives the
save → load → concatenate round trip).
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np

# 1: sent/failed/size/evals; 2: + cause breakdown & mailbox/compact diag;
# 3: + gossip-dynamics probe arrays (probe_*) and the static probe context;
# 4: + numerics-sentinel health arrays (health_*; telemetry.health);
# 5: + scheduled-fault chaos arrays (chaos_*; simulation.faults) and the
#    optional "chaos" key in failed_per_cause;
# 6: + performance arrays (perf_*; telemetry.cost) — host-measured
#    ms/round and the per-round MFU estimate;
# 7: + active-cohort accounting arrays (cohort_*; simulation.cohort) —
#    pool coverage fraction and the materialized cohort width per round.
REPORT_SCHEMA = 7

# Optional per-round arrays (attribute name == JSON key), concatenated
# along axis 0 by :meth:`SimulationReport.concatenate` (surviving only
# when EVERY segment carries them) and round-tripped by
# ``to_dict``/``from_dict``. int-valued entries round-trip as ints; float
# entries may carry NaN (serialized as null).
PER_ROUND_FIELDS = (
    "mailbox_hwm_per_round",
    "compact_slots_per_round",
    "wide_slots_per_round",
    "probe_consensus_mean",          # [R] f32
    "probe_consensus_max",           # [R] f32
    "probe_consensus_per_layer",     # [R, L] f32
    "probe_stale_mean",              # [R] f32
    "probe_stale_max",               # [R] i32
    "probe_stale_hist",              # [R, B] i32; rows sum to accepted count
    "probe_accepted_per_node",       # [R, N] i32
    "probe_merge_delta",             # [R] f32 (NaN when not decomposable)
    "probe_train_delta",             # [R] f32
    "health_nonfinite_params",       # [R, L] i32: non-finite count per leaf
    "health_nonfinite_delta",        # [R, L] i32: ... on the round delta
    "health_nonfinite_metrics",      # [R] i32: ... in evaluated metric rows
    "health_first_bad_slot",         # [R] i32: first deliver slot whose
                                     # merge introduced a non-finite; -1 clean
    "health_mix_nonfinite",          # [R] i32 (All2All): non-finite mixing
                                     # weights this round
    "health_diverged_per_node",      # [R, N] i32: norm-vs-EMA flags
    "health_param_norm_max",         # [R] f32
    "health_delta_norm",             # [R] f32: round movement L2
    "health_delta_hwm",              # [R] f32: running high-water mark
    "health_mailbox_hwm_run",        # [R] i32: run-level saturation watermark
    "health_trip",                   # [R] i32: any sentinel tripped
    "chaos_component_gap",           # [R] f32: max distance between
                                     # scheduled-component mean params
    "chaos_within_mean",             # [R] f32: mean distance of nodes from
                                     # their own component's mean
    "chaos_active_components",       # [R] i32: non-empty components
    "perf_round_ms",                 # [R] f64: host-measured wall ms per
                                     # round (uniform within one start()
                                     # segment; perf= runs only)
    "perf_mfu_est",                  # [R] f32: flops/round vs the chip
                                     # peak (NaN off known accelerators)
    "cohort_coverage",               # [R] f32: fraction of the nominal
                                     # pool touched by any cohort so far
                                     # (cohort runs only)
    "cohort_active_nodes",           # [R] i32: materialized cohort width
                                     # C (cohort runs only)
    "wall_clock_seconds_per_round",  # [R] f64 (live runs only)
)

# Static (non-per-round) optional fields: carried from the FIRST segment by
# ``concatenate`` and round-tripped verbatim by ``to_dict``/``from_dict``.
STATIC_FIELDS = (
    "probe_layer_names",      # [L] list[str]: consensus per-layer ordering
    "probe_expected_fanin",   # [N] f64: topology's expected accepted fan-in
    "health_layer_names",     # [L] list[str]: health per-leaf ordering
)

# Integer-valued per-round fields (restored as int arrays by from_dict).
_INT_FIELDS = frozenset({
    "mailbox_hwm_per_round", "compact_slots_per_round",
    "wide_slots_per_round", "probe_stale_max", "probe_stale_hist",
    "probe_accepted_per_node",
    "health_nonfinite_params", "health_nonfinite_delta",
    "health_nonfinite_metrics", "health_first_bad_slot",
    "health_mix_nonfinite", "health_diverged_per_node",
    "health_mailbox_hwm_run", "health_trip",
    "chaos_active_components", "cohort_active_nodes",
})


class SimulationReport:
    """Results of a simulation run.

    Parameters mirror what the engine's scan emits:

    - ``metric_names``: static ordering of the metric dict keys
    - ``local_evals`` / ``global_evals``: float arrays [R, M] of per-round
      mean metric values (NaN where no eval ran)
    - ``sent`` / ``failed``: int arrays [R] of messages generated / lost
      (drop, churn, mailbox overflow) per round
    - ``total_size``: cumulative message size in "atomic scalar" units, the
      reference's ``Sizeable`` accounting (gossipy/__init__.py:134-156)
    - ``failed_by_cause``: optional {cause: [R] int array} breakdown whose
      per-round sum equals ``failed``
    - ``mailbox_hwm`` / ``compact_slots`` / ``wide_slots``: optional [R]
      engine diagnostics (see the engine's ``_deliver_phase``)
    - ``**extras``: any field named in :data:`PER_ROUND_FIELDS` /
      :data:`STATIC_FIELDS` (the probe arrays land here); unknown names
      raise.
    """

    def __init__(self,
                 metric_names: list[str],
                 local_evals: Optional[np.ndarray],
                 global_evals: Optional[np.ndarray],
                 sent: np.ndarray,
                 failed: np.ndarray,
                 total_size: int,
                 failed_by_cause: Optional[dict] = None,
                 mailbox_hwm: Optional[np.ndarray] = None,
                 compact_slots: Optional[np.ndarray] = None,
                 wide_slots: Optional[np.ndarray] = None,
                 **extras):
        self.metric_names = list(metric_names)
        self._local = local_evals
        self._global = global_evals
        self.sent_messages = int(np.sum(sent))
        self.failed_messages = int(np.sum(failed))
        self.sent_per_round = np.asarray(sent)
        self.failed_per_round = np.asarray(failed)
        self.total_size = int(total_size)
        self.failed_per_cause: Optional[dict] = (
            {k: np.asarray(v) for k, v in failed_by_cause.items()}
            if failed_by_cause is not None else None)
        # Registry-driven optional fields: every name defaults to None,
        # then the legacy named params and **extras fill them in.
        for name in PER_ROUND_FIELDS + STATIC_FIELDS:
            setattr(self, name, None)
        legacy = {"mailbox_hwm_per_round": mailbox_hwm,
                  "compact_slots_per_round": compact_slots,
                  "wide_slots_per_round": wide_slots}
        for name, val in {**legacy, **extras}.items():
            if name not in PER_ROUND_FIELDS and name not in STATIC_FIELDS:
                raise TypeError(
                    f"unknown report field {name!r}; add it to "
                    "PER_ROUND_FIELDS/STATIC_FIELDS so to_dict/concatenate "
                    "cannot silently drop it")
            if val is None:
                continue
            if name in PER_ROUND_FIELDS:
                val = np.asarray(val)
            setattr(self, name, val)
        # Host wall-clock EMA (live io_callback runs only; attach_wall_clock).
        self.rounds_per_sec_ema: Optional[float] = None

    def attach_wall_clock(self, t_start: float, round_times: list,
                          ema_alpha: float = 0.1) -> None:
        """Derive per-round wall-clock and a rounds/sec EMA from the host
        timestamps the live io_callback collected (one per round boundary,
        measured from ``t_start`` = just before dispatch). The first
        interval includes compile time on a cold run — the EMA seeds from
        the SECOND round when there is one, so a cold compile does not
        poison the steady-state rate."""
        ts = np.asarray([t_start] + list(round_times), dtype=np.float64)
        per_round = np.diff(ts)
        if per_round.size == 0:
            return
        self.wall_clock_seconds_per_round = per_round
        rates = 1.0 / np.maximum(per_round, 1e-9)
        ema = rates[1] if rates.size > 1 else rates[0]
        for v in rates[2:]:
            ema = (1.0 - ema_alpha) * ema + ema_alpha * v
        self.rounds_per_sec_ema = float(ema)

    def _to_rounds(self, arr: Optional[np.ndarray]):
        if arr is None:
            return []
        out = []
        for r in range(arr.shape[0]):
            row = arr[r]
            if np.all(np.isnan(row)):
                continue
            out.append((r + 1, {k: float(v) for k, v in zip(self.metric_names, row)}))
        return out

    def get_evaluation(self, local: bool = True):
        """[(round, {metric: mean})] — API parity with reference simul.py:262-266."""
        return self._to_rounds(self._local if local else self._global)

    def curves(self, local: bool = True,
               drop_nan: bool = True) -> dict[str, np.ndarray]:
        """{metric: array} convenience view for plotting/benchmarks.

        ``drop_nan=True`` (default) removes rounds where no evaluation ran
        (``eval_every > 1`` skips), so ``curves(...)["accuracy"][-1]`` is
        always the LAST EVALUATED value; the matching round numbers are
        ``eval_rounds(local)``. Pass ``drop_nan=False`` for row-per-round
        arrays aligned with ``sent_per_round``.
        """
        arr = self._local if local else self._global
        if arr is None:
            return {}
        if drop_nan:
            keep = ~np.all(np.isnan(arr), axis=1)
            arr = arr[keep]
        return {k: arr[:, i] for i, k in enumerate(self.metric_names)}

    def eval_rounds(self, local: bool = True) -> np.ndarray:
        """1-based round numbers where evaluation ran (rows of ``curves``)."""
        arr = self._local if local else self._global
        if arr is None:
            return np.zeros((0,), dtype=int)
        return np.nonzero(~np.all(np.isnan(arr), axis=1))[0] + 1

    def final(self, metric: str, local: bool = False) -> float:
        """Last evaluated value of ``metric``; NaN when the metric was never
        evaluated OR is not one this run's handler produces (an unknown
        name is an empty series, not an exception — callers probe
        uniformly across handler types)."""
        arr = self._local if local else self._global
        if arr is None or metric not in self.metric_names:
            return float("nan")
        col = arr[:, self.metric_names.index(metric)]
        col = col[~np.isnan(col)]
        return float(col[-1]) if len(col) else float("nan")

    def to_dict(self) -> dict:
        """The full run record as JSON-able primitives (strict JSON: every
        NaN — skipped-eval metric rows, non-decomposable probe deltas —
        becomes null). Optional per-round/static fields are emitted from
        the module registry, so new fields cannot be forgotten here."""
        def scrub(x):
            if isinstance(x, list):
                return [scrub(v) for v in x]
            if isinstance(x, float) and np.isnan(x):
                return None
            return x

        def arr(a):
            return None if a is None else scrub(np.asarray(a).tolist())
        out = {
            "schema": REPORT_SCHEMA,
            "metric_names": self.metric_names,
            "sent_messages": self.sent_messages,
            "failed_messages": self.failed_messages,
            "total_size": self.total_size,
            "sent_per_round": arr(self.sent_per_round),
            "failed_per_round": arr(self.failed_per_round),
            "failed_per_cause": (
                {k: arr(v) for k, v in self.failed_per_cause.items()}
                if self.failed_per_cause is not None else None),
            "local_evals": arr(self._local),
            "global_evals": arr(self._global),
            "rounds_per_sec_ema": self.rounds_per_sec_ema,
        }
        for name in PER_ROUND_FIELDS:
            out[name] = arr(getattr(self, name))
        for name in STATIC_FIELDS:
            val = getattr(self, name)
            out[name] = (arr(val) if isinstance(val, np.ndarray)
                         else scrub(val) if isinstance(val, list) else val)
        return out

    def save(self, path: str) -> str:
        """Write :meth:`to_dict` as JSON to ``path``."""
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh, indent=2, allow_nan=False)
            fh.write("\n")
        return path

    @classmethod
    def from_dict(cls, d: dict) -> "SimulationReport":
        """Rebuild a report from :meth:`to_dict` output (any schema
        version; absent fields come back None, nulls inside float arrays
        come back NaN)."""
        def unscrub(x):
            if isinstance(x, list):
                return [unscrub(v) for v in x]
            return np.nan if x is None else x

        def farr(v):
            return None if v is None else np.asarray(unscrub(v), np.float64)

        def opt(name):
            v = d.get(name)
            if v is None:
                return None
            if name in _INT_FIELDS:
                return np.asarray(v, np.int64)
            return np.asarray(unscrub(v), np.float64)

        causes = d.get("failed_per_cause")
        extras = {name: opt(name) for name in PER_ROUND_FIELDS}
        for name in STATIC_FIELDS:
            v = d.get(name)
            if v is None:
                continue
            extras[name] = (np.asarray(v, np.float64)
                            if name == "probe_expected_fanin" else list(v))
        rep = cls(
            metric_names=list(d["metric_names"]),
            local_evals=farr(d.get("local_evals")),
            global_evals=farr(d.get("global_evals")),
            sent=np.asarray(d["sent_per_round"], np.int64),
            failed=np.asarray(d["failed_per_round"], np.int64),
            total_size=int(d["total_size"]),
            failed_by_cause=({k: np.asarray(v, np.int64)
                              for k, v in causes.items()}
                             if causes is not None else None),
            **{k: v for k, v in extras.items() if v is not None})
        if d.get("rounds_per_sec_ema") is not None:
            rep.rounds_per_sec_ema = float(d["rounds_per_sec_ema"])
        return rep

    @classmethod
    def load(cls, path: str) -> "SimulationReport":
        """Read a report written by :meth:`save`."""
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    @classmethod
    def concatenate(cls, reports: list) -> "SimulationReport":
        """Stitch consecutive run segments (e.g. the PENS phase split) into
        one report. Optional per-round arrays (module registry) survive
        only when EVERY segment carries them; static fields carry over
        from the first segment."""
        def cat(arrs):
            arrs = [a for a in arrs if a is not None]
            return np.concatenate(arrs) if arrs else None

        def cat_all(key):
            vals = [getattr(r, key, None) for r in reports]
            if any(v is None for v in vals):
                return None
            return np.concatenate(vals)

        causes = None
        if all(r.failed_per_cause is not None for r in reports):
            keys = reports[0].failed_per_cause.keys()
            causes = {k: np.concatenate([r.failed_per_cause[k]
                                         for r in reports]) for k in keys}
        extras = {name: cat_all(name) for name in PER_ROUND_FIELDS}
        for name in STATIC_FIELDS:
            extras[name] = getattr(reports[0], name, None)
        return cls(
            metric_names=reports[0].metric_names,
            local_evals=cat([r._local for r in reports]),
            global_evals=cat([r._global for r in reports]),
            sent=np.concatenate([r.sent_per_round for r in reports]),
            failed=np.concatenate([r.failed_per_round for r in reports]),
            total_size=sum(r.total_size for r in reports),
            failed_by_cause=causes,
            **{k: v for k, v in extras.items() if v is not None})

    def __str__(self) -> str:
        return json.dumps({
            "sent_messages": self.sent_messages,
            "failed_messages": self.failed_messages,
            "total_size": self.total_size,
            "rounds": 0 if self._local is None and self._global is None
                      else int((self._local if self._local is not None
                                else self._global).shape[0]),
            "metrics": self.metric_names,
        }, indent=2)
