"""Simulation reporting: host-side view over the engine's traced accumulators.

The reference uses an Observer pattern (``SimulationEventReceiver`` /
``SimulationReport``, gossipy/simul.py:37-270) with per-message callbacks.
A jitted engine cannot call back per message, so the engine emits per-round
arrays (message counters, mean metrics) from the scan, and this module wraps
them in an API-compatible report: ``get_evaluation(local)`` returns the
``[(round, {metric: mean})]`` list the reference produces
(simul.py:262-266).

Telemetry extensions beyond the reference's report:

- ``failed_per_cause``: the per-round failure breakdown
  (:data:`~gossipy_tpu.telemetry.FAILURE_CAUSES`: drop / offline /
  overflow) whose per-round sum equals ``failed_per_round`` bit-for-bit.
- ``mailbox_hwm_per_round`` / ``compact_slots_per_round`` /
  ``wide_slots_per_round``: mailbox occupancy high-water mark and the
  compact-vs-wide delivery-path indicator (engine runs only; None from
  engines without a mailbox).
- ``wall_clock_seconds_per_round`` / ``rounds_per_sec_ema``: host timing
  captured through the live io_callback path (None for non-live runs).
- ``to_dict()`` / ``save(path)``: a JSON-able run record (strict JSON:
  NaN metric rows become nulls).
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np

REPORT_SCHEMA = 2  # 1: sent/failed/size/evals; 2: + cause breakdown & diag


class SimulationReport:
    """Results of a simulation run.

    Parameters mirror what the engine's scan emits:

    - ``metric_names``: static ordering of the metric dict keys
    - ``local_evals`` / ``global_evals``: float arrays [R, M] of per-round
      mean metric values (NaN where no eval ran)
    - ``sent`` / ``failed``: int arrays [R] of messages generated / lost
      (drop, churn, mailbox overflow) per round
    - ``total_size``: cumulative message size in "atomic scalar" units, the
      reference's ``Sizeable`` accounting (gossipy/__init__.py:134-156)
    - ``failed_by_cause``: optional {cause: [R] int array} breakdown whose
      per-round sum equals ``failed``
    - ``mailbox_hwm`` / ``compact_slots`` / ``wide_slots``: optional [R]
      engine diagnostics (see the engine's ``_deliver_phase``)
    """

    def __init__(self,
                 metric_names: list[str],
                 local_evals: Optional[np.ndarray],
                 global_evals: Optional[np.ndarray],
                 sent: np.ndarray,
                 failed: np.ndarray,
                 total_size: int,
                 failed_by_cause: Optional[dict] = None,
                 mailbox_hwm: Optional[np.ndarray] = None,
                 compact_slots: Optional[np.ndarray] = None,
                 wide_slots: Optional[np.ndarray] = None):
        self.metric_names = list(metric_names)
        self._local = local_evals
        self._global = global_evals
        self.sent_messages = int(np.sum(sent))
        self.failed_messages = int(np.sum(failed))
        self.sent_per_round = np.asarray(sent)
        self.failed_per_round = np.asarray(failed)
        self.total_size = int(total_size)
        self.failed_per_cause: Optional[dict] = (
            {k: np.asarray(v) for k, v in failed_by_cause.items()}
            if failed_by_cause is not None else None)
        self.mailbox_hwm_per_round = (
            np.asarray(mailbox_hwm) if mailbox_hwm is not None else None)
        self.compact_slots_per_round = (
            np.asarray(compact_slots) if compact_slots is not None else None)
        self.wide_slots_per_round = (
            np.asarray(wide_slots) if wide_slots is not None else None)
        # Host wall-clock (live io_callback runs only; attach_wall_clock).
        self.wall_clock_seconds_per_round: Optional[np.ndarray] = None
        self.rounds_per_sec_ema: Optional[float] = None

    def attach_wall_clock(self, t_start: float, round_times: list,
                          ema_alpha: float = 0.1) -> None:
        """Derive per-round wall-clock and a rounds/sec EMA from the host
        timestamps the live io_callback collected (one per round boundary,
        measured from ``t_start`` = just before dispatch). The first
        interval includes compile time on a cold run — the EMA seeds from
        the SECOND round when there is one, so a cold compile does not
        poison the steady-state rate."""
        ts = np.asarray([t_start] + list(round_times), dtype=np.float64)
        per_round = np.diff(ts)
        if per_round.size == 0:
            return
        self.wall_clock_seconds_per_round = per_round
        rates = 1.0 / np.maximum(per_round, 1e-9)
        ema = rates[1] if rates.size > 1 else rates[0]
        for v in rates[2:]:
            ema = (1.0 - ema_alpha) * ema + ema_alpha * v
        self.rounds_per_sec_ema = float(ema)

    def _to_rounds(self, arr: Optional[np.ndarray]):
        if arr is None:
            return []
        out = []
        for r in range(arr.shape[0]):
            row = arr[r]
            if np.all(np.isnan(row)):
                continue
            out.append((r + 1, {k: float(v) for k, v in zip(self.metric_names, row)}))
        return out

    def get_evaluation(self, local: bool = True):
        """[(round, {metric: mean})] — API parity with reference simul.py:262-266."""
        return self._to_rounds(self._local if local else self._global)

    def curves(self, local: bool = True,
               drop_nan: bool = True) -> dict[str, np.ndarray]:
        """{metric: array} convenience view for plotting/benchmarks.

        ``drop_nan=True`` (default) removes rounds where no evaluation ran
        (``eval_every > 1`` skips), so ``curves(...)["accuracy"][-1]`` is
        always the LAST EVALUATED value; the matching round numbers are
        ``eval_rounds(local)``. Pass ``drop_nan=False`` for row-per-round
        arrays aligned with ``sent_per_round``.
        """
        arr = self._local if local else self._global
        if arr is None:
            return {}
        if drop_nan:
            keep = ~np.all(np.isnan(arr), axis=1)
            arr = arr[keep]
        return {k: arr[:, i] for i, k in enumerate(self.metric_names)}

    def eval_rounds(self, local: bool = True) -> np.ndarray:
        """1-based round numbers where evaluation ran (rows of ``curves``)."""
        arr = self._local if local else self._global
        if arr is None:
            return np.zeros((0,), dtype=int)
        return np.nonzero(~np.all(np.isnan(arr), axis=1))[0] + 1

    def final(self, metric: str, local: bool = False) -> float:
        """Last evaluated value of ``metric``; NaN when the metric was never
        evaluated OR is not one this run's handler produces (an unknown
        name is an empty series, not an exception — callers probe
        uniformly across handler types)."""
        arr = self._local if local else self._global
        if arr is None or metric not in self.metric_names:
            return float("nan")
        col = arr[:, self.metric_names.index(metric)]
        col = col[~np.isnan(col)]
        return float(col[-1]) if len(col) else float("nan")

    def to_dict(self) -> dict:
        """The full run record as JSON-able primitives (strict JSON: every
        NaN — skipped-eval metric rows — becomes null)."""
        def scrub(x):
            if isinstance(x, list):
                return [scrub(v) for v in x]
            if isinstance(x, float) and np.isnan(x):
                return None
            return x

        def arr(a):
            return None if a is None else scrub(np.asarray(a).tolist())
        return {
            "schema": REPORT_SCHEMA,
            "metric_names": self.metric_names,
            "sent_messages": self.sent_messages,
            "failed_messages": self.failed_messages,
            "total_size": self.total_size,
            "sent_per_round": arr(self.sent_per_round),
            "failed_per_round": arr(self.failed_per_round),
            "failed_per_cause": (
                {k: arr(v) for k, v in self.failed_per_cause.items()}
                if self.failed_per_cause is not None else None),
            "mailbox_hwm_per_round": arr(self.mailbox_hwm_per_round),
            "compact_slots_per_round": arr(self.compact_slots_per_round),
            "wide_slots_per_round": arr(self.wide_slots_per_round),
            "local_evals": arr(self._local),
            "global_evals": arr(self._global),
            "wall_clock_seconds_per_round":
                arr(self.wall_clock_seconds_per_round),
            "rounds_per_sec_ema": self.rounds_per_sec_ema,
        }

    def save(self, path: str) -> str:
        """Write :meth:`to_dict` as JSON to ``path``."""
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh, indent=2, allow_nan=False)
            fh.write("\n")
        return path

    @classmethod
    def concatenate(cls, reports: list) -> "SimulationReport":
        """Stitch consecutive run segments (e.g. the PENS phase split) into
        one report; optional per-round arrays survive only when EVERY
        segment carries them."""
        def cat(arrs):
            arrs = [a for a in arrs if a is not None]
            return np.concatenate(arrs) if arrs else None

        def cat_all(key):
            vals = [getattr(r, key) for r in reports]
            if any(v is None for v in vals):
                return None
            return np.concatenate(vals)

        causes = None
        if all(r.failed_per_cause is not None for r in reports):
            keys = reports[0].failed_per_cause.keys()
            causes = {k: np.concatenate([r.failed_per_cause[k]
                                         for r in reports]) for k in keys}
        return cls(
            metric_names=reports[0].metric_names,
            local_evals=cat([r._local for r in reports]),
            global_evals=cat([r._global for r in reports]),
            sent=np.concatenate([r.sent_per_round for r in reports]),
            failed=np.concatenate([r.failed_per_round for r in reports]),
            total_size=sum(r.total_size for r in reports),
            failed_by_cause=causes,
            mailbox_hwm=cat_all("mailbox_hwm_per_round"),
            compact_slots=cat_all("compact_slots_per_round"),
            wide_slots=cat_all("wide_slots_per_round"),
        )

    def __str__(self) -> str:
        return json.dumps({
            "sent_messages": self.sent_messages,
            "failed_messages": self.failed_messages,
            "total_size": self.total_size,
            "rounds": 0 if self._local is None and self._global is None
                      else int((self._local if self._local is not None
                                else self._global).shape[0]),
            "metrics": self.metric_names,
        }, indent=2)
