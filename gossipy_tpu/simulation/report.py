"""Simulation reporting: host-side view over the engine's traced accumulators.

The reference uses an Observer pattern (``SimulationEventReceiver`` /
``SimulationReport``, gossipy/simul.py:37-270) with per-message callbacks.
A jitted engine cannot call back per message, so the engine emits per-round
arrays (message counters, mean metrics) from the scan, and this module wraps
them in an API-compatible report: ``get_evaluation(local)`` returns the
``[(round, {metric: mean})]`` list the reference produces
(simul.py:262-266).
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np


class SimulationReport:
    """Results of a simulation run.

    Parameters mirror what the engine's scan emits:

    - ``metric_names``: static ordering of the metric dict keys
    - ``local_evals`` / ``global_evals``: float arrays [R, M] of per-round
      mean metric values (NaN where no eval ran)
    - ``sent`` / ``failed``: int arrays [R] of messages generated / lost
      (drop, churn, mailbox overflow) per round
    - ``total_size``: cumulative message size in "atomic scalar" units, the
      reference's ``Sizeable`` accounting (gossipy/__init__.py:134-156)
    """

    def __init__(self,
                 metric_names: list[str],
                 local_evals: Optional[np.ndarray],
                 global_evals: Optional[np.ndarray],
                 sent: np.ndarray,
                 failed: np.ndarray,
                 total_size: int):
        self.metric_names = list(metric_names)
        self._local = local_evals
        self._global = global_evals
        self.sent_messages = int(np.sum(sent))
        self.failed_messages = int(np.sum(failed))
        self.sent_per_round = np.asarray(sent)
        self.failed_per_round = np.asarray(failed)
        self.total_size = int(total_size)

    def _to_rounds(self, arr: Optional[np.ndarray]):
        if arr is None:
            return []
        out = []
        for r in range(arr.shape[0]):
            row = arr[r]
            if np.all(np.isnan(row)):
                continue
            out.append((r + 1, {k: float(v) for k, v in zip(self.metric_names, row)}))
        return out

    def get_evaluation(self, local: bool = True):
        """[(round, {metric: mean})] — API parity with reference simul.py:262-266."""
        return self._to_rounds(self._local if local else self._global)

    def curves(self, local: bool = True,
               drop_nan: bool = True) -> dict[str, np.ndarray]:
        """{metric: array} convenience view for plotting/benchmarks.

        ``drop_nan=True`` (default) removes rounds where no evaluation ran
        (``eval_every > 1`` skips), so ``curves(...)["accuracy"][-1]`` is
        always the LAST EVALUATED value; the matching round numbers are
        ``eval_rounds(local)``. Pass ``drop_nan=False`` for row-per-round
        arrays aligned with ``sent_per_round``.
        """
        arr = self._local if local else self._global
        if arr is None:
            return {}
        if drop_nan:
            keep = ~np.all(np.isnan(arr), axis=1)
            arr = arr[keep]
        return {k: arr[:, i] for i, k in enumerate(self.metric_names)}

    def eval_rounds(self, local: bool = True) -> np.ndarray:
        """1-based round numbers where evaluation ran (rows of ``curves``)."""
        arr = self._local if local else self._global
        if arr is None:
            return np.zeros((0,), dtype=int)
        return np.nonzero(~np.all(np.isnan(arr), axis=1))[0] + 1

    def final(self, metric: str, local: bool = False) -> float:
        arr = self._local if local else self._global
        if arr is None:
            return float("nan")
        col = arr[:, self.metric_names.index(metric)]
        col = col[~np.isnan(col)]
        return float(col[-1]) if len(col) else float("nan")

    def __str__(self) -> str:
        return json.dumps({
            "sent_messages": self.sent_messages,
            "failed_messages": self.failed_messages,
            "total_size": self.total_size,
            "rounds": 0 if self._local is None and self._global is None
                      else int((self._local if self._local is not None
                                else self._global).shape[0]),
            "metrics": self.metric_names,
        }, indent=2)
