"""Chaos layer: scheduled fault injection for gossip simulations.

The engines' built-in fault model — an i.i.d. per-message ``drop_prob``
Bernoulli and a per-round ``online_prob`` availability draw over a frozen
topology (reference core.py:311-389; engine.py ``_send_phase`` /
``_deliver_phase``) — cannot express the failures that actually kill
decentralized learning: correlated outages, network partitions, and churn
that rewires edges. This module adds a declarative, *scheduled* fault
plane on top of it:

- :class:`ChaosConfig` — the JSON-able description of what goes wrong
  when: :class:`OutageEpisode` (node groups forced offline for contiguous
  round windows, replacing the independent availability draw while
  scheduled), :class:`PartitionEpisode` (the graph split into components
  for rounds ``[start, stop)`` then healed), :class:`ChurnProcess`
  (per-epoch rewiring *within the static superset adjacency* — the
  topology the simulator was built with — so compiled shapes never
  change), and :class:`FaultSpike` (piecewise-constant per-round
  overrides of ``drop_prob`` and a message-delay scale).

- :func:`build_fault_schedule` — compiles a config into a
  :class:`FaultSchedule`: a pure, shape-static pytree of per-round
  tables the jitted round program indexes by the TRACED absolute round
  number. The control plane stays host-side (the Podracer split,
  PAPERS.md): all randomness and window arithmetic happens here at
  build time; the in-loop work is a handful of gathers. Edge effects
  (partitions + churn) compose into per-round edge-alive masks stored
  as a small set of DEDUPLICATED masks plus a per-round index — dense
  ``[M, N, N]`` over a :class:`~gossipy_tpu.core.Topology`, per-edge
  ``[M, 2E]`` (CSR directed-edge order) plus a padded ``[M, N, max_deg]``
  slot form over a :class:`~gossipy_tpu.core.SparseTopology`, so the
  sparse in-loop update stays O(E).

- :func:`chaos_round_stats` — the in-graph recovery evidence: per-round
  partition consensus gap (max L2 distance between scheduled-component
  mean parameter vectors), within-component mixing (mean distance of
  each node from its OWN component's mean), and the live component
  count. Engine-agnostic pure math, like the rest of the telemetry
  helpers — the jitted engine, the All2All variant and the sequential
  engine all compute it through this one function, so
  jitted-vs-sequential chaos parity is testable.

- :func:`rounds_to_reconverge` — host-side post-processing naming how
  many rounds after a heal the consensus gap took to close.

Everything is OPT-IN (``GossipSimulator(chaos=...)``): with the default
``chaos=None`` the round program traces exactly as before — no schedule
arrays, no extra stats keys, byte-identical HLO (tested, like
probes/sentinels).

Semantics notes (documented divergences, deliberate):

- A forced-offline node neither SENDS nor RECEIVES while its window is
  active (a crashed process does neither), unlike the engine's
  ``online_prob`` draw which only gates receipt. Delivery failures on
  forced-offline receivers are attributed to the ``"chaos"`` failure
  cause; the random availability draw keeps the ``"offline"`` cause.
- Partitions/churn sever links at SEND time (a sender never picks a dead
  edge, and never counts a send toward one); messages already in flight
  when a partition starts still drain — links die, mailboxes don't.
- Rounds at or beyond the schedule ``horizon`` read a trailing baseline
  row: no forced outages, all edges alive, base fault rates.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Declarative config
# ---------------------------------------------------------------------------

def _check_window(start: int, stop: int, what: str) -> None:
    if not (0 <= start < stop):
        raise ValueError(f"{what} window must satisfy 0 <= start < stop, "
                         f"got [{start}, {stop})")


@dataclasses.dataclass(frozen=True)
class OutageEpisode:
    """A correlated outage: ``nodes`` are forced offline (no sends, no
    receives) for rounds ``[start, stop)``, replacing the independent
    per-round availability draw for those nodes while scheduled."""

    nodes: tuple
    start: int
    stop: int

    def __post_init__(self):
        _check_window(self.start, self.stop, "outage")
        object.__setattr__(self, "nodes", tuple(int(n) for n in self.nodes))
        if not self.nodes:
            raise ValueError("an outage episode needs at least one node")


@dataclasses.dataclass(frozen=True)
class PartitionEpisode:
    """A network partition: for rounds ``[start, stop)`` only edges whose
    endpoints share a component stay alive; the graph heals at ``stop``.
    ``components`` are disjoint node-id groups; nodes listed in no group
    form one implicit extra component. Overlapping partition windows:
    the LAST episode in the config wins per round."""

    components: tuple
    start: int
    stop: int

    def __post_init__(self):
        _check_window(self.start, self.stop, "partition")
        comps = tuple(tuple(int(n) for n in c) for c in self.components)
        object.__setattr__(self, "components", comps)
        if len(comps) < 1:
            raise ValueError("a partition needs at least one component")
        seen: set = set()
        for c in comps:
            if seen & set(c):
                raise ValueError("partition components must be disjoint")
            seen |= set(c)


@dataclasses.dataclass(frozen=True)
class ChurnProcess:
    """Edge churn within the static superset adjacency: every ``period``
    rounds of the window ``[start, stop)`` a fresh uniform subset of
    ``keep_frac`` of the topology's (undirected) edges is drawn alive;
    the rest are down until the next epoch. Deterministic per
    ``(seed, epoch)``."""

    keep_frac: float
    start: int
    stop: int
    period: int = 1
    seed: int = 0

    def __post_init__(self):
        _check_window(self.start, self.stop, "churn")
        if not 0.0 <= self.keep_frac <= 1.0:
            raise ValueError("keep_frac must be in [0, 1], got "
                             f"{self.keep_frac}")
        if self.period < 1:
            raise ValueError("churn period must be >= 1")


@dataclasses.dataclass(frozen=True)
class FaultSpike:
    """A piecewise-constant fault-rate override for rounds
    ``[start, stop)``: ``drop_prob`` replaces the simulator's base
    per-message drop rate (None = keep the base), ``delay_scale``
    multiplies every sampled message delay (floor-rounded)."""

    start: int
    stop: int
    drop_prob: Optional[float] = None
    delay_scale: float = 1.0

    def __post_init__(self):
        _check_window(self.start, self.stop, "spike")
        if self.drop_prob is not None and not 0.0 <= self.drop_prob <= 1.0:
            raise ValueError("spike drop_prob must be in [0, 1], got "
                             f"{self.drop_prob}")
        if self.delay_scale <= 0.0:
            raise ValueError("delay_scale must be > 0")


_EPISODE_KINDS = {"outages": OutageEpisode, "partitions": PartitionEpisode,
                  "spikes": FaultSpike}


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """A full chaos scenario: which faults hit which rounds.

    ``horizon`` bounds the schedule tables (rounds beyond it are
    baseline); None derives it as the max ``stop`` over every episode.
    JSON-able via :meth:`to_dict` / :meth:`from_dict` — the form
    :class:`~gossipy_tpu.config.ExperimentConfig` carries in its
    ``chaos`` field.
    """

    outages: tuple = ()
    partitions: tuple = ()
    churn: Optional[ChurnProcess] = None
    spikes: tuple = ()
    horizon: Optional[int] = None

    def __post_init__(self):
        for name, cls in _EPISODE_KINDS.items():
            eps = tuple(ep if isinstance(ep, cls) else cls(**ep)
                        for ep in getattr(self, name))
            object.__setattr__(self, name, eps)
        if self.churn is not None and not isinstance(self.churn,
                                                     ChurnProcess):
            object.__setattr__(self, "churn", ChurnProcess(**self.churn))
        if not (self.outages or self.partitions or self.churn is not None
                or self.spikes):
            raise ValueError("an empty ChaosConfig schedules nothing; pass "
                             "chaos=None instead")
        stops = [ep.stop for ep in self.outages + self.partitions
                 + self.spikes]
        if self.churn is not None:
            stops.append(self.churn.stop)
        derived = max(stops)
        if self.horizon is None:
            object.__setattr__(self, "horizon", derived)
        elif self.horizon < derived:
            raise ValueError(f"horizon {self.horizon} does not cover the "
                             f"latest episode stop {derived}")

    # -- coercion / serialization -------------------------------------------

    @classmethod
    def coerce(cls, chaos: Union[None, dict, "ChaosConfig"]
               ) -> Optional["ChaosConfig"]:
        """Normalize the ``chaos=`` constructor argument: ``None`` → off,
        a dict → :meth:`from_dict`, a :class:`ChaosConfig` → itself."""
        if chaos is None:
            return None
        if isinstance(chaos, cls):
            return chaos
        if isinstance(chaos, dict):
            return cls.from_dict(chaos)
        raise TypeError("chaos= expects None, dict or ChaosConfig; got "
                        f"{type(chaos).__name__}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown chaos fields: {sorted(unknown)}; "
                             f"valid: {sorted(known)}")
        return cls(**d)

    # -- static facts the engines need at construction ----------------------

    def max_delay_scale(self) -> float:
        """Worst-case delay multiplier (sizes the history ring)."""
        return max([1.0] + [sp.delay_scale for sp in self.spikes])

    def max_components(self) -> int:
        """Static component count for the in-graph chaos stats: the max
        over partition windows of (listed components + the implicit
        unlisted group), floor 1."""
        return max([1] + [len(p.components) + 1 for p in self.partitions])

    def has_edge_faults(self) -> bool:
        return bool(self.partitions) or self.churn is not None

    def active_at(self, round_idx: int) -> list:
        """The fault windows active at absolute round ``round_idx`` as
        JSON-able dicts — what a flight-recorder bundle verdict names
        when a chaos-scenario run trips a sentinel."""
        r = int(round_idx)
        out = []
        for ep in self.outages:
            if ep.start <= r < ep.stop:
                out.append({"kind": "outage", "start": ep.start,
                            "stop": ep.stop, "nodes": list(ep.nodes)})
        for ep in self.partitions:
            if ep.start <= r < ep.stop:
                out.append({"kind": "partition", "start": ep.start,
                            "stop": ep.stop,
                            "components": [list(c) for c in ep.components]})
        if self.churn is not None and \
                self.churn.start <= r < self.churn.stop:
            out.append({"kind": "churn", "start": self.churn.start,
                        "stop": self.churn.stop,
                        "keep_frac": self.churn.keep_frac,
                        "period": self.churn.period})
        for sp in self.spikes:
            if sp.start <= r < sp.stop:
                out.append({"kind": "spike", "start": sp.start,
                            "stop": sp.stop, "drop_prob": sp.drop_prob,
                            "delay_scale": sp.delay_scale})
        return out


# ---------------------------------------------------------------------------
# The compiled schedule
# ---------------------------------------------------------------------------

class FaultSchedule(NamedTuple):
    """Shape-static per-round fault tables, indexed by the traced absolute
    round number clamped to the trailing baseline row (``horizon``). Every
    field is an array leaf (or the empty-pytree ``()``), so the whole
    schedule stacks/vmaps cleanly — the service megabatch rides tenants'
    schedule VALUES on the batch axis while the SHAPES are part of the
    bucket signature.

    ``edge_masks`` (dense topologies) / ``csr_masks`` + ``slot_masks``
    (sparse topologies) hold the deduplicated edge-alive masks;
    ``mask_idx[t]`` picks the round's mask (0 = baseline, everything
    alive). Masks are modifiers: the engine ANDs them with the base
    adjacency, so a True entry on a non-edge is inert.
    """

    forced_offline: Any   # [T+1, N] bool: node scheduled offline this round
    drop_prob: Any        # [T+1] f32: per-round message drop rate
    delay_scale: Any      # [T+1] f32: per-round delay multiplier
    mask_idx: Any         # [T+1] i32: edge-mask index (0 = baseline)
    component_id: Any     # [T+1, N] i32: scheduled partition component
    edge_masks: Any = ()  # [M, N, N] bool (dense topology) | ()
    csr_masks: Any = ()   # [M, 2E] bool, CSR directed-edge order | ()
    slot_masks: Any = ()  # [M, N, max_deg] bool, padded neighbor slots | ()

    @property
    def rows(self) -> int:
        return self.forced_offline.shape[0]


def schedule_shape_summary(sched: FaultSchedule) -> dict:
    """Shapes/dtypes of a schedule's arrays — the part of a chaos config
    that pins the compiled program (the service packer buckets on this;
    the VALUES ride the tenant axis)."""
    out = {}
    for name, v in sched._asdict().items():
        out[name] = (None if isinstance(v, tuple)
                     else [list(np.shape(v)), str(np.asarray(v).dtype)])
    return out


def _undirected_pairs(topology):
    """(pi, pj) int64 arrays of the topology's undirected edges, sorted
    lexicographically — the canonical pair ordering every churn draw and
    mask form derives from, identical for dense and CSR topologies."""
    from ..core import SparseTopology
    if isinstance(topology, SparseTopology):
        src = np.repeat(np.arange(topology.num_nodes, dtype=np.int64),
                        np.asarray(topology.degrees, dtype=np.int64))
        dst = topology.indices.astype(np.int64)
        keep = src < dst
        pi, pj = src[keep], dst[keep]
    else:
        pi, pj = np.nonzero(np.triu(np.asarray(topology.adjacency)))
        pi, pj = pi.astype(np.int64), pj.astype(np.int64)
    order = np.lexsort((pj, pi))
    return pi[order], pj[order]


def build_fault_schedule(cfg: ChaosConfig, topology,
                         base_drop_prob: float) -> FaultSchedule:
    """Compile ``cfg`` against a topology into host-side numpy tables
    (the jitted engines convert the leaves to device arrays; the
    sequential engine consumes the numpy directly)."""
    from ..core import SparseTopology
    T = int(cfg.horizon)
    n = topology.num_nodes
    rows = T + 1  # trailing baseline row, read by rounds >= horizon

    forced = np.zeros((rows, n), dtype=bool)
    for ep in cfg.outages:
        forced[ep.start:min(ep.stop, T), list(ep.nodes)] = True

    drop = np.full(rows, float(base_drop_prob), dtype=np.float32)
    scale = np.ones(rows, dtype=np.float32)
    for sp in cfg.spikes:
        sl = slice(sp.start, min(sp.stop, T))
        if sp.drop_prob is not None:
            drop[sl] = sp.drop_prob
        scale[sl] = sp.delay_scale

    # Component ids PERSIST past the partition's heal (until a later
    # partition overwrites them): the recovery probe keeps measuring the
    # gap between the FORMER components after the edges heal, so
    # ``chaos_component_gap`` visibly decays to ~0 instead of snapping to
    # a structural zero the moment the window closes. Edge masks below
    # still heal exactly at ``stop``.
    comp = np.zeros((rows, n), dtype=np.int32)
    for p in cfg.partitions:
        ids = np.full(n, len(p.components), dtype=np.int32)  # implicit grp
        for g, grp in enumerate(p.components):
            ids[list(grp)] = g
        comp[p.start:] = ids

    mask_idx = np.zeros(rows, dtype=np.int32)
    edge_masks: Any = ()
    csr_masks: Any = ()
    slot_masks: Any = ()

    if cfg.has_edge_faults():
        pi, pj = _undirected_pairs(topology)
        n_pairs = len(pi)
        pair_alive_rows = [np.ones(n_pairs, dtype=bool)]  # mask 0: baseline
        seen = {pair_alive_rows[0].tobytes(): 0}
        churn = cfg.churn
        churn_cache: dict = {}

        def churn_alive(epoch: int) -> np.ndarray:
            if epoch not in churn_cache:
                rng = np.random.default_rng((int(churn.seed), int(epoch)))
                churn_cache[epoch] = rng.random(n_pairs) < churn.keep_frac
            return churn_cache[epoch]

        part_active = np.zeros(T, dtype=bool)
        for p in cfg.partitions:
            part_active[p.start:min(p.stop, T)] = True
        for r in range(T):
            churn_on = (churn is not None
                        and churn.start <= r < churn.stop)
            if not (part_active[r] or churn_on):
                continue
            alive = np.ones(n_pairs, dtype=bool)
            if part_active[r]:
                alive &= comp[r, pi] == comp[r, pj]
            if churn_on:
                alive &= churn_alive((r - churn.start) // churn.period)
            key = alive.tobytes()
            if key not in seen:
                seen[key] = len(pair_alive_rows)
                pair_alive_rows.append(alive)
            mask_idx[r] = seen[key]

        pair_alive = np.stack(pair_alive_rows)  # [M, n_pairs]
        m_count = pair_alive.shape[0]
        if isinstance(topology, SparseTopology):
            # Directed CSR edge order (rows ascending, neighbor-sorted):
            # map each directed edge to its unordered pair's draw.
            src = np.repeat(np.arange(n, dtype=np.int64),
                            np.asarray(topology.degrees, dtype=np.int64))
            dst = topology.indices.astype(np.int64)
            lo, hi = np.minimum(src, dst), np.maximum(src, dst)
            pair_key = pi * n + pj
            order = np.argsort(pair_key)
            pos = np.searchsorted(pair_key[order], lo * n + hi)
            pair_of_edge = order[pos]
            csr = pair_alive[:, pair_of_edge]  # [M, 2E]
            csr_masks = csr
            # Padded slot form for alive-neighbor sampling: slot s of row
            # i is edge (indptr[i] + s).
            degrees = np.asarray(topology.degrees, dtype=np.int64)
            max_deg = max(int(degrees.max()) if n else 0, 1)
            slot = np.zeros((m_count, n, max_deg), dtype=bool)
            rows_e = src
            pos_e = np.arange(len(src)) - topology.indptr[rows_e]
            slot[:, rows_e, pos_e] = csr
            slot_masks = slot
        else:
            dense = np.ones((m_count, n, n), dtype=bool)
            dense[:, pi, pj] = pair_alive
            dense[:, pj, pi] = pair_alive
            edge_masks = dense

    return FaultSchedule(
        forced_offline=forced,
        drop_prob=drop,
        delay_scale=scale,
        mask_idx=mask_idx,
        component_id=comp,
        edge_masks=edge_masks,
        csr_masks=csr_masks,
        slot_masks=slot_masks,
    )


# ---------------------------------------------------------------------------
# In-graph chaos stats (recovery evidence)
# ---------------------------------------------------------------------------

# Per-round chaos stat keys the engines emit when chaos + consensus probes
# are on (report registry fields, JSONL ``chaos`` row, ``update_chaos``
# observer event). ``failed_chaos`` — the fourth failure cause — travels
# with the cause breakdown instead.
CHAOS_PROBE_KEYS = ("chaos_component_gap", "chaos_within_mean",
                    "chaos_active_components")


def chaos_round_stats(params: Any, component_id: jax.Array,
                      n_components: int) -> dict:
    """One round's partition-recovery vitals over stacked params (leaves
    ``[N, ...]``), grouped by the round's SCHEDULED component ids:

    - ``chaos_component_gap``: max pairwise L2 distance between the mean
      parameter vectors of the non-empty components (0 with a single
      component) — the quantity that must OPEN while a partition holds
      and RECONVERGE to ~0 after the heal;
    - ``chaos_within_mean``: mean over nodes of the L2 distance to their
      own component's mean (per-component mixing health);
    - ``chaos_active_components``: how many scheduled components hold at
      least one node this round.

    ``n_components`` is static (``ChaosConfig.max_components()``), so
    the segment reductions have fixed shapes under jit.
    """
    leaves = jax.tree_util.tree_leaves(params)
    n = leaves[0].shape[0]
    flat = jnp.concatenate(
        [l.astype(jnp.float32).reshape(n, -1) for l in leaves], axis=1)
    comp = component_id.astype(jnp.int32)
    ones = jnp.ones((n,), jnp.float32)
    counts = jax.ops.segment_sum(ones, comp, num_segments=n_components)
    sums = jax.ops.segment_sum(flat, comp, num_segments=n_components)
    means = sums / jnp.maximum(counts, 1.0)[:, None]
    own = means[comp]  # [N, P]
    within = jnp.sqrt(((flat - own) ** 2).sum(axis=1)).mean()
    present = counts > 0
    d2 = ((means[:, None, :] - means[None, :, :]) ** 2).sum(-1)
    both = present[:, None] & present[None, :]
    gap = jnp.sqrt(jnp.max(jnp.where(both, d2, 0.0)))
    return {
        "chaos_component_gap": gap.astype(jnp.float32),
        "chaos_within_mean": within.astype(jnp.float32),
        "chaos_active_components": present.sum().astype(jnp.int32),
    }


def chaos_event_row(vals: dict) -> Optional[dict]:
    """The per-round ``update_chaos`` observer payload (JSON-able
    scalars) from one round's chaos values; None when ``vals`` carries
    none."""
    if not vals:
        return None
    row: dict = {}
    if "chaos_component_gap" in vals:
        row["component_gap"] = float(vals["chaos_component_gap"])
        row["within_mean"] = float(vals["chaos_within_mean"])
        row["active_components"] = int(vals["chaos_active_components"])
    if "failed_chaos" in vals:
        row["failed_chaos"] = int(vals["failed_chaos"])
    return row or None


# ---------------------------------------------------------------------------
# Host-side recovery analysis
# ---------------------------------------------------------------------------

def rounds_to_reconverge(gap: np.ndarray, heal_round: int,
                         tol: Optional[float] = None) -> Optional[int]:
    """How many rounds after ``heal_round`` the per-round ``gap`` series
    (e.g. a report's ``chaos_component_gap``, index = round) took to
    close. ``tol`` defaults to 5% of the gap's peak over the pre-heal
    window (floor 1e-6). Returns the 1-based round count after the heal
    (0 = already closed at the heal round), or None if the series never
    closes within the report."""
    gap = np.asarray(gap, dtype=np.float64)
    heal = int(heal_round)
    if tol is None:
        peak = float(np.nanmax(gap[:heal])) if heal > 0 else 0.0
        tol = max(0.05 * peak, 1e-6)
    for i in range(heal, len(gap)):
        if np.isfinite(gap[i]) and gap[i] <= tol:
            return i - heal
    return None
