"""Sampled active-cohort rounds: population size decoupled from round cost.

The engine materializes every node every round — state is ``[N, ...]``,
the round program is ``[N]``-wide, and the 50k-node TPU run already dies
(``BENCH_TPU_EVIDENCE.jsonl`` row 3). "Millions of users" needs the
cross-device-FL shape instead (the actor/learner split of the Podracer
architectures, PAPERS.md): the full population of NOMINAL size N lives as
a host-resident pool of per-node durable state, and each round only a
sampled **cohort** of C nodes is materialized — gather the cohort's
state, run the standard jitted round program at shape ``[C, ...]``,
scatter the updates back. Per-round cost (compute, HBM, compile) is a
function of C; N only prices the pool.

    sim = GossipSimulator(handler, topology, data,
                          cohort=CohortConfig(size=4096))
    pool = sim.init_cohort_pool(key)
    pool, report = sim.start(pool, n_rounds=500, key=key)

What persists per node across rounds is the pool
(:class:`CohortPool`): model params + optimizer state + update counts,
the phase/period, a per-node PRNG key, and the touched-mask the coverage
accounting reads. Round-scoped state (mailbox, params-history ring,
reply box) is rebuilt per cohort from the gathered params — cohort
rotation drains in-flight traffic, one of the documented bias caveats
(docs/scale.md) vs full-population gossip.

Peer sampling inside a cohort round (``CohortConfig.peer_mode``):

- ``"resample"`` (default): peers drawn uniformly over the active cohort
  — the cross-device-FL reading where the round's participants gossip
  among themselves. No O(N) topology structure is ever touched, so this
  is the 10M-node path (pair it with :class:`NominalTopology` to skip
  building a graph at all).
- ``"induced"``: the topology-induced subgraph on the cohort, via the
  existing :class:`~gossipy_tpu.core.SparseTopology` neighbor-table
  machinery — each cohort node may only contact its real neighbors that
  are ALSO in the cohort (others' sends are skipped like isolated
  nodes). Exact subset semantics; at C << N most nodes are isolated, so
  this mode is for cohorts a sizable fraction of N.

``cohort=None`` (the default) traces the byte-identical round program —
the ``engine/cohort-off`` identity pair in ``analysis/hlo.py``'s gate
enforces it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import tracing as _tracing

# Report keys this layer adds (registered in report.PER_ROUND_FIELDS; the
# tracelint registry-field rule covers the cohort_ prefix).
COHORT_STAT_KEYS = ("cohort_coverage", "cohort_active_nodes")

_PEER_MODES = ("resample", "induced")


@dataclasses.dataclass(frozen=True)
class CohortConfig:
    """Active-cohort mode configuration.

    - ``size``: C, the number of nodes materialized per round.
    - ``rounds_per_cohort``: how many consecutive rounds one sampled
      cohort runs before rotating (1 = fresh cohort every round, the
      cross-device-FL default). Larger values amortize the gather/scatter
      against more in-cohort mixing.
    - ``peer_mode``: ``"resample"`` | ``"induced"`` (module doc).
    - ``prefetch``: pipeline depth of the streaming driver. 0 (default)
      runs segments strictly serially; ``k >= 1`` stages (samples +
      gathers) up to ``k`` future cohorts on a background thread while
      the current cohort runs on-device, and scatters finished cohorts
      back asynchronously. The streamed schedule is bit-identical to the
      serial one — late scatters are overlaid onto staged gathers before
      launch (see ``cohort_start``).
    - ``pool_dir``: when set, the resident pool is disk-backed: every
      :class:`CohortPool` leaf is an ``np.memmap`` over a sparse file in
      this directory, rows are lazily initialized the first time they
      are sampled, and nominal N is bounded by storage, not host RAM
      (the nominal-100M flag). See :class:`PoolStore`.
    """

    size: int
    rounds_per_cohort: int = 1
    peer_mode: str = "resample"
    prefetch: int = 0
    pool_dir: Optional[str] = None

    def __post_init__(self):
        if int(self.size) < 2:
            raise ValueError(f"cohort size must be >= 2, got {self.size}")
        if int(self.rounds_per_cohort) < 1:
            raise ValueError("rounds_per_cohort must be >= 1, got "
                             f"{self.rounds_per_cohort}")
        if self.peer_mode not in _PEER_MODES:
            raise ValueError(f"unknown peer_mode {self.peer_mode!r}; "
                             f"options: {_PEER_MODES}")
        if int(self.prefetch) < 0:
            raise ValueError(
                f"prefetch must be >= 0, got {self.prefetch}")
        if self.pool_dir is not None and not isinstance(self.pool_dir,
                                                        str):
            raise ValueError("pool_dir must be a directory path string "
                             f"or None, got {type(self.pool_dir).__name__}")

    @staticmethod
    def coerce(value: Union[None, int, dict, "CohortConfig"]
               ) -> Optional["CohortConfig"]:
        """None | C | dict | CohortConfig -> Optional[CohortConfig]."""
        if value is None or isinstance(value, CohortConfig):
            return value
        if isinstance(value, bool):
            raise ValueError("cohort= takes a size/config, not a bool")
        if isinstance(value, int):
            return CohortConfig(size=value)
        if isinstance(value, dict):
            return CohortConfig.from_dict(value)
        raise ValueError(f"cannot coerce {type(value).__name__} to "
                         "CohortConfig")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "CohortConfig":
        fields = {f.name for f in dataclasses.fields(CohortConfig)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(f"unknown cohort fields: {sorted(unknown)}; "
                             f"valid: {sorted(fields)}")
        return CohortConfig(**d)


class NominalTopology:
    """A population SIZE pretending to be a topology.

    Resample-mode cohorts never read edges, so a 10M-node run should not
    pay for (or even build) a 10M-node graph. This stand-in carries only
    ``num_nodes``; every structural query raises, which also guarantees
    it cannot silently reach a code path that needs real edges
    (``peer_mode="induced"``, chaos, the non-cohort engine).
    """

    def __init__(self, n: int):
        self.num_nodes = int(n)

    def __getattr__(self, name):
        raise AttributeError(
            f"NominalTopology has no {name!r}: it is a population size "
            "for resample-mode cohort runs, not a graph — use a real "
            "Topology/SparseTopology for edge-dependent features")

    def __repr__(self):
        return f"NominalTopology({self.num_nodes})"


class _CohortRoundTopology:
    """The inner round's C-node 'everyone may talk to everyone' world.

    ``sample_peers`` draws one uniform peer != self per node WITHOUT
    materializing a [C, C] adjacency (a clique at C=65536 would be 4 GB):
    ``peer_i = (i + 1 + U{0..C-2}) % C``. Expected fan-in is exactly
    ``F`` per node; the engine's mailbox/compaction sizing reads that
    through ``GossipSimulator._expected_fanin_vector``'s cohort branch.
    """

    def __init__(self, c: int):
        self.num_nodes = int(c)
        self.degrees = np.full(self.num_nodes, self.num_nodes - 1,
                               dtype=np.int64)

    def sample_peers(self, key: jax.Array) -> jax.Array:
        c = self.num_nodes
        r = jax.random.randint(key, (c,), 0, c - 1, dtype=jnp.int32)
        return (jnp.arange(c, dtype=jnp.int32) + 1 + r) % c

    def __repr__(self):
        return f"_CohortRoundTopology({self.num_nodes})"


class CohortPool(NamedTuple):
    """The resident per-node durable state of the nominal population.

    Every array leaf has leading axis N (host numpy by default — the pool
    is the thing that must NOT live in the round program's HBM budget).
    ``model`` is the stacked :class:`~gossipy_tpu.handlers.base.
    ModelState`; ``node_key`` the per-node PRNG key table the init drew
    from (gathered/scattered with the cohort so a node's identity
    survives checkpoints); ``touched`` the coverage-accounting mask;
    ``round`` the absolute round counter (round randomness keys off it,
    so a restored pool continues bit-for-bit).
    """

    model: Any
    phase: Any
    node_key: Any
    touched: Any
    round: Any


def setup_cohort(sim, topology):
    """Constructor-side wiring (called from ``GossipSimulator.__init__``
    when ``cohort=`` is given): validate the combination, remember the
    nominal population, and hand back the C-node inner round topology the
    rest of construction sizes against."""
    from .engine import GossipSimulator

    if type(sim) is not GossipSimulator:
        raise ValueError(
            f"cohort mode supports the base GossipSimulator only; "
            f"{type(sim).__name__} variants drive their own state shapes")
    cfg: CohortConfig = sim.cohort
    n = int(topology.num_nodes)
    if cfg.size > n:
        raise ValueError(f"cohort size {cfg.size} exceeds the nominal "
                         f"population {n}")
    sim.nominal_topology = topology
    sim.nominal_n = n
    sim._cohort_nbr_global = None
    if cfg.peer_mode == "induced":
        if isinstance(topology, NominalTopology):
            raise ValueError("peer_mode='induced' needs a real topology "
                             "(NominalTopology carries no edges)")
        from .nodes import build_neighbor_table
        sim._cohort_nbr_global = np.asarray(build_neighbor_table(topology),
                                            dtype=np.int32)
    return _CohortRoundTopology(cfg.size)


def induced_peers(sim, state, key: jax.Array) -> jax.Array:
    """Uniform peer draw over the cohort-induced subgraph: the cohort-
    local neighbor table rides ``state.aux["cohort_nbr"]`` ([C, max_deg],
    -1 = absent or not-in-cohort), so the compiled program is reused
    across cohorts — the table is data, not a trace constant. Nodes with
    no alive cohort neighbor get peer -1 (send skipped, like isolated
    nodes)."""
    nbr = state.aux["cohort_nbr"]
    alive = nbr >= 0
    logits = jnp.where(alive, 0.0, -jnp.inf)
    slot = jax.random.categorical(key, logits, axis=-1)
    has = alive.any(axis=-1)
    c = nbr.shape[0]
    peers = nbr[jnp.arange(c), jnp.clip(slot, 0, nbr.shape[1] - 1)]
    return jnp.where(has, peers, -1).astype(jnp.int32)


# -- pool construction -------------------------------------------------------

def _leaf_np(shape_dtype, n: int) -> np.ndarray:
    return np.empty((n,) + tuple(shape_dtype.shape),
                    dtype=np.dtype(shape_dtype.dtype))


def _model_shape(sim):
    return jax.eval_shape(sim.handler.init, jax.random.PRNGKey(0))


def pool_template(sim) -> CohortPool:
    """A zero-filled, correctly-shaped pool — the checkpoint-restore
    template (orbax needs structure + dtypes, not values), cheap even at
    nominal 10M (plain numpy zeros, no per-node init)."""
    n = sim.nominal_n
    st = _model_shape(sim)
    model = jax.tree.map(
        lambda l: np.zeros((n,) + tuple(l.shape), np.dtype(l.dtype)), st)
    key_t = np.zeros_like(
        np.asarray(jax.random.split(jax.random.PRNGKey(0), 2))[:1]
        .repeat(n, axis=0))
    return CohortPool(model=model,
                      phase=np.zeros(n, np.int32),
                      node_key=key_t,
                      touched=np.zeros(n, bool),
                      # 0-d ndarray, not a numpy scalar: orbax's restore-
                      # args builder only types ndarrays.
                      round=np.zeros((), np.int32))


def init_cohort_pool(sim, key: jax.Array, common_init: bool = False,
                     local_train: bool = False,
                     block: Optional[int] = None) -> CohortPool:
    """Initialize the resident pool (the cohort-mode ``init_nodes``).

    Per-node model init runs in device blocks of ``block`` nodes
    (default ``max(C, 65536)``) so nominal-10M pools never materialize
    the whole population on one device at once — each block's leaves are
    copied straight into preallocated host numpy.

    ``local_train`` defaults to **False** (unlike ``init_nodes``): the
    reference's init-time local pass would gather every node's data shard
    at pool scale. With it off, a node takes its first local update the
    first time it is sampled into a cohort — a documented bias vs the
    materialized engine (docs/scale.md). Pass ``True`` to pay the
    blocked pre-training pass anyway.

    With ``CohortConfig(pool_dir=...)`` no rows are initialized here at
    all: the returned pool's leaves are sparse-file memmaps
    (:class:`PoolStore`) and rows materialize lazily, keyed on
    ``fold_in(key, node_id)``, the first time they are sampled — an
    existing store directory is re-opened (resume), a missing one is
    created. Lazy rows are deterministic per (key, node id) but NOT
    numerically identical to this function's RAM batch init (documented
    in docs/scale.md).
    """
    n = sim.nominal_n
    cfg = sim.cohort
    if cfg.pool_dir:
        if local_train:
            raise ValueError(
                "local_train is not supported with pool_dir= (the lazy "
                "per-row init has no blocked pre-training pass)")
        if is_pool_store_dir(cfg.pool_dir):
            store = open_pool_store(sim, cfg.pool_dir)
        else:
            store = create_pool_store(sim, key, cfg.pool_dir,
                                      common_init=common_init)
        sim._pool_store = store
        return store.pool()
    block = int(block or max(cfg.size, 65536))
    k_init, k_phase, k_up = jax.random.split(key, 3)
    node_keys = np.asarray(jax.random.split(k_init, n))

    st_shape = _model_shape(sim)
    model = jax.tree.map(lambda l: _leaf_np(l, n), st_shape)
    flat_model = jax.tree.leaves(model)

    if common_init:
        one = jax.tree.map(np.asarray, sim.handler.init(k_init))
        for dst, src in zip(flat_model, jax.tree.leaves(one)):
            dst[...] = src[None]
    else:
        init_block = jax.jit(jax.vmap(sim.handler.init))
        for lo in range(0, n, block):
            hi = min(lo + block, n)
            blk = init_block(jnp.asarray(node_keys[lo:hi]))
            for dst, src in zip(flat_model, jax.tree.leaves(blk)):
                dst[lo:hi] = np.asarray(src)

    if local_train:
        p = _pool_data_rows(sim)
        upd_block = jax.jit(jax.vmap(sim.handler.update))
        for lo in range(0, n, block):
            hi = min(lo + block, n)
            idx = np.arange(lo, hi)
            sub = jax.tree.map(lambda l: jnp.asarray(l[lo:hi]), model)
            data = tuple(jnp.asarray(d)[jnp.asarray(idx % p)]
                         for d in (np.asarray(sim.data["xtr"]),
                                   np.asarray(sim.data["ytr"]),
                                   np.asarray(sim.data["mtr"])))
            keys = jax.random.split(jax.random.fold_in(k_up, lo), hi - lo)
            out = upd_block(sub, data, keys)
            for dst, src in zip(flat_model, jax.tree.leaves(out)):
                dst[lo:hi] = np.asarray(src)

    if sim.sync:
        phase = np.asarray(jax.random.randint(
            k_phase, (n,), 0, sim.delta, dtype=jnp.int32))
    else:
        raw = sim.delta + (sim.delta / 10.0) * np.asarray(
            jax.random.normal(k_phase, (n,)))
        phase = np.maximum(raw.astype(np.int32), 1)

    return _host_pool(CohortPool(model=model, phase=phase,
                                 node_key=node_keys,
                                 touched=np.zeros(n, bool),
                                 round=np.zeros((), np.int32)))


def _host_pool(pool: CohortPool, copy: bool = False) -> CohortPool:
    """Normalize a pool to WRITABLE host numpy leaves (jax exports and
    orbax restores can hand back read-only buffers; the scatter half of
    the segment loop writes in place). ``copy=True`` copies every leaf —
    ``cohort_start`` uses it so the caller's pool keeps its value
    semantics (a FlightRecorder's "last healthy state" reference must
    not alias the scatter target). Memmap leaves (disk-backed pools) are
    passed through untouched: the file IS the pool, updates are in-place
    by design."""
    def h(l):
        if isinstance(l, np.memmap):
            return l
        a = np.asarray(l)
        return a.copy() if copy or not a.flags.writeable else a
    return jax.tree.map(h, pool)


def _pool_data_rows(sim) -> int:
    """Leading axis P of the pool's per-node data: node ``i`` reads row
    ``i % P``, so a pool of nominal N can ride a data bank of P << N
    shards (at 10M users nobody stacks 10M distinct shards)."""
    return int(sim.data["xtr"].shape[0])


# -- disk-backed pools (CohortConfig.pool_dir) -------------------------------

_POOL_MANIFEST = "pool_manifest.json"
_POOL_FIXED_LEAVES = (("phase.bin", np.int32, 1),
                      ("node_key.bin", np.uint32, 2),
                      ("touched.bin", np.bool_, 1),
                      ("inited.bin", np.uint8, 1))


def is_mmap_pool(pool) -> bool:
    """True when any pool leaf is an ``np.memmap`` (disk-backed pool)."""
    return any(isinstance(l, np.memmap) for l in jax.tree.leaves(pool))


def is_pool_store_dir(path) -> bool:
    """True when ``path`` is a :class:`PoolStore` directory (live pool or
    file-copy checkpoint) — the ``load``/``init`` dispatch predicate."""
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, _POOL_MANIFEST))


def _write_manifest(path: str, manifest: dict):
    tmp = os.path.join(path, _POOL_MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(path, _POOL_MANIFEST))


def _read_manifest(path: str) -> dict:
    with open(os.path.join(path, _POOL_MANIFEST)) as f:
        return json.load(f)


def _alloc_sparse(fp: str, nbytes: int):
    """Create a hole-only file of ``nbytes`` apparent size (ftruncate):
    zero blocks on disk until a row is actually written."""
    with open(fp, "wb") as f:
        f.truncate(int(nbytes))


def _sparse_copy(src: str, dst: str, chunk: int = 16 << 20):
    """Copy a file preserving holes (SEEK_DATA/SEEK_HOLE) so a pool
    checkpoint costs only the written rows, not the apparent size. Falls
    back to a dense copy where the fs/OS lacks hole enumeration."""
    with open(src, "rb") as fi, open(dst, "wb") as fo:
        size = os.fstat(fi.fileno()).st_size
        fo.truncate(size)
        if not hasattr(os, "SEEK_DATA"):
            shutil.copyfileobj(fi, fo, chunk)
            return
        pos = 0
        while pos < size:
            try:
                data = fi.seek(pos, os.SEEK_DATA)
            except OSError:  # ENXIO: no data past pos — trailing hole
                break
            hole = fi.seek(data, os.SEEK_HOLE)
            fi.seek(data)
            fo.seek(data)
            left = hole - data
            while left > 0:
                buf = fi.read(min(chunk, left))
                if not buf:
                    break
                fo.write(buf)
                left -= len(buf)
            pos = hole


def _make_lazy_init(sim, manifest: dict):
    """The jitted per-row init batch for a :class:`PoolStore`: model,
    node key and phase for a ``[B]`` block of node ids, each derived by
    ``fold_in(key, node_id)`` — deterministic per (store key, id) and
    independent of sampling order, so two runs with different schedules
    materialize identical rows. Deliberately NOT numerically identical
    to ``init_cohort_pool``'s RAM batch init (``jax.random.split`` over
    N is an O(N) materialization; docs/scale.md)."""
    base = jnp.asarray(np.asarray(manifest["key_material"],
                                  np.uint32).reshape(-1)[:2])
    k_init, k_phase = jax.random.split(base, 3)[:2]
    sync, delta = bool(manifest["sync"]), int(manifest["delta"])
    common = bool(manifest["common_init"])
    handler = sim.handler

    def batch(ids):
        nkeys = jax.vmap(lambda i: jax.random.fold_in(k_init, i))(ids)
        if common:
            one = handler.init(k_init)
            model = jax.tree.map(
                lambda l: jnp.broadcast_to(l[None],
                                           (ids.shape[0],) + l.shape),
                one)
        else:
            model = jax.vmap(handler.init)(nkeys)
        pkeys = jax.vmap(lambda i: jax.random.fold_in(k_phase, i))(ids)
        if sync:
            phase = jax.vmap(
                lambda k: jax.random.randint(k, (), 0, delta,
                                             dtype=jnp.int32))(pkeys)
        else:
            raw = delta + (delta / 10.0) * jax.vmap(
                lambda k: jax.random.normal(k, ()))(pkeys)
            phase = jnp.maximum(raw.astype(jnp.int32), 1)
        return model, nkeys, phase

    return jax.jit(batch)


class PoolStore:
    """A :class:`CohortPool` whose leaves live in sparse files.

    Every leaf is an ``np.memmap`` (mode ``r+``) over a file under
    ``path``; apparent file size is the full nominal-N footprint but
    disk blocks materialize only for rows actually written, so nominal
    100M is bounded by storage, not RAM. Gather/scatter touch only the C
    sampled rows; ``ensure_rows`` lazily initializes never-seen rows
    (``_make_lazy_init``) tracked by the ``inited`` bitmask; checkpoints
    are hole-preserving file copies (``save_pool_store``). Unlike RAM
    pools, the pool object has in-place update semantics — the returned
    pool of a run aliases the same files.
    """

    def __init__(self, sim, path: str, manifest: dict):
        self.path = os.path.abspath(path)
        n = int(manifest["nominal_n"])
        if n != int(sim.nominal_n):
            raise ValueError(
                f"pool store {self.path!r} holds nominal_n={n}, "
                f"simulator expects {sim.nominal_n}")
        for fld in ("sync", "delta"):
            if manifest[fld] != getattr(sim, fld):
                raise ValueError(
                    f"pool store {self.path!r} was built with "
                    f"{fld}={manifest[fld]!r}, simulator has "
                    f"{getattr(sim, fld)!r} (phase init would diverge)")
        self.manifest = manifest
        st = _model_shape(sim)
        flat, treedef = jax.tree_util.tree_flatten(st)
        specs = manifest["model_leaves"]
        if len(specs) != len(flat):
            raise ValueError(
                f"pool store {self.path!r} holds {len(specs)} model "
                f"leaves, simulator's model has {len(flat)}")
        maps = []
        for spec, l in zip(specs, flat):
            shape = (n,) + tuple(l.shape)
            if (tuple(spec["shape"]) != shape
                    or np.dtype(spec["dtype"]) != np.dtype(l.dtype)):
                raise ValueError(
                    f"pool store leaf {spec['file']} is "
                    f"{spec['shape']}/{spec['dtype']}; simulator expects "
                    f"{list(shape)}/{np.dtype(l.dtype).name}")
            maps.append(self._open(spec["file"], np.dtype(l.dtype),
                                   shape))
        self.model = jax.tree_util.tree_unflatten(treedef, maps)
        self.phase = self._open("phase.bin", np.int32, (n,))
        self.node_key = self._open("node_key.bin", np.uint32, (n, 2))
        self.touched = self._open("touched.bin", np.bool_, (n,))
        self.inited = self._open("inited.bin", np.uint8, (n,))
        self._init_fn = None
        self._init_block = None

    def _open(self, name: str, dtype, shape) -> np.memmap:
        return np.memmap(os.path.join(self.path, name), dtype=dtype,
                         mode="r+", shape=shape)

    def files(self) -> list[str]:
        return ([s["file"] for s in self.manifest["model_leaves"]]
                + [name for name, _, _ in _POOL_FIXED_LEAVES])

    def pool(self) -> CohortPool:
        return CohortPool(model=self.model, phase=self.phase,
                          node_key=self.node_key, touched=self.touched,
                          round=np.asarray(int(self.manifest["round"]),
                                           np.int32))

    def ensure_rows(self, sim, idx: np.ndarray) -> int:
        """Materialize any not-yet-initialized rows among ``idx`` (lazy
        init). Runs in fixed-size id blocks (padded by repeating the last
        id) so the jitted init compiles once per store."""
        idx = np.asarray(idx)
        need = idx[self.inited[idx] == 0]
        if need.size == 0:
            return 0
        if self._init_fn is None:
            self._init_fn = _make_lazy_init(sim, self.manifest)
            self._init_block = max(int(sim.cohort.size), 256)
        B = self._init_block
        model_leaves = jax.tree.leaves(self.model)
        for lo in range(0, need.size, B):
            blk = need[lo:lo + B]
            pad = np.empty(B, np.int32)
            pad[:blk.size] = blk
            pad[blk.size:] = blk[-1]
            model, nkeys, phase = self._init_fn(jnp.asarray(pad))
            m = blk.size
            for dst, src in zip(model_leaves, jax.tree.leaves(model)):
                dst[blk] = np.asarray(src)[:m]
            self.node_key[blk] = np.asarray(nkeys)[:m]
            self.phase[blk] = np.asarray(phase)[:m]
            self.inited[blk] = 1
        return int(need.size)

    def flush(self):
        for l in jax.tree.leaves(self.model):
            l.flush()
        for l in (self.phase, self.node_key, self.touched, self.inited):
            l.flush()

    def set_round(self, r: int):
        self.manifest["round"] = int(r)
        _write_manifest(self.path, self.manifest)


def create_pool_store(sim, key: jax.Array, path: str,
                      common_init: bool = False) -> PoolStore:
    """Create a fresh disk-backed pool under ``path`` (sparse files +
    manifest; no row is initialized — that happens lazily on first
    sample)."""
    n = int(sim.nominal_n)
    if n >= 2 ** 31:
        raise ValueError(f"pool store node ids are int32; nominal_n={n} "
                         "exceeds 2**31-1")
    os.makedirs(path, exist_ok=True)
    st = _model_shape(sim)
    model_specs = []
    for i, l in enumerate(jax.tree.leaves(st)):
        shape = (n,) + tuple(l.shape)
        fname = f"model_{i:03d}.bin"
        _alloc_sparse(os.path.join(path, fname),
                      int(np.prod(shape)) * np.dtype(l.dtype).itemsize)
        model_specs.append({"file": fname, "shape": list(shape),
                            "dtype": np.dtype(l.dtype).name})
    for fname, dt, width in _POOL_FIXED_LEAVES:
        _alloc_sparse(os.path.join(path, fname),
                      n * width * np.dtype(dt).itemsize)
    manifest = {
        "schema": 1,
        "nominal_n": n,
        "round": 0,
        "key_material": _seed_material(key),
        "ckpt_key_material": None,
        "common_init": bool(common_init),
        "sync": bool(sim.sync),
        "delta": int(sim.delta),
        "cohort": sim.cohort.to_dict(),
        "model_leaves": model_specs,
    }
    _write_manifest(path, manifest)
    return PoolStore(sim, path, manifest)


def open_pool_store(sim, path: str) -> PoolStore:
    """Open an existing store directory in place (writes go to its
    files) — the resume path of ``init_cohort_pool(pool_dir=...)``."""
    return PoolStore(sim, path, _read_manifest(path))


def save_pool_store(sim, pool: CohortPool, path: str,
                    key: Optional[jax.Array] = None) -> str:
    """Checkpoint a disk-backed pool: flush the memmaps, hole-preserving
    file copies into ``path``, manifest stamped with the pool's round
    (and the run key, like ``save_checkpoint``'s sidecar)."""
    store: Optional[PoolStore] = getattr(sim, "_pool_store", None)
    if store is None:
        raise ValueError("no live PoolStore on this simulator; disk-"
                         "backed pools come from init_cohort_pool/load "
                         "with CohortConfig(pool_dir=...)")
    dst = os.path.abspath(path)
    if dst == store.path:
        raise ValueError("pool checkpoint dir must differ from the live "
                         f"pool_dir {store.path!r}")
    tr = getattr(sim, "tracer", None)
    with _tracing.span("checkpoint.save", cat="checkpoint", tracer=tr,
                       path=str(path), pool_store=True):
        store.flush()
        os.makedirs(dst, exist_ok=True)
        for name in store.files():
            _sparse_copy(os.path.join(store.path, name),
                         os.path.join(dst, name))
        manifest = dict(store.manifest)
        manifest["round"] = int(np.asarray(pool.round))
        manifest["ckpt_key_material"] = (_seed_material(key)
                                         if key is not None else None)
        _write_manifest(dst, manifest)
    return dst


def load_pool_checkpoint(sim, path: str, workdir: Optional[str] = None):
    """Restore ``(pool, key)`` from a pool-store checkpoint directory.

    The checkpoint files are hole-preserving-copied into ``workdir``
    (default ``<path>.live``, replaced if present) and the store opened
    there, so continuing the run never mutates the checkpoint itself.
    """
    src = os.path.abspath(path)
    manifest = _read_manifest(src)
    dst = os.path.abspath(workdir or (src.rstrip("/\\") + ".live"))
    if dst != src:
        if os.path.isdir(dst):
            shutil.rmtree(dst)
        os.makedirs(dst)
        files = ([s["file"] for s in manifest["model_leaves"]]
                 + [name for name, _, _ in _POOL_FIXED_LEAVES])
        for name in files:
            _sparse_copy(os.path.join(src, name),
                         os.path.join(dst, name))
        _write_manifest(dst, manifest)
    store = PoolStore(sim, dst, dict(manifest))
    sim._pool_store = store
    km = manifest.get("ckpt_key_material")
    restored_key = (jnp.asarray(np.asarray(km, np.uint32))
                    if km else None)
    return store.pool(), restored_key


# -- cohort sampling ---------------------------------------------------------

def _seed_material(key: jax.Array) -> list[int]:
    """Deterministic host seed material from a jax PRNG key (typed or
    raw uint32)."""
    try:
        if jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key):
            key = jax.random.key_data(key)
    except Exception:
        pass
    return [int(x) for x in np.asarray(key).ravel().astype(np.uint32)]


def sample_cohort(key: jax.Array, round0: int, n: int, c: int) -> np.ndarray:
    """The round-``round0`` cohort: C distinct node ids, deterministic in
    ``(key, round0)`` — a restored pool re-draws the identical schedule.

    At C << N the draw rejection-samples uniques (no O(N) permutation —
    the 10M path); small ratios fall back to numpy's exact choice.
    Sorted ascending for gather locality.
    """
    ss = np.random.SeedSequence(_seed_material(key) + [int(round0)])
    rng = np.random.default_rng(ss)
    if c >= n:
        return np.arange(n, dtype=np.int64)
    if c * 8 >= n:
        return np.sort(rng.choice(n, c, replace=False).astype(np.int64))
    out = np.unique(rng.integers(0, n, int(c * 1.1) + 16))
    while out.size < c:
        out = np.unique(np.concatenate(
            [out, rng.integers(0, n, c)]))
    rng.shuffle(out)  # drop the unique-sort's small-id bias before cutting
    return np.sort(out[:c])


def _local_neighbor_table(sim, idx: np.ndarray) -> np.ndarray:
    """[C, max_deg] cohort-LOCAL neighbor slots for ``peer_mode='induced'``:
    gather the global table's cohort rows, keep entries that are
    themselves in the cohort (membership via an inverse-index table),
    everything else -1."""
    n = sim.nominal_n
    nbr = sim._cohort_nbr_global[idx]  # [C, max_deg] global ids / -1
    pos = np.full(n, -1, dtype=np.int32)
    pos[idx] = np.arange(idx.size, dtype=np.int32)
    local = np.where(nbr >= 0, pos[np.clip(nbr, 0, n - 1)], -1)
    return local.astype(np.int32)


# -- the round-segment program ----------------------------------------------

def _active_state(sim, model, phase, round0: int, aux):
    """A [C]-shaped SimState for one cohort segment: gathered durable
    state + freshly-built round-scoped state (empty mailboxes, history
    ring re-broadcast from the gathered params — cohort rotation has no
    in-flight traffic to preserve, so the broadcast IS the ring a
    same-round send would read)."""
    from .engine import Mailbox, SimState
    c = sim.n_nodes
    d = sim._history_depth(sim._model_size(model.params))
    stored, scales = sim._encode_history_rows(model.params)
    bcast = lambda l: jnp.broadcast_to(l[None], (d,) + l.shape)
    hist_p = jax.tree.map(bcast, stored)
    hist_s = (jax.tree.map(bcast, scales)
              if sim.history_dtype == "int8" else ())
    hist_a = jnp.broadcast_to(model.n_updates[None],
                              (d,) + model.n_updates.shape)
    return SimState(
        model=model, phase=phase,
        history_params=hist_p, history_ages=hist_a,
        mailbox=Mailbox.empty(d, c, sim.K),
        reply_box=Mailbox.empty(d, c, sim.Kr),
        round=jnp.int32(round0), aux=aux, history_scale=hist_s)


def _make_cohort_run(sim, n_rounds: int):
    """The segment program: ``(state, key, data, last_round[, hc]) ->
    (state[, hc], stats)``. The ``_make_run`` scan with the RUN's final
    absolute round as a traced argument — segments share one compiled
    program even though only the last one force-evaluates."""
    sentinels_on = sim.sentinels is not None

    def scan_rounds(state, key, last_round, hc):
        def body(carry, _):
            if sentinels_on:
                st, c = carry
                pre_params = st.model.params
            else:
                st, c = carry, None
            st, stats = sim._round(st, key, last_round)
            if sentinels_on:
                c, hstats = sim._health_round(c, pre_params, st, stats)
                stats.update(hstats)
            return ((st, c) if sentinels_on else st), stats

        init = (state, hc) if sentinels_on else state
        return jax.lax.scan(body, init, None, length=n_rounds)

    if sentinels_on:
        def run(state, key, data, last_round, hc):
            saved = sim.data
            sim.data = data
            try:
                (state, hc), stats = scan_rounds(state, key, last_round, hc)
                return state, hc, stats
            finally:
                sim.data = saved
    else:
        def run(state, key, data, last_round):
            saved = sim.data
            sim.data = data
            try:
                return scan_rounds(state, key, last_round, None)
            finally:
                sim.data = saved
    return run


def _mesh_fingerprint(mesh):
    """Hashable mesh identity for the segment-program cache key (same
    axes + same device ids = same program placement)."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names),
            tuple(int(d.id) for d in np.ravel(mesh.devices)))


def _validate_cohort_mesh(sim, mesh):
    """Mesh-sharded cohort rounds need C to split evenly across the node
    axis (the registry's rules put every [C]-leading leaf there)."""
    from ..parallel import rules as _rules
    span_sz = _rules.node_axis_size(mesh)
    c = int(sim.cohort.size)
    if c % span_sz:
        raise ValueError(
            f"cohort size {c} is not divisible by the mesh node-axis "
            f"extent {span_sz} (axes "
            f"{_rules.node_axis_entry(mesh)!r}); sharded gather/scatter "
            "needs equal per-device rows")


def _segment_fn(sim, seg_rounds: int, mesh=None):
    """Compile-cached segment program (one per distinct segment length —
    the tail segment of a run whose n_rounds is not a multiple of
    rounds_per_cohort costs one extra compile, like CheckpointManager
    tail chunks — and per mesh placement)."""
    cache_k = ("cohort", seg_rounds, sim._cache_salt(),
               _mesh_fingerprint(mesh))
    if cache_k not in sim._jit_cache:
        fn = jax.jit(_make_cohort_run(sim, seg_rounds), donate_argnums=(0,))
        sim._jit_cache[cache_k] = fn
    return sim._jit_cache[cache_k], cache_k


# -- the driver --------------------------------------------------------------

class _Staged:
    """One staged cohort: host-gathered rows ready to launch."""

    __slots__ = ("s", "r0", "seg", "idx", "model_rows", "phase_rows",
                 "nbr", "data_rows", "seen", "ts_us")


def _patch_rows(staged: _Staged, out_idx: np.ndarray, out_model: list,
                out_phase: np.ndarray):
    """Overlay a finished segment's output rows onto a staged gather:
    rows of ``staged.idx`` that also appear in ``out_idx`` (both sorted
    ascending) take the fresher values. Applying outputs oldest-first
    makes the staged rows exactly what a serial gather would have read —
    the streaming ≡ serial bit-identity hinges on this."""
    if out_idx.size == 0 or staged.idx.size == 0:
        return
    pos = np.searchsorted(out_idx, staged.idx)
    pos = np.minimum(pos, out_idx.size - 1)
    hit = out_idx[pos] == staged.idx
    if not hit.any():
        return
    src = pos[hit]
    for dst, row in zip(staged.model_rows, out_model):
        dst[hit] = row[src]
    staged.phase_rows[hit] = out_phase[src]


def cohort_start(sim, pool: CohortPool, n_rounds: int,
                 key: Optional[jax.Array] = None, mesh=None):
    """Run ``n_rounds`` active-cohort rounds against the resident pool.

    Host-driven segment loop (the actor/learner split): per segment,
    sample the cohort (deterministic in ``(key, absolute round)``),
    gather pool rows + data rows, run the jitted ``[C]`` round program,
    scatter the durable state back and advance the pool round counter.
    Returns ``(pool, SimulationReport)`` — the report carries the
    standard per-round arrays at cohort width plus the
    ``cohort_coverage`` / ``cohort_active_nodes`` accounting rows.

    ``CohortConfig(prefetch=k)`` turns the sequence into a pipeline:
    a stager thread samples + gathers up to ``k`` future cohorts while
    the current one runs on-device (XLA releases the GIL during
    execution), and a flusher thread scatters finished cohorts back
    asynchronously — the ``cohort.sample/gather/scatter`` spans then
    overlap the ``cohort.run`` device window on the trace timeline.
    Bit-identity with the serial schedule is maintained by construction:
    a staged gather snapshots the not-yet-flushed outputs under a lock
    and overlays them (``_patch_rows``), and any output that lands
    after that snapshot is patched in on the main thread right before
    launch. At most one flush is in flight, so by launch time of
    segment ``s`` every output ``o < s`` is either in the pool, in the
    overlay snapshot, or in the launch-time patch set — never lost,
    never stale.

    ``mesh`` shards every [C]-leading leaf of the active state and data
    along the mesh's node axis via the ``parallel/rules.py`` registry
    (``shard_state`` / ``shard_data``) so C grows with the pod; C must
    divide the node-axis extent.
    """
    if not isinstance(pool, CohortPool):
        raise TypeError(
            "cohort mode takes the resident CohortPool (init_cohort_pool), "
            f"got {type(pool).__name__}")
    if key is None:
        key = jax.random.PRNGKey(42)
    cfg: CohortConfig = sim.cohort
    c, n = cfg.size, sim.nominal_n
    p_rows = _pool_data_rows(sim)
    first_round = int(np.asarray(pool.round))
    last_round = first_round + n_rounds - 1
    depth = int(cfg.prefetch)

    if sim.has_live_receivers():
        import warnings
        warnings.warn("cohort mode has no in-run host callback path; live "
                      "event receivers fall back to post-run replay")

    store: Optional[PoolStore] = getattr(sim, "_pool_store", None)
    if is_mmap_pool(pool):
        if store is None:
            raise ValueError(
                "mmap-backed pool has no live PoolStore on this "
                "simulator; obtain the pool from init_cohort_pool/load "
                "with CohortConfig(pool_dir=...) — the store owns lazy "
                "row init")
    else:
        store = None
    if mesh is not None:
        _validate_cohort_mesh(sim, mesh)

    pool = _host_pool(pool, copy=store is None)
    model_def = jax.tree.structure(pool.model)
    model_leaves = jax.tree.leaves(pool.model)
    phase_leaf = pool.phase
    touched = pool.touched
    touched_count = int(np.count_nonzero(touched))
    seg_stats: list[dict] = []
    coverage: list[float] = []
    perf_on = sim.perf is not None and sim.perf.timing
    any_cold = False
    tr = getattr(sim, "tracer", None)
    induced = cfg.peer_mode == "induced"
    pernode_keys = [k for k in sim.data if k not in ("x_eval", "y_eval")]
    host_data = {k: np.asarray(sim.data[k]) for k in pernode_keys}
    eval_data = {k: v for k, v in sim.data.items()
                 if k in ("x_eval", "y_eval")}
    if mesh is not None and eval_data:
        from .. import parallel as _parallel
        eval_data = _parallel.shard_data(eval_data, mesh)

    plan: list[tuple[int, int]] = []
    done = 0
    while done < n_rounds:
        seg = min(cfg.rounds_per_cohort, n_rounds - done)
        plan.append((first_round + done, seg))
        done += seg

    pend_lock = threading.Lock() if depth > 0 else None
    pending: dict[int, tuple] = {}

    def stage_job(s: int, r0: int, seg: int) -> _Staged:
        """Sample + host-gather one cohort (stager thread under
        prefetch; inline otherwise). Under prefetch the gather snapshots
        the not-yet-flushed outputs FIRST, raw-gathers the pool rows,
        then overlays the snapshot oldest-first — any row torn by a
        concurrent flush necessarily belongs to a snapshotted output and
        is overwritten whole."""
        st = _Staged()
        st.s, st.r0, st.seg = s, r0, seg
        with _tracing.span("cohort.sample", cat="cohort", tracer=tr,
                           window=r0) as sp_s:
            st.idx = sample_cohort(key, r0, n, c)
        st.ts_us = sp_s.ts_us
        with _tracing.span("cohort.gather", cat="cohort", tracer=tr,
                           window=r0):
            if store is not None:
                store.ensure_rows(sim, st.idx)
            if pend_lock is not None:
                with pend_lock:
                    snap = [pending[o] for o in sorted(pending)]
                    st.seen = set(pending)
            else:
                snap, st.seen = [], set()
            st.model_rows = [np.asarray(l)[st.idx] for l in model_leaves]
            st.phase_rows = np.asarray(phase_leaf)[st.idx]
            for out in snap:
                _patch_rows(st, *out)
            st.nbr = (_local_neighbor_table(sim, st.idx) if induced
                      else None)
            st.data_rows = {k: host_data[k][st.idx % p_rows]
                            for k in pernode_keys}
        return st

    def launch(st: _Staged):
        """Build the [C] active state from staged host rows, run the
        jitted segment program, return host copies of the durable
        outputs (main thread only — sentinel health carry and stats
        ordering stay serial)."""
        nonlocal any_cold
        r0, seg = st.r0, st.seg
        fn, cache_k = _segment_fn(sim, seg, mesh)
        cold = not getattr(fn, "_gossipy_warm", False)
        with _tracing.span("cohort.stage", cat="cohort", tracer=tr,
                           window=r0):
            sub_model = jax.tree.unflatten(
                model_def, [jnp.asarray(r) for r in st.model_rows])
            phase_c = jnp.asarray(st.phase_rows)
            aux = ({"cohort_nbr": jnp.asarray(st.nbr)}
                   if st.nbr is not None else ())
            rows = {k: jnp.asarray(v) for k, v in st.data_rows.items()}
            state = _active_state(sim, sub_model, phase_c, r0, aux)
            if mesh is not None:
                from .. import parallel as _parallel
                state = _parallel.shard_state(state, mesh)
                rows = _parallel.shard_data(rows, mesh)
            data_c = dict(eval_data)
            data_c.update(rows)

            args = (state, key, data_c, jnp.int32(last_round))
            if sim.sentinels is not None:
                hc = (sim._health_carry
                      if sim._health_carry is not None
                      else sim._health_zero_carry())
                args = args + (hc,)
        if cold:
            any_cold = True
            if sim.perf is not None and sim.perf.cost:
                # The start() AOT detour: bank the segment program's
                # own cost/memory analysis at compile time. The span IS
                # the compile measurement.
                sp_c = _tracing.span(
                    "cohort.compile", cat="cohort", tracer=tr,
                    program=f"cohort[{seg}r/C{c}]", window=r0)
                with sp_c:
                    try:
                        compiled = fn.lower(*args).compile()
                    except Exception:
                        compiled = None
                if compiled is not None:
                    sim._record_cost(
                        compiled,
                        label=f"cohort_start[{seg}r/C{c}]",
                        n_rounds=seg)
                    sim._jit_cache[cache_k] = compiled
                    fn = compiled
                    if sim.last_compile_seconds is None:
                        sim.last_compile_seconds = sp_c.duration
            try:
                fn._gossipy_warm = True  # jit wrappers take attrs
            except Exception:
                pass
        # cat="host.wait": dispatch + completion wait, not host work;
        # the bridged device span below accounts for it.
        sp_r = _tracing.span("cohort.run", cat=_tracing.WAIT_CAT,
                             tracer=tr, window=r0)
        with sp_r:
            out = fn(*args)
            if tr is not None:
                # The run span must close at execution end, not at
                # async dispatch (the scatter would otherwise absorb
                # the device wait invisibly).
                jax.block_until_ready(out)
        if tr is not None:
            _tracing.attach_device_spans(
                tr, sp_r.ts_us, sp_r.dur_us,
                args={"segment_rounds": seg, "window": r0})
        if sim.sentinels is not None:
            final_state, sim._health_carry, stats = out
        else:
            final_state, stats = out
        if cold and sim.last_compile_seconds is None:
            # No AOT detour: the cold dispatch folded tracing +
            # compilation — the run span is the best available compile
            # wall (plus execution when a tracer forced the sync above;
            # same caveat as engine.start).
            sim.last_compile_seconds = sp_r.duration
        with _tracing.span("cohort.fetch", cat="cohort", tracer=tr,
                           window=r0):
            seg_stats.append(jax.tree.map(np.asarray, stats))
            # copy=True is load-bearing: np.asarray here can be a
            # zero-copy view of the donated input buffers (CPU jax<->np
            # round-trips alias), whose memory dies with the staged
            # segment — but `pending`/`recent` must outlive it.
            out_model = [np.array(l, copy=True)
                         for l in jax.tree.leaves(final_state.model)]
            out_phase = np.array(final_state.phase, copy=True)
        return out_model, out_phase

    def flush_job(st: _Staged, out_model: list, out_phase: np.ndarray):
        """Scatter one segment's durable outputs back into the pool
        (flusher thread under prefetch; inline otherwise). The flusher
        owns coverage accounting — flushes are FIFO, so the incremental
        count matches the serial schedule exactly."""
        nonlocal touched_count
        with _tracing.span("cohort.scatter", cat="cohort", tracer=tr,
                           window=st.r0):
            for dst, src in zip(model_leaves, out_model):
                dst[st.idx] = src
            phase_leaf[st.idx] = out_phase
            newly = int(np.count_nonzero(~touched[st.idx]))
            touched[st.idx] = True
        touched_count += newly
        coverage.extend([touched_count / float(n)] * st.seg)
        if pend_lock is not None:
            with pend_lock:
                pending.pop(st.s, None)
            if tr is not None and st.ts_us is not None:
                # Streaming windows are emitted post-flush as explicit
                # complete events [sample start, flush end] — they
                # overlap in time, which trace_report's window-tag
                # attribution handles.
                tr.add_complete(
                    "cohort.segment", st.ts_us,
                    tr._now_us() - st.ts_us, cat="cohort",
                    args={"round_start": st.r0, "rounds": st.seg,
                          "streaming": True})

    # Every host segment is spanned (telemetry.tracing): the span handles
    # are the ONE timing source — perf exec wall reads the outer span,
    # last_compile_seconds reads the compile span — no parallel
    # perf_counter locals to drift from what the trace shows. The
    # per-segment "cohort.segment" span carries the round_start/rounds
    # window args scripts/trace_report.py reduces on; under prefetch the
    # inner sample/gather/compile/run/scatter spans carry window=r0 tags
    # because windows overlap and containment alone cannot attribute.
    sp_all = _tracing.span("cohort.start", cat="cohort", tracer=tr,
                           total_rounds=n_rounds, cohort_size=c,
                           prefetch=depth)
    with sp_all:
        if depth == 0:
            for s, (r0, seg) in enumerate(plan):
                with _tracing.span("cohort.segment", cat="cohort",
                                   tracer=tr, round_start=r0,
                                   rounds=seg):
                    st = stage_job(s, r0, seg)
                    out_model, out_phase = launch(st)
                    flush_job(st, out_model, out_phase)
        else:
            from concurrent.futures import ThreadPoolExecutor
            stager = ThreadPoolExecutor(
                1, thread_name_prefix="cohort-stage")
            flusher = ThreadPoolExecutor(
                1, thread_name_prefix="cohort-flush")
            stage_futs: dict[int, Any] = {}
            recent: dict[int, tuple] = {}
            flush_fut = None
            try:
                for s, (r0, seg) in enumerate(plan):
                    for j in range(s, min(s + depth + 1, len(plan))):
                        if j not in stage_futs:
                            stage_futs[j] = stager.submit(
                                stage_job, j, *plan[j])
                    st = stage_futs.pop(s).result()
                    # Launch-time patch: outputs that landed after the
                    # staged gather's snapshot (retained in `recent` for
                    # the last depth+1 segments) — ascending order, so
                    # the newest write wins, exactly like serial. Only
                    # outputs NEWER than everything the snapshot saw
                    # qualify: an output absent from `seen` but older
                    # than max(seen) was flushed before the snapshot
                    # (flushes are FIFO), so the raw gather already holds
                    # it — re-patching it here would clobber a newer
                    # pending output's overlay on shared rows.
                    cut = max(st.seen) if st.seen else -1
                    for o in sorted(recent):
                        if o > cut:
                            _patch_rows(st, *recent[o])
                    out_model, out_phase = launch(st)
                    if flush_fut is not None:
                        # Bound pending flushes to <= 1: by the time
                        # stage_job(s) was submitted, every output
                        # older than s - depth - 1 had been flushed.
                        flush_fut.result()
                    with pend_lock:
                        pending[s] = (st.idx, out_model, out_phase)
                    recent[s] = (st.idx, out_model, out_phase)
                    for o in [o for o in recent if o < s - depth]:
                        del recent[o]
                    flush_fut = flusher.submit(
                        flush_job, st, out_model, out_phase)
                if flush_fut is not None:
                    flush_fut.result()
            finally:
                stager.shutdown(wait=True)
                flusher.shutdown(wait=True)

    stats_all: dict = {}
    for k in seg_stats[0]:
        stats_all[k] = np.concatenate([s[k] for s in seg_stats], axis=0)
    stats_all["cohort_coverage"] = np.asarray(coverage, np.float32)
    stats_all["cohort_active_nodes"] = np.full((n_rounds,), c, np.int32)

    if perf_on:
        stats_all = sim._attach_perf_stats(stats_all, n_rounds,
                                           sp_all.duration, any_cold)
    report = sim._build_report(stats_all)
    if sim.metrics_enabled:
        stats_all = sim._feed_metrics(dict(stats_all), report, n_rounds)
    sim.replay_events(first_round, stats_all, sim._metric_keys(),
                      include_live=True)

    if store is not None:
        # Live disk-backed pools persist their round counter so a
        # re-opened pool_dir resumes where the run left off.
        store.flush()
        store.set_round(first_round + n_rounds)
    new_pool = CohortPool(model=pool.model, phase=pool.phase,
                          node_key=pool.node_key, touched=touched,
                          round=np.asarray(first_round + n_rounds,
                                           np.int32))
    return new_pool, report


def pool_bytes(sim) -> int:
    """Pool-residency bytes: the durable per-node state x nominal N (the
    ``memory_budget`` cohort block and the ladder's pool column)."""
    st = _model_shape(sim)
    per_node = sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                   for l in jax.tree.leaves(st))
    per_node += 4            # phase (int32)
    per_node += 8            # node_key (2 x uint32)
    per_node += 1            # touched (bool)
    return per_node * sim.nominal_n
