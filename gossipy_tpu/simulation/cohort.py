"""Sampled active-cohort rounds: population size decoupled from round cost.

The engine materializes every node every round — state is ``[N, ...]``,
the round program is ``[N]``-wide, and the 50k-node TPU run already dies
(``BENCH_TPU_EVIDENCE.jsonl`` row 3). "Millions of users" needs the
cross-device-FL shape instead (the actor/learner split of the Podracer
architectures, PAPERS.md): the full population of NOMINAL size N lives as
a host-resident pool of per-node durable state, and each round only a
sampled **cohort** of C nodes is materialized — gather the cohort's
state, run the standard jitted round program at shape ``[C, ...]``,
scatter the updates back. Per-round cost (compute, HBM, compile) is a
function of C; N only prices the pool.

    sim = GossipSimulator(handler, topology, data,
                          cohort=CohortConfig(size=4096))
    pool = sim.init_cohort_pool(key)
    pool, report = sim.start(pool, n_rounds=500, key=key)

What persists per node across rounds is the pool
(:class:`CohortPool`): model params + optimizer state + update counts,
the phase/period, a per-node PRNG key, and the touched-mask the coverage
accounting reads. Round-scoped state (mailbox, params-history ring,
reply box) is rebuilt per cohort from the gathered params — cohort
rotation drains in-flight traffic, one of the documented bias caveats
(docs/scale.md) vs full-population gossip.

Peer sampling inside a cohort round (``CohortConfig.peer_mode``):

- ``"resample"`` (default): peers drawn uniformly over the active cohort
  — the cross-device-FL reading where the round's participants gossip
  among themselves. No O(N) topology structure is ever touched, so this
  is the 10M-node path (pair it with :class:`NominalTopology` to skip
  building a graph at all).
- ``"induced"``: the topology-induced subgraph on the cohort, via the
  existing :class:`~gossipy_tpu.core.SparseTopology` neighbor-table
  machinery — each cohort node may only contact its real neighbors that
  are ALSO in the cohort (others' sends are skipped like isolated
  nodes). Exact subset semantics; at C << N most nodes are isolated, so
  this mode is for cohorts a sizable fraction of N.

``cohort=None`` (the default) traces the byte-identical round program —
the ``engine/cohort-off`` identity pair in ``analysis/hlo.py``'s gate
enforces it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import tracing as _tracing

# Report keys this layer adds (registered in report.PER_ROUND_FIELDS; the
# tracelint registry-field rule covers the cohort_ prefix).
COHORT_STAT_KEYS = ("cohort_coverage", "cohort_active_nodes")

_PEER_MODES = ("resample", "induced")


@dataclasses.dataclass(frozen=True)
class CohortConfig:
    """Active-cohort mode configuration.

    - ``size``: C, the number of nodes materialized per round.
    - ``rounds_per_cohort``: how many consecutive rounds one sampled
      cohort runs before rotating (1 = fresh cohort every round, the
      cross-device-FL default). Larger values amortize the gather/scatter
      against more in-cohort mixing.
    - ``peer_mode``: ``"resample"`` | ``"induced"`` (module doc).
    """

    size: int
    rounds_per_cohort: int = 1
    peer_mode: str = "resample"

    def __post_init__(self):
        if int(self.size) < 2:
            raise ValueError(f"cohort size must be >= 2, got {self.size}")
        if int(self.rounds_per_cohort) < 1:
            raise ValueError("rounds_per_cohort must be >= 1, got "
                             f"{self.rounds_per_cohort}")
        if self.peer_mode not in _PEER_MODES:
            raise ValueError(f"unknown peer_mode {self.peer_mode!r}; "
                             f"options: {_PEER_MODES}")

    @staticmethod
    def coerce(value: Union[None, int, dict, "CohortConfig"]
               ) -> Optional["CohortConfig"]:
        """None | C | dict | CohortConfig -> Optional[CohortConfig]."""
        if value is None or isinstance(value, CohortConfig):
            return value
        if isinstance(value, bool):
            raise ValueError("cohort= takes a size/config, not a bool")
        if isinstance(value, int):
            return CohortConfig(size=value)
        if isinstance(value, dict):
            return CohortConfig.from_dict(value)
        raise ValueError(f"cannot coerce {type(value).__name__} to "
                         "CohortConfig")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "CohortConfig":
        fields = {f.name for f in dataclasses.fields(CohortConfig)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(f"unknown cohort fields: {sorted(unknown)}; "
                             f"valid: {sorted(fields)}")
        return CohortConfig(**d)


class NominalTopology:
    """A population SIZE pretending to be a topology.

    Resample-mode cohorts never read edges, so a 10M-node run should not
    pay for (or even build) a 10M-node graph. This stand-in carries only
    ``num_nodes``; every structural query raises, which also guarantees
    it cannot silently reach a code path that needs real edges
    (``peer_mode="induced"``, chaos, the non-cohort engine).
    """

    def __init__(self, n: int):
        self.num_nodes = int(n)

    def __getattr__(self, name):
        raise AttributeError(
            f"NominalTopology has no {name!r}: it is a population size "
            "for resample-mode cohort runs, not a graph — use a real "
            "Topology/SparseTopology for edge-dependent features")

    def __repr__(self):
        return f"NominalTopology({self.num_nodes})"


class _CohortRoundTopology:
    """The inner round's C-node 'everyone may talk to everyone' world.

    ``sample_peers`` draws one uniform peer != self per node WITHOUT
    materializing a [C, C] adjacency (a clique at C=65536 would be 4 GB):
    ``peer_i = (i + 1 + U{0..C-2}) % C``. Expected fan-in is exactly
    ``F`` per node; the engine's mailbox/compaction sizing reads that
    through ``GossipSimulator._expected_fanin_vector``'s cohort branch.
    """

    def __init__(self, c: int):
        self.num_nodes = int(c)
        self.degrees = np.full(self.num_nodes, self.num_nodes - 1,
                               dtype=np.int64)

    def sample_peers(self, key: jax.Array) -> jax.Array:
        c = self.num_nodes
        r = jax.random.randint(key, (c,), 0, c - 1, dtype=jnp.int32)
        return (jnp.arange(c, dtype=jnp.int32) + 1 + r) % c

    def __repr__(self):
        return f"_CohortRoundTopology({self.num_nodes})"


class CohortPool(NamedTuple):
    """The resident per-node durable state of the nominal population.

    Every array leaf has leading axis N (host numpy by default — the pool
    is the thing that must NOT live in the round program's HBM budget).
    ``model`` is the stacked :class:`~gossipy_tpu.handlers.base.
    ModelState`; ``node_key`` the per-node PRNG key table the init drew
    from (gathered/scattered with the cohort so a node's identity
    survives checkpoints); ``touched`` the coverage-accounting mask;
    ``round`` the absolute round counter (round randomness keys off it,
    so a restored pool continues bit-for-bit).
    """

    model: Any
    phase: Any
    node_key: Any
    touched: Any
    round: Any


def setup_cohort(sim, topology):
    """Constructor-side wiring (called from ``GossipSimulator.__init__``
    when ``cohort=`` is given): validate the combination, remember the
    nominal population, and hand back the C-node inner round topology the
    rest of construction sizes against."""
    from .engine import GossipSimulator

    if type(sim) is not GossipSimulator:
        raise ValueError(
            f"cohort mode supports the base GossipSimulator only; "
            f"{type(sim).__name__} variants drive their own state shapes")
    cfg: CohortConfig = sim.cohort
    n = int(topology.num_nodes)
    if cfg.size > n:
        raise ValueError(f"cohort size {cfg.size} exceeds the nominal "
                         f"population {n}")
    sim.nominal_topology = topology
    sim.nominal_n = n
    sim._cohort_nbr_global = None
    if cfg.peer_mode == "induced":
        if isinstance(topology, NominalTopology):
            raise ValueError("peer_mode='induced' needs a real topology "
                             "(NominalTopology carries no edges)")
        from .nodes import build_neighbor_table
        sim._cohort_nbr_global = np.asarray(build_neighbor_table(topology),
                                            dtype=np.int32)
    return _CohortRoundTopology(cfg.size)


def induced_peers(sim, state, key: jax.Array) -> jax.Array:
    """Uniform peer draw over the cohort-induced subgraph: the cohort-
    local neighbor table rides ``state.aux["cohort_nbr"]`` ([C, max_deg],
    -1 = absent or not-in-cohort), so the compiled program is reused
    across cohorts — the table is data, not a trace constant. Nodes with
    no alive cohort neighbor get peer -1 (send skipped, like isolated
    nodes)."""
    nbr = state.aux["cohort_nbr"]
    alive = nbr >= 0
    logits = jnp.where(alive, 0.0, -jnp.inf)
    slot = jax.random.categorical(key, logits, axis=-1)
    has = alive.any(axis=-1)
    c = nbr.shape[0]
    peers = nbr[jnp.arange(c), jnp.clip(slot, 0, nbr.shape[1] - 1)]
    return jnp.where(has, peers, -1).astype(jnp.int32)


# -- pool construction -------------------------------------------------------

def _leaf_np(shape_dtype, n: int) -> np.ndarray:
    return np.empty((n,) + tuple(shape_dtype.shape),
                    dtype=np.dtype(shape_dtype.dtype))


def _model_shape(sim):
    return jax.eval_shape(sim.handler.init, jax.random.PRNGKey(0))


def pool_template(sim) -> CohortPool:
    """A zero-filled, correctly-shaped pool — the checkpoint-restore
    template (orbax needs structure + dtypes, not values), cheap even at
    nominal 10M (plain numpy zeros, no per-node init)."""
    n = sim.nominal_n
    st = _model_shape(sim)
    model = jax.tree.map(
        lambda l: np.zeros((n,) + tuple(l.shape), np.dtype(l.dtype)), st)
    key_t = np.zeros_like(
        np.asarray(jax.random.split(jax.random.PRNGKey(0), 2))[:1]
        .repeat(n, axis=0))
    return CohortPool(model=model,
                      phase=np.zeros(n, np.int32),
                      node_key=key_t,
                      touched=np.zeros(n, bool),
                      # 0-d ndarray, not a numpy scalar: orbax's restore-
                      # args builder only types ndarrays.
                      round=np.zeros((), np.int32))


def init_cohort_pool(sim, key: jax.Array, common_init: bool = False,
                     local_train: bool = False,
                     block: Optional[int] = None) -> CohortPool:
    """Initialize the resident pool (the cohort-mode ``init_nodes``).

    Per-node model init runs in device blocks of ``block`` nodes
    (default ``max(C, 65536)``) so nominal-10M pools never materialize
    the whole population on one device at once — each block's leaves are
    copied straight into preallocated host numpy.

    ``local_train`` defaults to **False** (unlike ``init_nodes``): the
    reference's init-time local pass would gather every node's data shard
    at pool scale. With it off, a node takes its first local update the
    first time it is sampled into a cohort — a documented bias vs the
    materialized engine (docs/scale.md). Pass ``True`` to pay the
    blocked pre-training pass anyway.
    """
    n = sim.nominal_n
    cfg = sim.cohort
    block = int(block or max(cfg.size, 65536))
    k_init, k_phase, k_up = jax.random.split(key, 3)
    node_keys = np.asarray(jax.random.split(k_init, n))

    st_shape = _model_shape(sim)
    model = jax.tree.map(lambda l: _leaf_np(l, n), st_shape)
    flat_model = jax.tree.leaves(model)

    if common_init:
        one = jax.tree.map(np.asarray, sim.handler.init(k_init))
        for dst, src in zip(flat_model, jax.tree.leaves(one)):
            dst[...] = src[None]
    else:
        init_block = jax.jit(jax.vmap(sim.handler.init))
        for lo in range(0, n, block):
            hi = min(lo + block, n)
            blk = init_block(jnp.asarray(node_keys[lo:hi]))
            for dst, src in zip(flat_model, jax.tree.leaves(blk)):
                dst[lo:hi] = np.asarray(src)

    if local_train:
        p = _pool_data_rows(sim)
        upd_block = jax.jit(jax.vmap(sim.handler.update))
        for lo in range(0, n, block):
            hi = min(lo + block, n)
            idx = np.arange(lo, hi)
            sub = jax.tree.map(lambda l: jnp.asarray(l[lo:hi]), model)
            data = tuple(jnp.asarray(d)[jnp.asarray(idx % p)]
                         for d in (np.asarray(sim.data["xtr"]),
                                   np.asarray(sim.data["ytr"]),
                                   np.asarray(sim.data["mtr"])))
            keys = jax.random.split(jax.random.fold_in(k_up, lo), hi - lo)
            out = upd_block(sub, data, keys)
            for dst, src in zip(flat_model, jax.tree.leaves(out)):
                dst[lo:hi] = np.asarray(src)

    if sim.sync:
        phase = np.asarray(jax.random.randint(
            k_phase, (n,), 0, sim.delta, dtype=jnp.int32))
    else:
        raw = sim.delta + (sim.delta / 10.0) * np.asarray(
            jax.random.normal(k_phase, (n,)))
        phase = np.maximum(raw.astype(np.int32), 1)

    return _host_pool(CohortPool(model=model, phase=phase,
                                 node_key=node_keys,
                                 touched=np.zeros(n, bool),
                                 round=np.zeros((), np.int32)))


def _host_pool(pool: CohortPool, copy: bool = False) -> CohortPool:
    """Normalize a pool to WRITABLE host numpy leaves (jax exports and
    orbax restores can hand back read-only buffers; the scatter half of
    the segment loop writes in place). ``copy=True`` copies every leaf —
    ``cohort_start`` uses it so the caller's pool keeps its value
    semantics (a FlightRecorder's "last healthy state" reference must
    not alias the scatter target)."""
    def h(l):
        a = np.asarray(l)
        return a.copy() if copy or not a.flags.writeable else a
    return jax.tree.map(h, pool)


def _pool_data_rows(sim) -> int:
    """Leading axis P of the pool's per-node data: node ``i`` reads row
    ``i % P``, so a pool of nominal N can ride a data bank of P << N
    shards (at 10M users nobody stacks 10M distinct shards)."""
    return int(sim.data["xtr"].shape[0])


# -- cohort sampling ---------------------------------------------------------

def _seed_material(key: jax.Array) -> list[int]:
    """Deterministic host seed material from a jax PRNG key (typed or
    raw uint32)."""
    try:
        if jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key):
            key = jax.random.key_data(key)
    except Exception:
        pass
    return [int(x) for x in np.asarray(key).ravel().astype(np.uint32)]


def sample_cohort(key: jax.Array, round0: int, n: int, c: int) -> np.ndarray:
    """The round-``round0`` cohort: C distinct node ids, deterministic in
    ``(key, round0)`` — a restored pool re-draws the identical schedule.

    At C << N the draw rejection-samples uniques (no O(N) permutation —
    the 10M path); small ratios fall back to numpy's exact choice.
    Sorted ascending for gather locality.
    """
    ss = np.random.SeedSequence(_seed_material(key) + [int(round0)])
    rng = np.random.default_rng(ss)
    if c >= n:
        return np.arange(n, dtype=np.int64)
    if c * 8 >= n:
        return np.sort(rng.choice(n, c, replace=False).astype(np.int64))
    out = np.unique(rng.integers(0, n, int(c * 1.1) + 16))
    while out.size < c:
        out = np.unique(np.concatenate(
            [out, rng.integers(0, n, c)]))
    rng.shuffle(out)  # drop the unique-sort's small-id bias before cutting
    return np.sort(out[:c])


def _local_neighbor_table(sim, idx: np.ndarray) -> np.ndarray:
    """[C, max_deg] cohort-LOCAL neighbor slots for ``peer_mode='induced'``:
    gather the global table's cohort rows, keep entries that are
    themselves in the cohort (membership via an inverse-index table),
    everything else -1."""
    n = sim.nominal_n
    nbr = sim._cohort_nbr_global[idx]  # [C, max_deg] global ids / -1
    pos = np.full(n, -1, dtype=np.int32)
    pos[idx] = np.arange(idx.size, dtype=np.int32)
    local = np.where(nbr >= 0, pos[np.clip(nbr, 0, n - 1)], -1)
    return local.astype(np.int32)


# -- the round-segment program ----------------------------------------------

def _active_state(sim, model, phase, round0: int, aux):
    """A [C]-shaped SimState for one cohort segment: gathered durable
    state + freshly-built round-scoped state (empty mailboxes, history
    ring re-broadcast from the gathered params — cohort rotation has no
    in-flight traffic to preserve, so the broadcast IS the ring a
    same-round send would read)."""
    from .engine import Mailbox, SimState
    c = sim.n_nodes
    d = sim._history_depth(sim._model_size(model.params))
    stored, scales = sim._encode_history_rows(model.params)
    bcast = lambda l: jnp.broadcast_to(l[None], (d,) + l.shape)
    hist_p = jax.tree.map(bcast, stored)
    hist_s = (jax.tree.map(bcast, scales)
              if sim.history_dtype == "int8" else ())
    hist_a = jnp.broadcast_to(model.n_updates[None],
                              (d,) + model.n_updates.shape)
    return SimState(
        model=model, phase=phase,
        history_params=hist_p, history_ages=hist_a,
        mailbox=Mailbox.empty(d, c, sim.K),
        reply_box=Mailbox.empty(d, c, sim.Kr),
        round=jnp.int32(round0), aux=aux, history_scale=hist_s)


def _make_cohort_run(sim, n_rounds: int):
    """The segment program: ``(state, key, data, last_round[, hc]) ->
    (state[, hc], stats)``. The ``_make_run`` scan with the RUN's final
    absolute round as a traced argument — segments share one compiled
    program even though only the last one force-evaluates."""
    sentinels_on = sim.sentinels is not None

    def scan_rounds(state, key, last_round, hc):
        def body(carry, _):
            if sentinels_on:
                st, c = carry
                pre_params = st.model.params
            else:
                st, c = carry, None
            st, stats = sim._round(st, key, last_round)
            if sentinels_on:
                c, hstats = sim._health_round(c, pre_params, st, stats)
                stats.update(hstats)
            return ((st, c) if sentinels_on else st), stats

        init = (state, hc) if sentinels_on else state
        return jax.lax.scan(body, init, None, length=n_rounds)

    if sentinels_on:
        def run(state, key, data, last_round, hc):
            saved = sim.data
            sim.data = data
            try:
                (state, hc), stats = scan_rounds(state, key, last_round, hc)
                return state, hc, stats
            finally:
                sim.data = saved
    else:
        def run(state, key, data, last_round):
            saved = sim.data
            sim.data = data
            try:
                return scan_rounds(state, key, last_round, None)
            finally:
                sim.data = saved
    return run


def _segment_fn(sim, seg_rounds: int):
    """Compile-cached segment program (one per distinct segment length —
    the tail segment of a run whose n_rounds is not a multiple of
    rounds_per_cohort costs one extra compile, like CheckpointManager
    tail chunks)."""
    cache_k = ("cohort", seg_rounds, sim._cache_salt())
    if cache_k not in sim._jit_cache:
        fn = jax.jit(_make_cohort_run(sim, seg_rounds), donate_argnums=(0,))
        sim._jit_cache[cache_k] = fn
    return sim._jit_cache[cache_k], cache_k


# -- the driver --------------------------------------------------------------

def cohort_start(sim, pool: CohortPool, n_rounds: int,
                 key: Optional[jax.Array] = None):
    """Run ``n_rounds`` active-cohort rounds against the resident pool.

    Host-driven segment loop (the actor/learner split): per segment,
    sample the cohort (deterministic in ``(key, absolute round)``),
    gather pool rows + data rows, run the jitted ``[C]`` round program,
    scatter the durable state back and advance the pool round counter.
    Returns ``(pool, SimulationReport)`` — the report carries the
    standard per-round arrays at cohort width plus the
    ``cohort_coverage`` / ``cohort_active_nodes`` accounting rows.
    """
    if not isinstance(pool, CohortPool):
        raise TypeError(
            "cohort mode takes the resident CohortPool (init_cohort_pool), "
            f"got {type(pool).__name__}")
    if key is None:
        key = jax.random.PRNGKey(42)
    cfg: CohortConfig = sim.cohort
    c, n = cfg.size, sim.nominal_n
    p_rows = _pool_data_rows(sim)
    first_round = int(np.asarray(pool.round))
    last_round = first_round + n_rounds - 1

    if sim.has_live_receivers():
        import warnings
        warnings.warn("cohort mode has no in-run host callback path; live "
                      "event receivers fall back to post-run replay")

    pool = _host_pool(pool, copy=True)
    model_leaves = jax.tree.leaves(pool.model)
    touched = pool.touched
    seg_stats: list[dict] = []
    coverage: list[float] = []
    perf_on = sim.perf is not None and sim.perf.timing
    any_cold = False
    tr = getattr(sim, "tracer", None)

    # Every host segment is spanned (telemetry.tracing): the span handles
    # are the ONE timing source — perf exec wall reads the outer span,
    # last_compile_seconds reads the compile span — no parallel
    # perf_counter locals to drift from what the trace shows. The
    # per-segment "cohort.segment" span carries the round_start/rounds
    # window args scripts/trace_report.py reduces on.
    sp_all = _tracing.span("cohort.start", cat="cohort", tracer=tr,
                           total_rounds=n_rounds, cohort_size=c)
    with sp_all:
        done = 0
        while done < n_rounds:
            seg = min(cfg.rounds_per_cohort, n_rounds - done)
            r0 = first_round + done
            with _tracing.span("cohort.segment", cat="cohort", tracer=tr,
                               round_start=r0, rounds=seg):
                fn, cache_k = _segment_fn(sim, seg)
                cold = not getattr(fn, "_gossipy_warm", False)

                with _tracing.span("cohort.sample", cat="cohort",
                                   tracer=tr):
                    idx = sample_cohort(key, r0, n, c)
                    jidx = jnp.asarray(idx)
                with _tracing.span("cohort.gather", cat="cohort",
                                   tracer=tr):
                    sub_model = jax.tree.map(
                        lambda l: jnp.asarray(np.asarray(l)[idx]),
                        pool.model)
                    phase_c = jnp.asarray(np.asarray(pool.phase)[idx])
                    aux = ()
                    if cfg.peer_mode == "induced":
                        aux = {"cohort_nbr": jnp.asarray(
                            _local_neighbor_table(sim, idx))}
                    data_c = {k: (v if k in ("x_eval", "y_eval")
                                  else v[jidx % p_rows])
                              for k, v in sim.data.items()}
                    state = _active_state(sim, sub_model, phase_c, r0,
                                          aux)

                args = (state, key, data_c, jnp.int32(last_round))
                if sim.sentinels is not None:
                    hc = (sim._health_carry
                          if sim._health_carry is not None
                          else sim._health_zero_carry())
                    args = args + (hc,)
                if cold:
                    any_cold = True
                    if sim.perf is not None and sim.perf.cost:
                        # The start() AOT detour: bank the segment
                        # program's own cost/memory analysis at compile
                        # time. The span IS the compile measurement.
                        sp_c = _tracing.span(
                            "cohort.compile", cat="cohort", tracer=tr,
                            program=f"cohort[{seg}r/C{c}]")
                        with sp_c:
                            try:
                                compiled = fn.lower(*args).compile()
                            except Exception:
                                compiled = None
                        if compiled is not None:
                            sim._record_cost(
                                compiled,
                                label=f"cohort_start[{seg}r/C{c}]",
                                n_rounds=seg)
                            sim._jit_cache[cache_k] = compiled
                            fn = compiled
                            if sim.last_compile_seconds is None:
                                sim.last_compile_seconds = sp_c.duration
                    try:
                        fn._gossipy_warm = True  # jit wrappers take attrs
                    except Exception:
                        pass
                # cat="host.wait": dispatch + completion wait, not host
                # work; the bridged device span below accounts for it.
                sp_r = _tracing.span("cohort.run", cat=_tracing.WAIT_CAT,
                                     tracer=tr)
                with sp_r:
                    out = fn(*args)
                    if tr is not None:
                        # The run span must close at execution end, not
                        # at async dispatch (the scatter below would
                        # otherwise absorb the device wait invisibly).
                        jax.block_until_ready(out)
                if tr is not None:
                    _tracing.attach_device_spans(
                        tr, sp_r.ts_us, sp_r.dur_us,
                        args={"segment_rounds": seg})
                if sim.sentinels is not None:
                    final_state, sim._health_carry, stats = out
                else:
                    final_state, stats = out
                if cold and sim.last_compile_seconds is None:
                    # No AOT detour: the cold dispatch folded tracing +
                    # compilation — the run span is the best available
                    # compile wall (plus execution when a tracer forced
                    # the sync above; same caveat as engine.start).
                    sim.last_compile_seconds = sp_r.duration

                # Scatter the durable state back into the pool (host).
                with _tracing.span("cohort.scatter", cat="cohort",
                                   tracer=tr):
                    for dst, src in zip(model_leaves,
                                        jax.tree.leaves(
                                            final_state.model)):
                        dst[idx] = np.asarray(src)
                    pool.phase[idx] = np.asarray(final_state.phase)
                    touched[idx] = True
                cov = float(touched.mean())
                coverage.extend([cov] * seg)
                seg_stats.append(jax.tree.map(np.asarray, stats))
            done += seg

    stats_all: dict = {}
    for k in seg_stats[0]:
        stats_all[k] = np.concatenate([s[k] for s in seg_stats], axis=0)
    stats_all["cohort_coverage"] = np.asarray(coverage, np.float32)
    stats_all["cohort_active_nodes"] = np.full((n_rounds,), c, np.int32)

    if perf_on:
        stats_all = sim._attach_perf_stats(stats_all, n_rounds,
                                           sp_all.duration, any_cold)
    report = sim._build_report(stats_all)
    if sim.metrics_enabled:
        stats_all = sim._feed_metrics(dict(stats_all), report, n_rounds)
    sim.replay_events(first_round, stats_all, sim._metric_keys(),
                      include_live=True)

    new_pool = CohortPool(model=pool.model, phase=pool.phase,
                          node_key=pool.node_key, touched=touched,
                          round=np.asarray(first_round + n_rounds,
                                           np.int32))
    return new_pool, report


def pool_bytes(sim) -> int:
    """Pool-residency bytes: the durable per-node state x nominal N (the
    ``memory_budget`` cohort block and the ladder's pool column)."""
    st = _model_shape(sim)
    per_node = sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                   for l in jax.tree.leaves(st))
    per_node += 4            # phase (int32)
    per_node += 8            # node_key (2 x uint32)
    per_node += 1            # touched (bool)
    return per_node * sim.nominal_n
