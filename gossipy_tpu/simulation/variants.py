"""Simulator variants: token-account flow control and all-to-all mixing.

Re-designs of ``TokenizedGossipSimulator`` (reference simul.py:506-689) and
``All2AllGossipSimulator`` + ``All2AllGossipNode`` (simul.py:720-852,
node.py:789-870).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import AntiEntropyProtocol, CreateModelMode, MessageType
from ..flow_control import TokenAccount
from ..handlers.base import ModelState
from ..telemetry import (
    PHASE_EVAL,
    PHASE_RECEIVE_MERGE,
    PHASE_SEND,
    PHASE_TRAIN,
    FailureCounts,
)
from ..telemetry.probes import consensus_stats, sq_param_distance
from .engine import GossipSimulator, PROTO_TO_MSG, SimState, select_nodes
from .nodes import PartitioningGossipSimulator

# Variant PRNG purpose tags (>= 9000; engine-internal tags stay below).
_K_REACT_GATE = 9000       # proactive send gate
_K_REACT_SLOT = 9100       # + slot k: reactive randomized rounding
_K_REACT_PEER = 9200       # + 10*j: reaction wave peer choice
_K_REACT_DROP = 9201       # + 10*j
_K_REACT_DELAY = 9202      # + 10*j
_K_REACT_EXTRA = 9203      # + 10*j
_K_A2A_DROP = 9400
_K_A2A_ONLINE = 9401
_K_A2A_UPDATE = 9402


class TokenizedGossipSimulator(GossipSimulator):
    """Gossip with Danner-2018 token-account flow control.

    Per-node integer token balances live in ``state.aux``:

    - At timeout, a node sends with probability ``account.proactive(balance)``;
      otherwise it banks a token (reference simul.py:602-615).
    - On receiving a message that needs no reply, the receiver computes the
      message utility and performs ``account.reactive(balance, utility)``
      extra sends, debiting its balance (simul.py:631-648). Extra sends are
      capped at ``max_reactions`` per node per round (static shapes;
      SURVEY.md §7(e)) and delivered from the next round onwards.

    Intentional divergence: the reference's reactive block reuses a stale
    loop variable so reactions are emitted by the wrong node (simul.py:640,
    ``node`` is whatever the send loop last touched); here reactions
    correctly originate from the receiver.

    ``utility_fun(receiver_model: ModelState, sender_snapshot: PeerModel) ->
    [N] array`` replaces the reference's per-message callable; the repro
    config uses a constant 1 (main_hegedus_2021.py:59).
    """

    def __init__(self, *args, token_account: TokenAccount,
                 utility_fun: Optional[Callable] = None,
                 max_reactions: int = 3, **kwargs):
        super().__init__(*args, **kwargs)
        self.account = token_account
        self.utility_fun = utility_fun or (
            lambda recv_model, sender_snap: jnp.ones(self.n_nodes, jnp.float32))
        self.max_reactions = int(max_reactions)

    def _init_aux(self, model: ModelState, key: jax.Array):
        return {"balance": self.account.init_balance(self.n_nodes),
                "pending_reactions": jnp.zeros(self.n_nodes, dtype=jnp.int32)}

    def _send_gate(self, state: SimState, active, peers, base_key, r):
        balance = state.aux["balance"]
        p = self.account.proactive(balance)
        gate = jax.random.bernoulli(
            self._round_key(base_key, r, _K_REACT_GATE), jnp.clip(p, 0.0, 1.0))
        send = active & gate
        # Nodes that timed out but were gated bank one token (simul.py:613-615).
        balance = balance + (active & ~gate).astype(jnp.int32)
        aux = dict(state.aux)
        aux["balance"] = balance
        return send, state._replace(aux=aux)

    def _post_receive_slot(self, state: SimState, valid, ty, sender,
                           send_round, extra, base_key, r, k) -> SimState:
        # Reactions fire for messages that produce no reply (simul.py:636-639).
        no_reply = ~((ty == MessageType.PULL) | (ty == MessageType.PUSH_PULL))
        trigger = valid & no_reply
        # Sender snapshot for the utility: the cell the message was SENT
        # from (its payload), not this round's — the reference computes
        # utility on the *received* handler (simul.py:631-648), which under
        # delay is the sent-time model. Invisible with the constant utility
        # the shipped experiment uses (main_hegedus_2021.py:59); tested with
        # a snapshot-sensitive utility under UniformDelay.
        peer = self._gather_peer(state, send_round, sender)
        utility = self.utility_fun(state.model, peer)
        balance = state.aux["balance"]
        reaction = self.account.reactive(
            balance, jnp.where(trigger, utility, 0.0),
            self._round_key(base_key, r, _K_REACT_SLOT + k))
        reaction = jnp.where(trigger, reaction, 0)
        # Cap at the per-round reaction budget and only debit tokens for
        # sends that will actually be performed — tokens beyond the cap stay
        # banked for later rounds instead of vanishing.
        pending = state.aux["pending_reactions"]
        performed = jnp.minimum(reaction,
                                jnp.maximum(self.max_reactions - pending, 0))
        performed = jnp.minimum(performed, balance)  # flow_control.py:43-52
        aux = dict(state.aux)
        aux["balance"] = balance - performed
        aux["pending_reactions"] = pending + performed
        return state._replace(aux=aux)

    def _post_deliver(self, state: SimState, base_key, r):
        n = self.n_nodes
        size = self._model_size(state.model.params)
        pending = state.aux["pending_reactions"]
        n_sent = jnp.int32(0)
        fails = FailureCounts.zeros()
        total_size = jnp.int32(0)
        msg_type = PROTO_TO_MSG[self.protocol]
        for j in range(self.max_reactions):
            fire = pending > j
            if self.chaos is not None:
                fire = fire & ~self._chaos_forced_offline(r)
            kj = self._round_key(base_key, r, _K_REACT_PEER + 10 * j)
            if self.chaos is not None and self._chaos_edge_form is not None:
                peers = self._chaos_masked_peers(kj, r)
            else:
                peers = self.topology.sample_peers(kj)
            active = fire & (peers >= 0)
            dropped = jax.random.bernoulli(
                self._round_key(base_key, r, _K_REACT_DROP + 10 * j),
                self._chaos_drop_prob(r), (n,))
            delays = self._chaos_scale_delays(self.delay.sample(
                self._round_key(base_key, r, _K_REACT_DELAY + 10 * j),
                (n,), size), r)
            # Reaction messages are emitted mid-round; same-round delivery is
            # not possible once the mailbox cell was drained, so the earliest
            # delivery is next round (documented divergence).
            dr = jnp.maximum(delays // self.delta, 1)
            n_sent += active.sum()
            total_size += active.sum() * size
            fails = fails._replace(drop=fails.drop + (active & dropped).sum())
            live = active & ~dropped
            box, n_overflow = self._scatter_messages(
                state.mailbox, live, dr, peers, jnp.arange(n, dtype=jnp.int32),
                jnp.broadcast_to(r.astype(jnp.int32), (n,)),
                jnp.full((n,), int(msg_type), dtype=jnp.int32),
                self._send_extra(self._round_key(base_key, r, _K_REACT_EXTRA + 10 * j), state), r, self.K)
            fails = fails._replace(overflow=fails.overflow + n_overflow)
            state = state._replace(mailbox=box)
        aux = dict(state.aux)
        aux["pending_reactions"] = jnp.zeros_like(pending)
        return state._replace(aux=aux), n_sent, fails, total_size


class TokenizedPartitioningGossipSimulator(TokenizedGossipSimulator,
                                           PartitioningGossipSimulator):
    """Token-account flow control over partitioned model exchange.

    The reference composes these orthogonally: ``PartitioningBasedNode``
    objects inside a ``TokenizedGossipSimulator`` (main_hegedus_2021.py:35-60).
    The MRO does the same here: tokenized send gates / reactions +
    partition-id payload hooks, both cooperative subclasses of the engine.
    """


class All2AllGossipSimulator(GossipSimulator):
    """Koloskova-style decentralized SGD: broadcast + weighted mixing.

    Reference behavior (simul.py:720-852 + node.py:789-870): every timed-out
    node PUSHes to ALL peers; receivers park models; at its own timeout a
    node merges its cache with mixing weights (``WeightedTMH``) and trains.

    TPU-native formulation: with round-start params stacked as ``P [N, ...]``
    and the effective (drop/churn-masked, row-renormalized) mixing matrix
    ``W_eff [N, N]``, the entire network's merge is ONE einsum
    ``P' = W_eff @ P`` — dense MXU work instead of N^2 Python receives —
    followed by the vmapped local update.

    Documented divergences: lost messages' mixing weight is redistributed by
    row renormalization (the reference silently shrinks the average,
    node.py:841 with missing cache entries); message delays collapse to
    round granularity (a round's mix uses round-start snapshots).

    ``history_dtype`` (engine knob): under a quantized wire format the PEER
    contributions to the mix are routed through the wire round-trip
    (quantize -> dequantize, modelling the broadcast payload) while each
    node's self term stays exact; the fp32 default keeps today's single
    fused matmul unchanged.

    With ``ring_mix=True`` (requires ``mesh``) the mixing matmul runs as an
    explicit shard_map + ppermute ring schedule over the mesh's node axis
    (:mod:`gossipy_tpu.parallel.collectives`) instead of a dense einsum whose
    collectives XLA chooses: per-hop MXU work pipelines with ICI chunk
    rotation and no device materializes the full stacked params.

    With a :class:`~gossipy_tpu.core.SparseMixing` (O(E) edge weights over a
    ``SparseTopology``) the merge never builds an [N, N] tensor. Two
    formulations, chosen at construction by degree shape: near-regular
    graphs pad into [N, max_deg] tables and mix with a gather + einsum
    (regular shapes, no scatter — the TPU-native form); heavy-tailed
    graphs (hubs) keep the edge-list gather + sorted ``segment_sum``.
    """

    def __init__(self, *args, mixing, mesh=None,
                 ring_mix: bool = False, sparse_mix_form: str = "auto",
                 **kwargs):
        from ..core import SparseMixing
        kwargs.setdefault("protocol", AntiEntropyProtocol.PUSH)
        # The All2All round never reads the mailbox (the whole neighborhood
        # mixes in one einsum/segment-sum) — don't let the derived hub-aware
        # default allocate a dead [D, N, 64] metadata ring.
        kwargs.setdefault("mailbox_slots", 1)
        super().__init__(*args, **kwargs)
        assert self.protocol == AntiEntropyProtocol.PUSH, \
            "All2AllNode only supports PUSH protocol."  # node.py:856-858
        if sparse_mix_form not in ("auto", "padded", "segment"):
            # Validated for BOTH mixing kinds: a typo must not silently
            # no-op on the dense path.
            raise ValueError(f"unknown sparse_mix_form {sparse_mix_form!r}; "
                             "options: auto, padded, segment")
        self.sparse_mix = isinstance(mixing, SparseMixing)
        if self.sparse_mix:
            if mixing.num_nodes != self.n_nodes:  # must survive python -O
                raise ValueError("mixing/topology node-count mismatch: "
                                 f"{mixing.num_nodes} vs {self.n_nodes}")
            # The segment ops run with indices_are_sorted=True; a hand-built
            # mixing with unsorted rows would produce silently wrong sums —
            # explicit raise, must survive python -O.
            rows = np.asarray(mixing.rows)
            if rows.size and not (np.diff(rows) >= 0).all():
                raise ValueError("SparseMixing.rows must be non-decreasing "
                                 "(CSR row order)")
            self.mixing = mixing
            # Formulation choice (override with sparse_mix_form=
            # "padded"/"segment"): on TPU with near-regular graphs, pad the
            # edge weights into [N, max_deg] tables so the merge is a plain
            # gather + einsum (MXU/VPU work, no scatter — segment_sum
            # lowers to sort+scatter there). On CPU the sorted segment-sum
            # wins (measured: 2.9 vs 1.1 r/s at 50k nodes — the [N, S, D]
            # gather materialization dominates). Heavy-tailed degree
            # distributions (BA hubs) always take the segment path: padding
            # to a hub's degree would be O(N * max_deg).
            degrees = np.bincount(rows, minlength=self.n_nodes)
            max_deg = int(degrees.max()) if rows.size else 0
            mean_deg = float(degrees.mean()) if rows.size else 0.0
            near_regular = (max_deg > 0
                            and max_deg <= max(4.0 * mean_deg, 8.0))
            if sparse_mix_form == "auto":
                self._sparse_padded = (near_regular
                                       and jax.default_backend() == "tpu")
            else:
                if sparse_mix_form == "padded" and not near_regular:
                    raise ValueError(
                        "sparse_mix_form='padded' on a heavy-tailed degree "
                        f"distribution (max {max_deg} vs mean "
                        f"{mean_deg:.1f}) would pad O(N * max_deg); use "
                        "'segment'")
                self._sparse_padded = sparse_mix_form == "padded"
            if self._sparse_padded:
                senders = np.asarray(mixing.senders)
                pos = np.arange(len(rows)) - np.searchsorted(rows, rows)
                nbr = np.zeros((self.n_nodes, max_deg), np.int32)
                wt = np.zeros((self.n_nodes, max_deg), np.float32)
                slot_valid = np.zeros((self.n_nodes, max_deg), bool)
                nbr[rows, pos] = senders
                wt[rows, pos] = np.asarray(mixing.edge_w)
                slot_valid[rows, pos] = True
                self._nbr_tab = jnp.asarray(nbr)
                self._w_tab = jnp.asarray(wt)
                self._slot_valid = jnp.asarray(slot_valid)
                # CSR-edge -> padded-slot scatter coordinates, used to
                # land the chaos per-edge alive mask in slot layout.
                self._pad_rows = jnp.asarray(rows.astype(np.int32))
                self._pad_pos = jnp.asarray(pos.astype(np.int32))
        else:
            # Fail at construction, not at the first jitted round's
            # adjacency_dev access deep inside _round (must survive -O).
            if not hasattr(self.topology, "adjacency_dev"):
                raise ValueError(
                    "a SparseTopology requires SparseMixing (pass "
                    "uniform_mixing(sparse_topology)); dense mixing arrays "
                    "need a dense Topology")
            self.mixing = jnp.asarray(mixing, dtype=jnp.float32)
        self.mesh = mesh
        self.ring_mix = bool(ring_mix)
        if self.ring_mix:
            assert mesh is not None, "ring_mix=True requires a mesh"
            assert not self.sparse_mix, \
                "ring_mix schedules the dense mixing matmul; use the " \
                "segment-sum sparse path without a ring"
            # Ring over the same axes the node dimension is sharded on — all
            # mesh axes combined on a 2-D (dcn, nodes) mesh, matching
            # parallel.shard_state's placement.
            from ..parallel import _node_axis_entry
            from ..parallel.collectives import _axis_size
            self._ring_axis = _node_axis_entry(mesh, None)
            assert self.n_nodes % _axis_size(mesh, self._ring_axis) == 0, \
                "node count must divide the mesh's node axes for ring_mix"

    def _warn_if_mailbox_undersized(self) -> None:
        """No-op: broadcast mixing cannot lose messages to slot overflow
        (the mailbox exists only as engine-state plumbing here; with the
        pinned ``mailbox_slots`` this also skips the O(E) fan-in scan)."""

    def _round(self, state: SimState, base_key: jax.Array, last_round=None):
        r = state.round
        # Probe plumbing (opt-in; None traces the exact pre-feature round).
        # Broadcast mixing has no mailbox: every contribution is a
        # same-round round-start snapshot, so staleness is structurally 0
        # and the accepted-merge count is the per-node count of live
        # incoming weighted edges. The merge/train delta split is exact
        # here — the mix and the local update are separate phases.
        probe_mix = self.probes is not None and (self.probes.mixing
                                                 or self.probes.staleness)
        # All2All-branch sentinel vital (telemetry.health): the effective
        # mixing weights are the one quantity this round shape owns that
        # the engine-generic vitals cannot see — a non-finite weight
        # (degenerate row renormalization) poisons every leaf it touches
        # before any param goes bad. Counted per round across whichever
        # formulation (dense / padded / segment) this simulator compiled.
        health_nf = self.sentinels is not None and self.sentinels.nonfinite
        mix_bad = None
        acc_count = None
        merge_sq = train_sq = jnp.float32(0)
        n_chaos = jnp.int32(0)
        with jax.named_scope(PHASE_SEND):
            state = self._snapshot(state, r)
            n = self.n_nodes
            fires, _ = self._fire_mask(state, r)

            online = jax.random.bernoulli(
                self._round_key(base_key, r, _K_A2A_ONLINE),
                self.online_prob, (n,))
            if self.chaos is not None:
                # Scheduled outages silence a node on BOTH sides of the
                # broadcast (it neither fires nor receives); partitions/
                # churn mask the mixed edge set per round below. Drop
                # spikes override the per-edge drop rate.
                forced = self._chaos_forced_offline(r)
                fires = fires & ~forced
                online = online & ~forced
        chaos_edges = (self.chaos is not None
                       and self._chaos_edge_form is not None)
        if chaos_edges:
            sched = self.chaos_schedule
            chaos_m = sched.mask_idx[self._chaos_t(r)]
        if self.sparse_mix and self._sparse_padded:
            # Padded [N, max_deg] formulation (near-regular graphs): the
            # merge is a gather + einsum — regular shapes, no scatter; the
            # TPU-native form of the sparse mix.
            nbr, wt, slot = self._nbr_tab, self._w_tab, self._slot_valid
            if chaos_edges:
                # Per-round alive-edge mask scattered from the CSR-order
                # per-edge mask into the padded slot layout (one O(E)
                # scatter per round; masked edges do not exist — their
                # sends are neither counted nor failed).
                pad = jnp.zeros(slot.shape, bool).at[
                    self._pad_rows, self._pad_pos].set(sched.csr_masks[chaos_m])
                slot = slot & pad
            drop = jax.random.bernoulli(
                self._round_key(base_key, r, _K_A2A_DROP),
                self._chaos_drop_prob(r), wt.shape)
            sent = fires[nbr] & slot
            live = sent & ~drop & online[:, None]
            w = wt * live
            row_sum = self.mixing.self_w + w.sum(axis=1)
            inv = 1.0 / jnp.maximum(row_sum, 1e-12)
            w_eff = w * inv[:, None]
            self_eff = self.mixing.self_w * inv
            if health_nf:
                mix_bad = ((~jnp.isfinite(w_eff)).sum()
                           + (~jnp.isfinite(self_eff)).sum()) \
                    .astype(jnp.int32)

            def mix_tree(params):
                # Peer contributions travel the wire: gather the wire-format
                # round-trip of the senders' params (identity — the same
                # arrays — for fp32); the self term stays exact.
                wire = (params if self.history_dtype == "float32"
                        else self._wire_roundtrip(params))

                def leaf(p, wp):
                    flat = p.reshape(n, -1)
                    gathered = wp.reshape(n, -1)[nbr]  # [N, S, D]
                    out = self_eff[:, None] * flat + \
                        jnp.einsum("ns,nsd->nd", w_eff, gathered)
                    return out.reshape(p.shape)
                return jax.tree.map(leaf, params, wire)

            n_sent = sent.sum()
            # Cause attribution matches the bulk engine: a dropped message
            # never reaches its receiver, so drop is charged first and
            # offline only on surviving edges (forced-offline receivers
            # get the scheduled-fault "chaos" cause).
            n_drop = (sent & drop).sum()
            n_offline = (sent & ~drop & ~online[:, None]).sum()
            if self.chaos is not None:
                n_chaos = (sent & ~drop & forced[:, None]).sum()
                n_offline = n_offline - n_chaos
            received_any = (live & (wt > 0)).any(axis=1)
            if probe_mix:
                acc_count = (live & (wt > 0)).sum(axis=1).astype(jnp.int32)

            def age_max(n_updates):
                return jnp.where(live, n_updates[nbr], 0).max(axis=1)
        elif self.sparse_mix:
            # O(E) formulation over the CSR edge list (heavy-tailed degree
            # distributions where padding to max_deg would blow up):
            # liveness, row renormalization and the merge itself are
            # gathers + segment-sums — no [N, N] tensor exists at any
            # point.
            mix = self.mixing
            n_edges = mix.rows.shape[0]
            drop_e = jax.random.bernoulli(
                self._round_key(base_key, r, _K_A2A_DROP),
                self._chaos_drop_prob(r), (n_edges,))
            sent_e = fires[mix.senders]
            if chaos_edges:
                # O(E) per-edge alive mask, gathered in CSR order (the
                # SparseMixing edge layout).
                sent_e = sent_e & sched.csr_masks[chaos_m]
            live_e = sent_e & ~drop_e & online[mix.rows]
            w_e = mix.edge_w * live_e
            # mix.rows is non-decreasing by CSR construction: the sorted
            # segment path beats the general scatter on accelerators.
            row_sum = mix.self_w + jax.ops.segment_sum(
                w_e, mix.rows, n, indices_are_sorted=True)
            inv = 1.0 / jnp.maximum(row_sum, 1e-12)
            w_e_eff = w_e * inv[mix.rows]
            self_eff = mix.self_w * inv
            if health_nf:
                mix_bad = ((~jnp.isfinite(w_e_eff)).sum()
                           + (~jnp.isfinite(self_eff)).sum()) \
                    .astype(jnp.int32)

            def mix_tree(params):
                wire = (params if self.history_dtype == "float32"
                        else self._wire_roundtrip(params))

                def leaf(p, wp):
                    flat = p.reshape(n, -1)
                    contrib = w_e_eff[:, None] * wp.reshape(n, -1)[mix.senders]
                    out = self_eff[:, None] * flat + \
                        jax.ops.segment_sum(contrib, mix.rows, n,
                                            indices_are_sorted=True)
                    return out.reshape(p.shape)
                return jax.tree.map(leaf, params, wire)

            n_sent = sent_e.sum()
            n_drop = (sent_e & drop_e).sum()
            n_offline = (sent_e & ~drop_e & ~online[mix.rows]).sum()
            if self.chaos is not None:
                n_chaos = (sent_e & ~drop_e & forced[mix.rows]).sum()
                n_offline = n_offline - n_chaos
            received_any = jax.ops.segment_max(
                (live_e & (mix.edge_w > 0)).astype(jnp.int32), mix.rows, n,
                indices_are_sorted=True) > 0
            if probe_mix:
                acc_count = jax.ops.segment_sum(
                    (live_e & (mix.edge_w > 0)).astype(jnp.int32), mix.rows,
                    n, indices_are_sorted=True)

            def age_max(n_updates):
                return jax.ops.segment_max(
                    jnp.where(live_e, n_updates[mix.senders], 0), mix.rows,
                    n, indices_are_sorted=True)
        else:
            # Per-edge liveness: sender fired, message not dropped, receiver
            # online.
            drop = jax.random.bernoulli(
                self._round_key(base_key, r, _K_A2A_DROP),
                self._chaos_drop_prob(r), (n, n))
            adj = self.topology.adjacency_dev
            if chaos_edges:
                adj = adj & sched.edge_masks[chaos_m]
            live = adj & fires[None, :] & ~drop & online[:, None]  # [recv, sender]

            w = self.mixing * live
            w = w + jnp.diag(jnp.diag(self.mixing))  # self weight always present
            row_sum = w.sum(axis=1, keepdims=True)
            w_eff = w / jnp.maximum(row_sum, 1e-12)
            if health_nf:
                mix_bad = (~jnp.isfinite(w_eff)).sum().astype(jnp.int32)

            sent_mask = adj & fires[None, :]
            n_sent = sent_mask.sum()
            n_drop = (sent_mask & drop).sum()
            n_offline = (sent_mask & ~drop & ~online[:, None]).sum()
            if self.chaos is not None:
                n_chaos = (sent_mask & ~drop & forced[:, None]).sum()
                n_offline = n_offline - n_chaos
            received_any = (live & (self.mixing > 0)).any(axis=1)
            if probe_mix:
                acc_count = (live & (self.mixing > 0)).sum(axis=1) \
                    .astype(jnp.int32)

            def age_max(n_updates):
                return jnp.where(live, n_updates[None, :], 0).max(axis=1)

            # The mixing merge: one matmul per parameter leaf — dense
            # einsum, or the explicit shard_map+ppermute ring schedule over
            # the mesh. Under a quantized wire format the matmul splits
            # into exact-self-diagonal + off-diagonal-over-wire-params (the
            # fp32 path keeps today's single fused matmul bit-for-bit).
            if self.history_dtype != "float32":
                w_diag = jnp.diag(w_eff)
                w_off = w_eff - jnp.diag(w_diag)
            if self.ring_mix:
                from ..parallel.collectives import ring_mix_pytree

                if self.history_dtype == "float32":
                    def mix_tree(params):
                        return ring_mix_pytree(w_eff, params, self.mesh,
                                               self._ring_axis)
                else:
                    def mix_tree(params):
                        wire = self._wire_roundtrip(params)
                        mixed = ring_mix_pytree(w_off, wire, self.mesh,
                                                self._ring_axis)
                        return jax.tree.map(
                            lambda p, m: (w_diag[:, None] * p.reshape(n, -1)
                                          + m.reshape(n, -1)).reshape(p.shape),
                            params, mixed)
            elif self.history_dtype == "float32":
                def mix_tree(params):
                    return jax.tree.map(
                        lambda p: (w_eff @ p.reshape(n, -1)).reshape(p.shape),
                        params)
            else:
                def mix_tree(params):
                    wire = self._wire_roundtrip(params)
                    return jax.tree.map(
                        lambda p, wp: (w_diag[:, None] * p.reshape(n, -1)
                                       + w_off @ wp.reshape(n, -1)
                                       ).reshape(p.shape),
                        params, wire)

        size = self._model_size(state.model.params)
        mode = self.handler.mode
        probe_delta = probe_mix and self.probes.mixing
        if mode == CreateModelMode.UPDATE_MERGE:
            with jax.named_scope(PHASE_TRAIN):
                keys = jax.random.split(
                    self._round_key(base_key, r, _K_A2A_UPDATE), n)
                updated = jax.vmap(self.handler.update)(
                    state.model, self._local_data(), keys)
                # Only nodes that fired (timed out) train this round
                # (node.py:833-843) — same gate as the MERGE_UPDATE branch.
                model = select_nodes(fires, updated, state.model)
                if probe_delta:
                    train_sq = sq_param_distance(model.params,
                                                 state.model.params)
            with jax.named_scope(PHASE_RECEIVE_MERGE):
                mixed = mix_tree(model.params)
        else:  # MERGE_UPDATE (the reference's supported path, handler.py:652-654)
            with jax.named_scope(PHASE_RECEIVE_MERGE):
                mixed = mix_tree(state.model.params)
            model = state.model
        with jax.named_scope(PHASE_RECEIVE_MERGE):
            ages = age_max(model.n_updates)
            new_age = jnp.maximum(model.n_updates, ages)
            params = select_nodes(received_any, mixed, model.params)
            if probe_delta:
                merge_sq = sq_param_distance(params, model.params)
            model = ModelState(params, model.opt_state,
                               jnp.where(received_any, new_age,
                                         model.n_updates))

        if mode != CreateModelMode.UPDATE_MERGE:
            with jax.named_scope(PHASE_TRAIN):
                keys = jax.random.split(
                    self._round_key(base_key, r, _K_A2A_UPDATE), n)
                pre_train = model.params
                updated = jax.vmap(self.handler.update)(
                    model, self._local_data(), keys)
                # Only nodes that fired (timed out) train this round
                # (node.py:833-843).
                model = select_nodes(fires, updated, model)
                if probe_delta:
                    train_sq = sq_param_distance(model.params, pre_train)

        state = state._replace(model=model)
        with jax.named_scope(PHASE_EVAL):
            local, glob = self._maybe_eval(state, base_key, r, last_round)
        state = state._replace(round=r + 1)
        fails = FailureCounts(drop=n_drop.astype(jnp.int32),
                              offline=n_offline.astype(jnp.int32),
                              overflow=jnp.int32(0),
                              chaos=(n_chaos.astype(jnp.int32)
                                     if self.chaos is not None else ()))
        stats = {
            "sent": n_sent,
            "failed": fails.total(),
            "failed_drop": fails.drop,
            "failed_offline": fails.offline,
            "failed_overflow": fails.overflow,
            # Broadcast mixing has no mailbox and one fused delivery path:
            # the per-round diagnostics are structurally zero, kept so the
            # report/JSONL columns line up across simulators.
            "mailbox_hwm": jnp.int32(0),
            "compact_slots": jnp.int32(0),
            "wide_slots": jnp.int32(0),
            "size": n_sent * size,
            "local": local,
            "global": glob,
        }
        if self.chaos is not None:
            stats["failed_chaos"] = fails.chaos
            if self._chaos_probes_on():
                stats.update(self._chaos_stats(state, r))
        if self.probes is not None:
            cfg = self.probes
            if cfg.consensus:
                cm, cx, cl = consensus_stats(state.model.params)
                stats["probe_consensus_mean"] = cm
                stats["probe_consensus_max"] = cx
                stats["probe_consensus_per_layer"] = cl
            if cfg.staleness:
                # Every mixed contribution is this round's round-start
                # snapshot: staleness is structurally zero and the whole
                # histogram lands in bucket 0 (still summing to the
                # accepted count bit-for-bit).
                hist = jnp.zeros((cfg.staleness_buckets,), jnp.int32) \
                    .at[0].set(acc_count.sum())
                stats["probe_stale_mean"] = jnp.float32(0)
                stats["probe_stale_max"] = jnp.int32(0)
                stats["probe_stale_hist"] = hist
            if cfg.mixing:
                stats["probe_accepted_per_node"] = acc_count
                stats["probe_merge_delta"] = jnp.sqrt(merge_sq)
                stats["probe_train_delta"] = jnp.sqrt(train_sq)
        if health_nf:
            stats["health_mix_nonfinite"] = mix_bad
        return state, stats

    def _probe_expected_fanin(self):
        """Broadcast mixing: every in-neighbor's send reaches a node each
        round (sync; async nodes fire ~once per round window), thinned by
        the per-edge drop draw and the receiver's online draw."""
        n = self.n_nodes
        if self.sparse_mix:
            rows = np.asarray(self.mixing.rows)
            w = np.asarray(self.mixing.edge_w)
            indeg = np.bincount(rows[w > 0], minlength=n).astype(np.float64)
        else:
            mix = np.asarray(self.mixing)
            adj = np.asarray(self.topology.adjacency).astype(bool)
            indeg = (adj & (mix > 0)).sum(axis=1).astype(np.float64)
        return indeg * (1.0 - self.drop_prob) * self.online_prob
