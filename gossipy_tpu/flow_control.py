"""Token-account flow control (Danner 2018), vectorized over the node axis.

Re-design of ``gossipy/flow_control.py``. The reference keeps one mutable
``TokenAccount`` object per node; here an account *type* is a static policy
whose ``proactive``/``reactive`` functions map a whole int32 balance vector
[N] to probabilities / reaction counts — so the tokenized simulator evaluates
flow control for every node in one fused op.

Balances themselves live in the simulator's stacked node state; ``add``/
``sub`` (reference flow_control.py:32-52, floored at 0) are plain array ops
applied by the engine.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TokenAccount:
    """Base policy. ``proactive(balance) -> float[N]`` gives each node's
    probability of sending at its timeout; ``reactive(balance, utility, key)
    -> int32[N]`` gives the number of immediate reaction sends triggered by a
    received message of the given utility (reference flow_control.py:54-82).
    """

    def init_balance(self, n_nodes: int) -> jax.Array:
        return jnp.zeros((n_nodes,), dtype=jnp.int32)

    def proactive(self, balance: jax.Array) -> jax.Array:
        raise NotImplementedError

    def reactive(self, balance: jax.Array, utility: jax.Array,
                 key: jax.Array) -> jax.Array:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class PurelyProactiveTokenAccount(TokenAccount):
    """Always send, never react — vanilla push gossip (flow_control.py:85-102)."""

    def proactive(self, balance):
        return jnp.ones_like(balance, dtype=jnp.float32)

    def reactive(self, balance, utility, key):
        return jnp.zeros_like(balance)


@dataclasses.dataclass(frozen=True)
class PurelyReactiveTokenAccount(TokenAccount):
    """Never proactive; react with ``utility * k`` sends (flow_control.py:105-127)."""

    k: int = 1

    def proactive(self, balance):
        return jnp.zeros_like(balance, dtype=jnp.float32)

    def reactive(self, balance, utility, key):
        return (utility * self.k).astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class SimpleTokenAccount(TokenAccount):
    """Proactive iff balance >= capacity; reactive iff balance > 0
    (flow_control.py:130-154)."""

    C: int = 1

    def __post_init__(self):
        assert self.C >= 1, "The capacity C must be strictly positive."

    def proactive(self, balance):
        return (balance >= self.C).astype(jnp.float32)

    def reactive(self, balance, utility, key):
        return (balance > 0).astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class GeneralizedTokenAccount(SimpleTokenAccount):
    """Danner 2018 generalized reactive rule (flow_control.py:157-189):

    reactive(a, u) = floor((A-1+a)/A) if u > 0 else floor((A-1+a)/(2A)).
    """

    A: int = 1

    def __post_init__(self):
        assert self.C >= 1, "The capacity C must be positive."
        assert self.A >= 1, "The reactivity A must be positive."
        assert self.A <= self.C, \
            "The capacity C must be greater or equal than the reactivity A."

    def reactive(self, balance, utility, key):
        num = self.A - 1 + balance
        useful = utility > 0
        return jnp.where(useful, num // self.A, num // (2 * self.A)).astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class RandomizedTokenAccount(GeneralizedTokenAccount):
    """Linear proactive ramp on [A-1, C]; randomized-rounding reactive
    (flow_control.py:192-236)."""

    def proactive(self, balance):
        b = balance.astype(jnp.float32)
        ramp = (b - self.A + 1) / float(self.C - self.A + 1)
        return jnp.clip(jnp.where(b < self.A - 1, 0.0, ramp), 0.0, 1.0)

    def reactive(self, balance, utility, key):
        r = balance.astype(jnp.float32) / self.A
        frac = r - jnp.floor(r)
        rand_round = jnp.floor(r).astype(jnp.int32) + \
            jax.random.bernoulli(key, jnp.clip(frac, 0.0, 1.0)).astype(jnp.int32)
        return jnp.where(utility > 0, rand_round, 0)
