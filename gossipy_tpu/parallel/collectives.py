"""Explicit ICI ring collectives for the gossip exchange.

The default engine lets XLA choose the collectives implied by shardings
(gathers along the sharded node axis become all-to-alls). This module is the
*explicit* communication backend: ``shard_map`` + ``lax.ppermute`` ring
schedules, the TPU-native analogue of what a hand-written NCCL/MPI backend
would be in a GPU framework (the reference has no backend at all — its
"network" is a Python loop, SURVEY.md §2.12).

Two primitives:

- :func:`ring_all_gather` — unidirectional ring gather: each device forwards
  its chunk one ring position per hop; after ``d-1`` hops every device holds
  the full array. One chunk in flight per device per hop.
- :func:`ring_mixed_matmul` — the all-to-all mixing merge ``W @ P`` as a ring
  matmul: each device keeps its row block of ``W`` and a rotating column
  chunk of ``P``; per hop it multiplies the resident chunk into its
  accumulator (MXU work) while the next chunk moves over ICI. The full
  stacked parameter matrix is never materialized on any device — peak
  per-device memory is ``N/d`` rows instead of ``N``.

:func:`ring_mix_pytree` applies the ring matmul leafwise over a stacked
params pytree; ``All2AllGossipSimulator(..., mesh=..., ring_mix=True)`` uses
it for the Koloskova mixing step (reference node.py:833-843 merges via a
Python loop per node; here the whole network's merge is ``d`` pipelined
MXU+ICI steps).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from . import _node_axis_entry
from .rules import node_leading_spec, replicated_spec

# ``shard_map`` became a top-level jax API (varying-axes switch named
# ``check_vma``) after living in ``jax.experimental.shard_map`` (same switch
# named ``check_rep``). Resolve once at import so every collective runs on
# either vintage; callers below always use the ``check_vma`` spelling.
try:
    _shard_map_impl = jax.shard_map
    _SM_CHECK_KW = "check_vma"
except AttributeError:  # pre-public-API jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _SM_CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **{_SM_CHECK_KW: check_vma})


def _pcast_varying(x, axis_name):
    """Mark ``x`` device-varying where the jax build has varying-axes types;
    a no-op on builds that predate them (nothing to mark there)."""
    pcast = getattr(jax.lax, "pcast", None)
    return x if pcast is None else pcast(x, axis_name, to="varying")


def _ring_perm(d: int):
    """Send each shard to the previous ring position (i -> i-1 mod d), so
    after ``s`` hops device ``m`` holds the chunk that started on device
    ``(m + s) % d``."""
    return [(i, (i - 1) % d) for i in range(d)]


# Hop loops are Python-unrolled up to this ring size (lets XLA pipeline
# compute against the next hop's ICI transfer); larger rings roll into a
# fori_loop so program size stays O(1) in pod size.
_UNROLL_MAX = 16


def _ring_hops(d: int, axis_name, hop, init):
    """Run ``d`` ring hops: ``carry = hop(s, carry, chunk)`` then rotate
    ``chunk`` one position (the final rotation is skipped). ``init`` is
    ``(carry0, chunk0)``; returns the final carry."""
    perm = _ring_perm(d)
    carry, chunk = init
    if d <= _UNROLL_MAX:
        for s in range(d):
            carry = hop(s, carry, chunk)
            if s != d - 1:
                chunk = jax.lax.ppermute(chunk, axis_name, perm)
        return carry

    def body(s, val):
        c, ch = val
        return hop(s, c, ch), jax.lax.ppermute(ch, axis_name, perm)

    # The loop carry must have a stable varying-axes type: the initial
    # accumulator (a plain zeros, device-invariant) becomes device-varying
    # after one hop, so mark it varying up front.
    carry = _pcast_varying(carry, axis_name)
    carry, chunk = jax.lax.fori_loop(0, d - 1, body, (carry, chunk))
    return hop(d - 1, carry, chunk)


def _axis_size(mesh: Mesh, axis_name) -> int:
    """Ring length: the mesh axis size, or the product over a tuple of axes
    (a 2-D ``(dcn, nodes)`` mesh rings over the combined flattened axes —
    collectives accept axis-name tuples, with ring positions in flattened
    order)."""
    names = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    d = 1
    for a in names:
        d *= int(mesh.shape[a])
    return d


def ring_all_gather(x: jax.Array, mesh: Mesh,
                    axis_name=None) -> jax.Array:
    """All-gather ``x`` (sharded on its leading axis) via a ppermute ring.

    Returns the full array, replicated. Equivalent to
    ``jax.lax.all_gather`` but with an explicit ring schedule (one
    neighbor-to-neighbor ICI transfer per hop). ``axis_name`` (a mesh axis
    or tuple of axes) defaults to the mesh-derived node placement — all
    axes combined on a multi-axis mesh, matching ``shard_state``.
    """
    axis_name = _node_axis_entry(mesh, axis_name)
    d = _axis_size(mesh, axis_name)
    n = x.shape[0]
    assert n % d == 0, f"leading axis {n} not divisible by mesh axis {d}"
    nl = n // d

    # Every device assembles the identical full array, but replication via a
    # ppermute ring is not statically inferable — skip the varying-axes check.
    # I/O specs derive from the rule registry's primitives: the input is
    # node-leading, the gathered output replicated (parallel/rules.py).
    @partial(shard_map, mesh=mesh,
             in_specs=node_leading_spec(x.ndim, axis_name),
             out_specs=replicated_spec(x.ndim), check_vma=False)
    def body(chunk):
        me = jax.lax.axis_index(axis_name)

        def hop(s, out, ch):
            src = (me + s) % d
            return jax.lax.dynamic_update_slice_in_dim(out, ch, src * nl, 0)

        out0 = jnp.zeros((n,) + chunk.shape[1:], chunk.dtype)
        return _ring_hops(d, axis_name, hop, (out0, chunk))

    return body(x)


def ring_mixed_matmul(w: jax.Array, x: jax.Array, mesh: Mesh,
                      axis_name=None) -> jax.Array:
    """``w @ x`` with ``x`` sharded on its leading (node) axis, as a ring
    matmul: per hop each device contracts its resident ``[n_local]`` chunk of
    senders against the matching column block of its ``W`` rows, then rotates
    the chunk one ring position. Compute (MXU) and communication (ICI)
    pipeline across hops; no device ever holds more than ``N/d`` sender rows.

    ``w`` is ``[N, N]`` (receiver rows x sender columns); ``x`` is
    ``[N, F]``. Result is ``[N, F]`` sharded like ``x``. ``axis_name``
    defaults to the mesh-derived node placement (see
    :func:`ring_all_gather`).
    """
    axis_name = _node_axis_entry(mesh, axis_name)
    d = _axis_size(mesh, axis_name)
    n, f = x.shape
    assert w.shape == (n, n), f"mixing matrix {w.shape} vs {n} nodes"
    assert n % d == 0, f"node axis {n} not divisible by mesh axis {d}"
    nl = n // d

    @partial(shard_map, mesh=mesh,
             in_specs=(node_leading_spec(2, axis_name),
                       node_leading_spec(2, axis_name)),
             out_specs=node_leading_spec(2, axis_name))
    def body(w_rows, chunk):
        me = jax.lax.axis_index(axis_name)

        def hop(s, acc, ch):
            src = (me + s) % d
            w_blk = jax.lax.dynamic_slice(w_rows, (0, src * nl), (nl, nl))
            return acc + w_blk @ ch

        acc0 = jnp.zeros((nl, f), jnp.promote_types(w_rows.dtype, chunk.dtype))
        return _ring_hops(d, axis_name, hop, (acc0, chunk)).astype(x.dtype)

    return body(w, x)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis_name=None, causal: bool = False,
                   flash: bool | None = None) -> jax.Array:
    """Sequence-parallel attention over a ppermute ring (blockwise softmax).

    ``q``/``k``/``v`` are ``[S, D]`` with the SEQUENCE axis sharded over the
    mesh; each device keeps its query block resident while key/value blocks
    rotate around the ring, maintaining the streaming-softmax statistics
    ``(running max, normalizer, weighted-value accumulator)`` per hop — so
    no device ever materializes the ``[S, S]`` score matrix or the full
    key/value sequence (peak per-device memory is ``S/d`` rows). Compute
    pipelines against the next hop's ICI transfer exactly like
    :func:`ring_mixed_matmul`.

    The reference has no sequence models (SURVEY §2.12/§5 — nothing to
    port); this primitive exists to show the explicit comm backend
    generalizes beyond the gossip exchange to long-context sequence
    parallelism (the public "ring attention" schedule). ``causal=True``
    masks by GLOBAL position (device-block offsets included). Heads/batch:
    ``jax.vmap`` this over leading axes.

    ``flash`` selects the hop implementation: the fused pallas kernel
    (:mod:`gossipy_tpu.ops.attention` — the per-hop score block stays in
    VMEM instead of round-tripping HBM between the two matmuls) or the
    inline jnp body. Default: kernel on TPU, jnp elsewhere. Both are
    differentiable (the kernel carries a recompute-based custom vjp) and
    tested equal.
    """
    axis_name = _node_axis_entry(mesh, axis_name)
    d = _axis_size(mesh, axis_name)
    if flash is None:
        flash = jax.default_backend() == "tpu"
    s_len, dim = q.shape
    assert k.shape == (s_len, dim), \
        f"k {k.shape} must match q {(s_len, dim)}"
    assert v.shape[0] == s_len, \
        f"v has {v.shape[0]} rows, expected {s_len}"
    assert s_len % d == 0, f"sequence {s_len} not divisible by mesh axis {d}"
    sl = s_len // d
    dv = v.shape[1]
    scale = 1.0 / np.sqrt(dim)
    NEG = jnp.asarray(-1e30, jnp.float32)  # finite: exp() stays nan-free

    @partial(shard_map, mesh=mesh,
             in_specs=(node_leading_spec(2, axis_name),) * 3,
             # The pallas hop kernel's interpreter mode does not thread
             # varying-axes types onto in-kernel constants, so the vma
             # check only runs on the jnp path.
             out_specs=node_leading_spec(2, axis_name), check_vma=not flash)
    def body(q_l, k_l, v_l):
        me = jax.lax.axis_index(axis_name)

        def hop(s_idx, carry, kv):
            m, l, acc = carry
            src = (me + s_idx) % d
            k_c = kv[:, :dim]
            v_c = kv[:, dim:]
            if flash:
                from ..ops.attention import flash_hop_update
                return flash_hop_update(q_l, k_c, v_c, m, l, acc,
                                        me * sl, src * sl, scale,
                                        causal=causal)
            from ..ops.attention import hop_update_reference
            return hop_update_reference(q_l, k_c, v_c, m, l, acc,
                                        me * sl, src * sl, scale, causal)

        kv0 = jnp.concatenate([k_l, v_l], axis=1)
        m0 = jnp.full((sl,), NEG, jnp.float32)
        l0 = jnp.zeros((sl,), jnp.float32)
        acc0 = jnp.zeros((sl, dv), jnp.float32)
        m, l, acc = _ring_hops(d, axis_name, hop, ((m0, l0, acc0), kv0))
        return (acc / jnp.maximum(l, 1e-30)[:, None]).astype(q.dtype)

    return body(q, k, v)


def sharded_gather_merge_multi(params, history, flat_idx: jax.Array,
                               w_self: jax.Array, w_peer: jax.Array,
                               mesh: Mesh, scales=None, axis_name=None):
    """The engine's multi-slot fused merge, sharded over the mesh's node
    axis: each device merges its OWN receiver rows while the history-ring
    chunks rotate around a ppermute ring — the merge math runs on each
    replica's shard instead of replicated (the cross-replica sharded
    weight-update pattern, PAPERS.md).

    ``params`` leaves are ``[N, ...]`` and ``history`` leaves ``[D, N,
    ...]`` (the engine's ring, fp32 or a wire format with optional
    ``scales``); ``flat_idx``/``w_self``/``w_peer`` are the ``[N, K]``
    slot tables of :func:`~gossipy_tpu.ops.merge.gather_merge_multi`.
    Per hop, ONE multi-slot kernel launch folds in every slot whose
    sender is resident in the rotating chunk.

    A rotating accumulation cannot honor slot order, so the left-to-right
    fold is first rewritten in its composed linear form::

        out = (prod_k ws_k) * p + sum_k [wp_k * prod_{j>k} ws_j] * peer_k

    which is hop-order independent — equal to the unsharded fold up to fp
    reassociation (the unsharded kernel stays the bit-compatibility
    reference). Leaves ride one ring concatenated, like
    :func:`ring_mix_pytree`. I/O specs derive from the rule registry's
    primitives (parallel/rules.py).
    """
    from ..ops.merge import gather_merge_multi

    axis_name = _node_axis_entry(mesh, axis_name)
    d = _axis_size(mesh, axis_name)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    hleaves = jax.tree_util.tree_leaves(history)
    sleaves = (jax.tree_util.tree_leaves(scales) if scales is not None
               else [None] * len(leaves))
    n = leaves[0].shape[0]
    assert n % d == 0, f"node axis {n} not divisible by mesh axis {d}"
    nl = n // d
    D = hleaves[0].shape[0]

    cat_dtype = jnp.result_type(*leaves)
    flats, hflats, widths = [], [], []
    for pl_, hl, sl in zip(leaves, hleaves, sleaves):
        f = int(np.prod(pl_.shape[1:])) if pl_.ndim > 1 else 1
        flats.append(pl_.reshape(n, f).astype(cat_dtype))
        h = hl.reshape(D, n, f).astype(cat_dtype)
        if sl is not None:
            # int8 wire rows dequantize where they LIVE (each device's own
            # ring shard), before the fp chunk enters the ring.
            h = h * sl.reshape(D, n, 1).astype(cat_dtype)
        hflats.append(h)
        widths.append(f)
    p_cat = jnp.concatenate(flats, axis=1)
    h_cat = jnp.concatenate(hflats, axis=2)
    fsum = p_cat.shape[1]

    # Composed linear weights (hop-order independent): W0 = prod_k ws_k,
    # Wk = wp_k * prod_{j>k} ws_j.
    ws = w_self.astype(cat_dtype)
    wp = w_peer.astype(cat_dtype)
    rev = jnp.cumprod(ws[:, ::-1], axis=1)[:, ::-1]  # prod_{j>=k} ws_j
    w0 = rev[:, 0]
    suffix = jnp.concatenate(
        [rev[:, 1:], jnp.ones((n, 1), cat_dtype)], axis=1)
    wk = wp * suffix

    @partial(shard_map, mesh=mesh,
             in_specs=(node_leading_spec(2, axis_name),
                       node_leading_spec(3, axis_name, 1),
                       node_leading_spec(2, axis_name),
                       node_leading_spec(1, axis_name),
                       node_leading_spec(2, axis_name)),
             out_specs=node_leading_spec(2, axis_name), check_vma=False)
    def body(p_l, h_l, idx_l, w0_l, wk_l):
        me = jax.lax.axis_index(axis_name)
        h_flat = h_l.reshape(D * nl, fsum)
        bb = idx_l // n  # ring cell of each (receiver, slot)
        ss = idx_l % n   # global sender of each (receiver, slot)

        def hop(s, acc, ch):
            src = (me + s) % d
            lo = src * nl
            res = (ss >= lo) & (ss < lo + nl)
            lidx = jnp.clip(bb * nl + (ss - lo), 0, D * nl - 1)
            wp_hop = jnp.where(res, wk_l, 0)
            return gather_merge_multi(acc, ch, lidx.astype(jnp.int32),
                                      jnp.ones_like(wp_hop), wp_hop)

        acc0 = w0_l[:, None] * p_l
        return _ring_hops(d, axis_name, hop, (acc0, h_flat))

    mixed = body(p_cat, h_cat, flat_idx, w0, wk)
    splits = jnp.split(mixed, np.cumsum(widths)[:-1], axis=1)
    out = [s.reshape(l.shape).astype(l.dtype)
           for s, l in zip(splits, leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def ring_mix_pytree(w: jax.Array, params, mesh: Mesh,
                    axis_name=None):
    """:func:`ring_mixed_matmul` over a stacked ``[N, ...]`` params pytree
    (the all-to-all mixing merge ``P' = W_eff @ P``).

    All leaves are flattened and concatenated into one ``[N, sum(F)]``
    matrix so the whole pytree rides a single d-hop ring (per-leaf rings
    would pay the hop latency once per leaf, with near-empty transfers for
    small bias leaves), then split back and cast to each leaf's dtype.
    """
    leaves, treedef = jax.tree_util.tree_flatten(params)
    n = leaves[0].shape[0]
    flats = [l.reshape(n, int(np.prod(l.shape[1:])) if l.ndim > 1 else 1)
             for l in leaves]
    widths = [f.shape[1] for f in flats]
    cat = jnp.concatenate([f.astype(jnp.result_type(*flats)) for f in flats],
                          axis=1)
    mixed = ring_mixed_matmul(w, cat, mesh, axis_name)
    splits = jnp.split(mixed, np.cumsum(widths)[:-1], axis=1)
    out = [s.reshape(l.shape).astype(l.dtype) for s, l in zip(splits, leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)
