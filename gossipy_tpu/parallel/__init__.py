"""Mesh construction and node-axis sharding.

The reference has no distributed execution at all — its "network" is a Python
loop (SURVEY.md §2.12). Here the *node* axis is a real device-mesh axis:
every leading-``N`` array in :class:`~gossipy_tpu.simulation.SimState` and in
the stacked data is sharded ``P("nodes")`` over ICI, so per-node local
training runs data-parallel while peer-model gathers compile to XLA
collectives (all-to-all / all-gather) over the mesh. Multi-host scales the
same way: a 2-D ``(dcn, nodes)`` mesh makes XLA route the node axis over ICI
within hosts and DCN across (jax.sharding semantics; cf. the public scaling
book recipe: pick a mesh, annotate shardings, let XLA insert collectives).

Model axes are left unsharded by default (gossip models are small); for a
large model the ``PartitionSpec`` returned by :func:`state_shardings` can be
extended with a ``model`` mesh axis on the parameter leaves (tensor
parallelism) without touching the engine.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..simulation.engine import Mailbox, SimState

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis_name: str = NODE_AXIS) -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        assert n_devices <= len(devs), \
            f"requested {n_devices} devices, have {len(devs)}"
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def _spec_for_rank(lead_axis_pos: int, ndim: int, axis_name: str) -> P:
    """PartitionSpec placing ``axis_name`` at position ``lead_axis_pos``."""
    dims = [None] * ndim
    dims[lead_axis_pos] = axis_name
    return P(*dims)


def state_shardings(state: SimState, mesh: Mesh,
                    axis_name: str = NODE_AXIS) -> SimState:
    """A SimState-shaped pytree of NamedShardings.

    - model / phase leaves: node axis leading -> ``P("nodes", ...)``
    - history / mailbox leaves: ``[D, N, ...]`` -> ``P(None, "nodes", ...)``
    - scalars (round counter): replicated
    """
    def shard(leaf, pos):
        if not hasattr(leaf, "ndim") or leaf.ndim <= pos:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, _spec_for_rank(pos, leaf.ndim, axis_name))

    model_sh = jax.tree.map(lambda l: shard(l, 0), state.model)
    phase_sh = shard(state.phase, 0)
    hist_p_sh = jax.tree.map(lambda l: shard(l, 1), state.history_params)
    hist_a_sh = shard(state.history_ages, 1)
    mb_sh = jax.tree.map(lambda l: shard(l, 1), state.mailbox)
    rb_sh = jax.tree.map(lambda l: shard(l, 1), state.reply_box)
    aux_sh = jax.tree.map(lambda l: shard(l, 0), state.aux)
    return SimState(model=model_sh, phase=phase_sh,
                    history_params=hist_p_sh, history_ages=hist_a_sh,
                    mailbox=mb_sh, reply_box=rb_sh,
                    round=NamedSharding(mesh, P()),
                    aux=aux_sh)


def shard_state(state: SimState, mesh: Mesh,
                axis_name: str = NODE_AXIS) -> SimState:
    """Place a SimState onto the mesh, node axis sharded."""
    return jax.device_put(state, state_shardings(state, mesh, axis_name))


def shard_data(data: dict, mesh: Mesh, axis_name: str = NODE_AXIS) -> dict:
    """Shard stacked data: per-node arrays over the node axis, the global
    eval set replicated."""
    out = {}
    for k, v in data.items():
        arr = jax.numpy.asarray(v)
        if k in ("x_eval", "y_eval"):
            out[k] = jax.device_put(arr, NamedSharding(mesh, P()))
        else:
            out[k] = jax.device_put(
                arr, NamedSharding(mesh, _spec_for_rank(0, arr.ndim, axis_name)))
    return out
