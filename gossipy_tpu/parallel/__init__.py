"""Mesh construction and node-axis sharding.

The reference has no distributed execution at all — its "network" is a Python
loop (SURVEY.md §2.12). Here the *node* axis is a real device-mesh axis:
every leading-``N`` array in :class:`~gossipy_tpu.simulation.SimState` and in
the stacked data is sharded ``P("nodes")`` over ICI, so per-node local
training runs data-parallel while peer-model gathers compile to XLA
collectives (all-to-all / all-gather) over the mesh. Multi-host scales the
same way: a 2-D ``(dcn, nodes)`` mesh makes XLA route the node axis over ICI
within hosts and DCN across (jax.sharding semantics; cf. the public scaling
book recipe: pick a mesh, annotate shardings, let XLA insert collectives).

Model axes are left unsharded by default (gossip models are small); for a
large model, tensor parallelism is one mesh away: build a
``(nodes, model)`` mesh with :func:`make_mesh_tp` and :func:`state_shardings`
shards each parameter leaf's largest eligible non-node dimension over the
``model`` axis — per-node matmuls then partition over the MXU across chips,
with XLA inserting the contraction psums. The engine is untouched: shardings
propagate from the input placement.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from ..simulation.engine import SimState
from . import rules
from .rules import (  # re-exported: the registry is the placement API
    DATA_RULES,
    DCN_AXIS,
    MODEL_AXIS,
    NODE_AXIS,
    RuleSpec,
    STATE_RULES,
    UnmatchedLeafError,
    make_shard_and_gather_fns,
    match_partition_rules,
    partition_specs,
)

__all__ = [
    "NODE_AXIS", "DCN_AXIS", "MODEL_AXIS",
    "STATE_RULES", "DATA_RULES", "RuleSpec", "UnmatchedLeafError",
    "match_partition_rules", "partition_specs", "make_shard_and_gather_fns",
    "init_distributed", "make_mesh", "make_mesh_2d", "make_mesh_tp",
    "state_shardings", "shard_state", "shard_data",
]


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     **kwargs) -> None:
    """Join (or form) a multi-host JAX cluster before any jax computation.

    The multi-host analogue of the reference's absent comm backend
    (SURVEY §2.12 — its "network" is a Python loop): after this call
    ``jax.devices()`` is GLOBAL across all processes, :func:`make_mesh` /
    :func:`make_mesh_2d` build cluster-wide meshes, and
    :func:`shard_state` / :func:`shard_data` place the node axis across
    hosts — every process runs the SAME program and XLA routes the
    collectives (ICI within a host, DCN/Gloo across).

    On Cloud TPU pods all three arguments auto-detect (call with no args);
    elsewhere pass the coordinator's ``host:port``, the process count, and
    this process's rank. Thin wrapper over ``jax.distributed.initialize``
    so user code never imports jax internals; extra kwargs pass through
    (e.g. ``local_device_ids``).
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)


def make_mesh(n_devices: Optional[int] = None, axis_name: str = NODE_AXIS) -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):  # explicit: must survive python -O
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def make_mesh_2d(n_hosts: int, devices_per_host: Optional[int] = None,
                 axis_names: tuple[str, str] = (DCN_AXIS, NODE_AXIS)) -> Mesh:
    """A 2-D ``(dcn, nodes)`` mesh for multi-host layouts.

    The outer axis spans hosts (slow DCN links), the inner axis the chips
    within a host (fast ICI) — the standard pjit multi-pod recipe: shard the
    node axis over BOTH axes (``P(("dcn", "nodes"))``) so neighbor gathers
    stay mostly intra-host while the population still spans all hosts.
    """
    devs = jax.devices()
    per = devices_per_host or len(devs) // n_hosts
    if n_hosts * per > len(devs):
        raise ValueError(
            f"requested {n_hosts}x{per} devices, have {len(devs)}")
    try:
        # On real multi-host hardware, plain jax.devices() order is NOT
        # guaranteed host-contiguous; the hybrid mesh helper places the DCN
        # axis on actual host boundaries.
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_hybrid_device_mesh(
            (per,), (n_hosts,), devices=devs[: n_hosts * per])
        arr = np.asarray(arr).reshape(n_hosts, per)
    except Exception:
        if jax.process_count() > 1:
            # The hybrid helper keys on slice metadata that CPU/virtual
            # clusters do not carry ("Number of slices 1 ..."). There the
            # process boundary IS the host boundary: order devices
            # host-contiguously by process_index and verify no inner-axis
            # row straddles a process — the exact property the helper
            # exists to guarantee. A layout that cannot satisfy it still
            # raises rather than silently cutting the dcn axis across ICI.
            by_host: dict[int, list] = {}
            for d in devs:
                by_host.setdefault(d.process_index, []).append(d)
            hosts = [sorted(v, key=lambda d: d.id)
                     for _, v in sorted(by_host.items())]
            flat = [d for h in hosts for d in h]
            arr = np.array(flat[: n_hosts * per]).reshape(n_hosts, per)
            if any(len({d.process_index for d in row}) != 1 for row in arr):
                raise ValueError(
                    f"make_mesh_2d({n_hosts}, {per}): an inner-axis row "
                    "would straddle a process boundary (processes have "
                    f"{[len(h) for h in hosts]} devices); choose "
                    "devices_per_host dividing the per-process count")
        else:
            # Single-process backends (CPU test mesh, one-host TPU) have
            # no host boundaries to respect — a plain reshape is exact.
            arr = np.array(devs[: n_hosts * per]).reshape(n_hosts, per)
    return Mesh(arr, axis_names)


def _tp_device_grid(devices, n_node_devices: int,
                    n_model_devices: int) -> np.ndarray:
    """Host-contiguous ``(nodes, model)`` device grid.

    Plain ``jax.devices()`` order is not guaranteed host-contiguous across
    processes; a naive reshape could pair a model-axis group across DCN,
    putting every tensor-parallel contraction psum on the slow links. This
    groups devices by ``process_index`` so each model-axis row lies within
    one host (psums ride ICI) and the nodes axis spans hosts (DCN only
    carries node-axis traffic, which the engine already keeps coarse).
    Pure placement logic, unit-testable with fake device objects.
    """
    by_host: dict[int, list] = {}
    for d in devices:
        by_host.setdefault(d.process_index, []).append(d)
    hosts = [sorted(v, key=lambda d: d.id) for _, v in sorted(by_host.items())]
    sizes = {len(h) for h in hosts}
    if len(sizes) != 1:
        raise ValueError(
            f"uneven device count per host: {sorted(len(h) for h in hosts)}")
    per_host = sizes.pop()
    if per_host % n_model_devices != 0:
        raise ValueError(
            f"model axis ({n_model_devices}) must divide the per-host device "
            f"count ({per_host}) so tensor-parallel groups stay on ICI")
    rows = [h[i:i + n_model_devices]
            for h in hosts for i in range(0, per_host, n_model_devices)]
    if len(rows) != n_node_devices:
        raise ValueError(
            f"device layout yields {len(rows)} node rows, "
            f"requested {n_node_devices}")
    return np.array(rows)


def make_mesh_tp(n_node_devices: int, n_model_devices: int,
                 axis_names: tuple[str, str] = (NODE_AXIS, MODEL_AXIS)) -> Mesh:
    """A 2-D ``(nodes, model)`` mesh: data parallelism over the node
    population x tensor parallelism over model axes.

    With this mesh, :func:`state_shardings` places the node dimension on the
    ``nodes`` axis only and additionally shards each parameter leaf's largest
    eligible non-node dimension over the ``model`` axis. Multi-host layouts
    are placed host-contiguously (see :func:`_tp_device_grid`): the model
    axis stays innermost on ICI, hosts span the nodes axis.
    """
    devs = jax.devices()
    need = n_node_devices * n_model_devices
    if need > len(devs):  # explicit: must survive python -O
        raise ValueError(f"requested {need} devices, have {len(devs)}")
    if jax.process_count() > 1 and need != len(devs):
        # A device subset cannot be chosen consistently across processes
        # without leaving some process idle; require the full complement.
        raise ValueError(
            "multi-host TP mesh must use every attached device: "
            f"requested {need} of {len(devs)}")
    return Mesh(_tp_device_grid(devs[:need], n_node_devices, n_model_devices),
                axis_names)


# Mesh-axis resolution lives in the rule registry; the underscored names
# remain as aliases for existing callers (collectives' shard_map specs).
_node_axis_entry = rules.node_axis_entry
_model_axis_entry = rules.model_axis_entry


def state_shardings(state: SimState, mesh: Mesh,
                    axis_name=None, model_axis=None,
                    batch_dims: int = 0) -> SimState:
    """A SimState-shaped pytree of NamedShardings, DERIVED from the
    partition-rule registry (:data:`~gossipy_tpu.parallel.rules.
    STATE_RULES`) — this function owns no placement decisions of its own:

    - model / phase / aux leaves: node axis leading -> ``P("nodes", ...)``
    - history / mailbox leaves (incl. the int8 scale sidecars):
      ``[D, N, ...]`` -> ``P(None, "nodes", ...)``
    - scalars (round counter): replicated
    - on a TP mesh (an axis named ``"model"``, or ``model_axis=...``):
      parameter, optimizer-state, and history-snapshot leaves additionally
      shard their largest eligible non-node dimension over the model axis

    ``batch_dims`` shifts every node position right by that many leading
    lane axes — the seed/tenant-vmapped megabatch placement (the service
    scheduler passes 1). An unmatched state leaf raises
    :class:`~gossipy_tpu.parallel.rules.UnmatchedLeafError`.
    """
    return rules.named_shardings(state, mesh, rules=STATE_RULES,
                                 axis_name=axis_name, model_axis=model_axis,
                                 batch_dims=batch_dims)


def shard_state(state: SimState, mesh: Mesh,
                axis_name=None, model_axis=None,
                batch_dims: int = 0) -> SimState:
    """Place a SimState onto the mesh per the rule registry (node axis
    sharded, plus model axes on a TP mesh)."""
    return jax.device_put(state, state_shardings(state, mesh, axis_name,
                                                 model_axis, batch_dims))


def shard_data(data: dict, mesh: Mesh, axis_name=None,
               batch_dims: int = 0) -> dict:
    """Shard stacked data per the registry's :data:`DATA_RULES`: per-node
    arrays over the node axis, the global eval set replicated."""
    arrs = {k: jax.numpy.asarray(v) for k, v in data.items()}
    shardings = rules.named_shardings(arrs, mesh, rules=DATA_RULES,
                                      axis_name=axis_name,
                                      batch_dims=batch_dims)
    return {k: jax.device_put(arrs[k], shardings[k]) for k in arrs}
