"""Mesh construction and node-axis sharding.

The reference has no distributed execution at all — its "network" is a Python
loop (SURVEY.md §2.12). Here the *node* axis is a real device-mesh axis:
every leading-``N`` array in :class:`~gossipy_tpu.simulation.SimState` and in
the stacked data is sharded ``P("nodes")`` over ICI, so per-node local
training runs data-parallel while peer-model gathers compile to XLA
collectives (all-to-all / all-gather) over the mesh. Multi-host scales the
same way: a 2-D ``(dcn, nodes)`` mesh makes XLA route the node axis over ICI
within hosts and DCN across (jax.sharding semantics; cf. the public scaling
book recipe: pick a mesh, annotate shardings, let XLA insert collectives).

Model axes are left unsharded by default (gossip models are small); for a
large model the ``PartitionSpec`` returned by :func:`state_shardings` can be
extended with a ``model`` mesh axis on the parameter leaves (tensor
parallelism) without touching the engine.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..simulation.engine import Mailbox, SimState

NODE_AXIS = "nodes"
DCN_AXIS = "dcn"


def make_mesh(n_devices: Optional[int] = None, axis_name: str = NODE_AXIS) -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        assert n_devices <= len(devs), \
            f"requested {n_devices} devices, have {len(devs)}"
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def make_mesh_2d(n_hosts: int, devices_per_host: Optional[int] = None,
                 axis_names: tuple[str, str] = (DCN_AXIS, NODE_AXIS)) -> Mesh:
    """A 2-D ``(dcn, nodes)`` mesh for multi-host layouts.

    The outer axis spans hosts (slow DCN links), the inner axis the chips
    within a host (fast ICI) — the standard pjit multi-pod recipe: shard the
    node axis over BOTH axes (``P(("dcn", "nodes"))``) so neighbor gathers
    stay mostly intra-host while the population still spans all hosts.
    """
    devs = jax.devices()
    per = devices_per_host or len(devs) // n_hosts
    assert n_hosts * per <= len(devs), \
        f"requested {n_hosts}x{per} devices, have {len(devs)}"
    try:
        # On real multi-host hardware, plain jax.devices() order is NOT
        # guaranteed host-contiguous; the hybrid mesh helper places the DCN
        # axis on actual host boundaries.
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_hybrid_device_mesh(
            (per,), (n_hosts,), devices=devs[: n_hosts * per])
        arr = np.asarray(arr).reshape(n_hosts, per)
    except Exception:
        # Single-process backends (CPU test mesh, one-host TPU) have no host
        # boundaries to respect — a plain reshape is exact. On a real
        # multi-process run a failed hybrid mesh must NOT silently degrade
        # to device order (the dcn axis would cut across ICI).
        if jax.process_count() > 1:
            raise
        arr = np.array(devs[: n_hosts * per]).reshape(n_hosts, per)
    return Mesh(arr, axis_names)


def _spec_for_rank(lead_axis_pos: int, ndim: int, axis_name) -> P:
    """PartitionSpec placing ``axis_name`` (a mesh axis name or a tuple of
    them, for 2-D meshes) at position ``lead_axis_pos``."""
    dims = [None] * ndim
    dims[lead_axis_pos] = axis_name
    return P(*dims)


def _node_axis_entry(mesh: Mesh, axis_name):
    """The PartitionSpec entry for the node dimension.

    ``axis_name=None`` (the default) derives it from the mesh: the single
    axis of a 1-D mesh, or ALL axes combined on a multi-axis mesh (the node
    population spans hosts x chips). An explicitly passed ``axis_name`` is
    honored verbatim — a caller with a custom multi-axis mesh can pin the
    node dimension to one axis.
    """
    if axis_name is not None:
        return axis_name
    if len(mesh.axis_names) > 1:
        return tuple(mesh.axis_names)
    return mesh.axis_names[0]


def state_shardings(state: SimState, mesh: Mesh,
                    axis_name=None) -> SimState:
    """A SimState-shaped pytree of NamedShardings.

    - model / phase leaves: node axis leading -> ``P("nodes", ...)``
    - history / mailbox leaves: ``[D, N, ...]`` -> ``P(None, "nodes", ...)``
    - scalars (round counter): replicated
    """
    entry = _node_axis_entry(mesh, axis_name)

    def shard(leaf, pos):
        if not hasattr(leaf, "ndim") or leaf.ndim <= pos:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, _spec_for_rank(pos, leaf.ndim, entry))

    model_sh = jax.tree.map(lambda l: shard(l, 0), state.model)
    phase_sh = shard(state.phase, 0)
    hist_p_sh = jax.tree.map(lambda l: shard(l, 1), state.history_params)
    hist_a_sh = shard(state.history_ages, 1)
    mb_sh = jax.tree.map(lambda l: shard(l, 1), state.mailbox)
    rb_sh = jax.tree.map(lambda l: shard(l, 1), state.reply_box)
    aux_sh = jax.tree.map(lambda l: shard(l, 0), state.aux)
    return SimState(model=model_sh, phase=phase_sh,
                    history_params=hist_p_sh, history_ages=hist_a_sh,
                    mailbox=mb_sh, reply_box=rb_sh,
                    round=NamedSharding(mesh, P()),
                    aux=aux_sh)


def shard_state(state: SimState, mesh: Mesh,
                axis_name=None) -> SimState:
    """Place a SimState onto the mesh, node axis sharded."""
    return jax.device_put(state, state_shardings(state, mesh, axis_name))


def shard_data(data: dict, mesh: Mesh, axis_name=None) -> dict:
    """Shard stacked data: per-node arrays over the node axis, the global
    eval set replicated."""
    entry = _node_axis_entry(mesh, axis_name)
    out = {}
    for k, v in data.items():
        arr = jax.numpy.asarray(v)
        if k in ("x_eval", "y_eval"):
            out[k] = jax.device_put(arr, NamedSharding(mesh, P()))
        else:
            out[k] = jax.device_put(
                arr, NamedSharding(mesh, _spec_for_rank(0, arr.ndim, entry)))
    return out
