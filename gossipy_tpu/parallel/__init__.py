"""Mesh construction and node-axis sharding.

The reference has no distributed execution at all — its "network" is a Python
loop (SURVEY.md §2.12). Here the *node* axis is a real device-mesh axis:
every leading-``N`` array in :class:`~gossipy_tpu.simulation.SimState` and in
the stacked data is sharded ``P("nodes")`` over ICI, so per-node local
training runs data-parallel while peer-model gathers compile to XLA
collectives (all-to-all / all-gather) over the mesh. Multi-host scales the
same way: a 2-D ``(dcn, nodes)`` mesh makes XLA route the node axis over ICI
within hosts and DCN across (jax.sharding semantics; cf. the public scaling
book recipe: pick a mesh, annotate shardings, let XLA insert collectives).

Model axes are left unsharded by default (gossip models are small); for a
large model, tensor parallelism is one mesh away: build a
``(nodes, model)`` mesh with :func:`make_mesh_tp` and :func:`state_shardings`
shards each parameter leaf's largest eligible non-node dimension over the
``model`` axis — per-node matmuls then partition over the MXU across chips,
with XLA inserting the contraction psums. The engine is untouched: shardings
propagate from the input placement.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..simulation.engine import SimState

NODE_AXIS = "nodes"
DCN_AXIS = "dcn"
MODEL_AXIS = "model"


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     **kwargs) -> None:
    """Join (or form) a multi-host JAX cluster before any jax computation.

    The multi-host analogue of the reference's absent comm backend
    (SURVEY §2.12 — its "network" is a Python loop): after this call
    ``jax.devices()`` is GLOBAL across all processes, :func:`make_mesh` /
    :func:`make_mesh_2d` build cluster-wide meshes, and
    :func:`shard_state` / :func:`shard_data` place the node axis across
    hosts — every process runs the SAME program and XLA routes the
    collectives (ICI within a host, DCN/Gloo across).

    On Cloud TPU pods all three arguments auto-detect (call with no args);
    elsewhere pass the coordinator's ``host:port``, the process count, and
    this process's rank. Thin wrapper over ``jax.distributed.initialize``
    so user code never imports jax internals; extra kwargs pass through
    (e.g. ``local_device_ids``).
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)


def make_mesh(n_devices: Optional[int] = None, axis_name: str = NODE_AXIS) -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):  # explicit: must survive python -O
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def make_mesh_2d(n_hosts: int, devices_per_host: Optional[int] = None,
                 axis_names: tuple[str, str] = (DCN_AXIS, NODE_AXIS)) -> Mesh:
    """A 2-D ``(dcn, nodes)`` mesh for multi-host layouts.

    The outer axis spans hosts (slow DCN links), the inner axis the chips
    within a host (fast ICI) — the standard pjit multi-pod recipe: shard the
    node axis over BOTH axes (``P(("dcn", "nodes"))``) so neighbor gathers
    stay mostly intra-host while the population still spans all hosts.
    """
    devs = jax.devices()
    per = devices_per_host or len(devs) // n_hosts
    if n_hosts * per > len(devs):
        raise ValueError(
            f"requested {n_hosts}x{per} devices, have {len(devs)}")
    try:
        # On real multi-host hardware, plain jax.devices() order is NOT
        # guaranteed host-contiguous; the hybrid mesh helper places the DCN
        # axis on actual host boundaries.
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_hybrid_device_mesh(
            (per,), (n_hosts,), devices=devs[: n_hosts * per])
        arr = np.asarray(arr).reshape(n_hosts, per)
    except Exception:
        if jax.process_count() > 1:
            # The hybrid helper keys on slice metadata that CPU/virtual
            # clusters do not carry ("Number of slices 1 ..."). There the
            # process boundary IS the host boundary: order devices
            # host-contiguously by process_index and verify no inner-axis
            # row straddles a process — the exact property the helper
            # exists to guarantee. A layout that cannot satisfy it still
            # raises rather than silently cutting the dcn axis across ICI.
            by_host: dict[int, list] = {}
            for d in devs:
                by_host.setdefault(d.process_index, []).append(d)
            hosts = [sorted(v, key=lambda d: d.id)
                     for _, v in sorted(by_host.items())]
            flat = [d for h in hosts for d in h]
            arr = np.array(flat[: n_hosts * per]).reshape(n_hosts, per)
            if any(len({d.process_index for d in row}) != 1 for row in arr):
                raise ValueError(
                    f"make_mesh_2d({n_hosts}, {per}): an inner-axis row "
                    "would straddle a process boundary (processes have "
                    f"{[len(h) for h in hosts]} devices); choose "
                    "devices_per_host dividing the per-process count")
        else:
            # Single-process backends (CPU test mesh, one-host TPU) have
            # no host boundaries to respect — a plain reshape is exact.
            arr = np.array(devs[: n_hosts * per]).reshape(n_hosts, per)
    return Mesh(arr, axis_names)


def _tp_device_grid(devices, n_node_devices: int,
                    n_model_devices: int) -> np.ndarray:
    """Host-contiguous ``(nodes, model)`` device grid.

    Plain ``jax.devices()`` order is not guaranteed host-contiguous across
    processes; a naive reshape could pair a model-axis group across DCN,
    putting every tensor-parallel contraction psum on the slow links. This
    groups devices by ``process_index`` so each model-axis row lies within
    one host (psums ride ICI) and the nodes axis spans hosts (DCN only
    carries node-axis traffic, which the engine already keeps coarse).
    Pure placement logic, unit-testable with fake device objects.
    """
    by_host: dict[int, list] = {}
    for d in devices:
        by_host.setdefault(d.process_index, []).append(d)
    hosts = [sorted(v, key=lambda d: d.id) for _, v in sorted(by_host.items())]
    sizes = {len(h) for h in hosts}
    if len(sizes) != 1:
        raise ValueError(
            f"uneven device count per host: {sorted(len(h) for h in hosts)}")
    per_host = sizes.pop()
    if per_host % n_model_devices != 0:
        raise ValueError(
            f"model axis ({n_model_devices}) must divide the per-host device "
            f"count ({per_host}) so tensor-parallel groups stay on ICI")
    rows = [h[i:i + n_model_devices]
            for h in hosts for i in range(0, per_host, n_model_devices)]
    if len(rows) != n_node_devices:
        raise ValueError(
            f"device layout yields {len(rows)} node rows, "
            f"requested {n_node_devices}")
    return np.array(rows)


def make_mesh_tp(n_node_devices: int, n_model_devices: int,
                 axis_names: tuple[str, str] = (NODE_AXIS, MODEL_AXIS)) -> Mesh:
    """A 2-D ``(nodes, model)`` mesh: data parallelism over the node
    population x tensor parallelism over model axes.

    With this mesh, :func:`state_shardings` places the node dimension on the
    ``nodes`` axis only and additionally shards each parameter leaf's largest
    eligible non-node dimension over the ``model`` axis. Multi-host layouts
    are placed host-contiguously (see :func:`_tp_device_grid`): the model
    axis stays innermost on ICI, hosts span the nodes axis.
    """
    devs = jax.devices()
    need = n_node_devices * n_model_devices
    if need > len(devs):  # explicit: must survive python -O
        raise ValueError(f"requested {need} devices, have {len(devs)}")
    if jax.process_count() > 1 and need != len(devs):
        # A device subset cannot be chosen consistently across processes
        # without leaving some process idle; require the full complement.
        raise ValueError(
            "multi-host TP mesh must use every attached device: "
            f"requested {need} of {len(devs)}")
    return Mesh(_tp_device_grid(devs[:need], n_node_devices, n_model_devices),
                axis_names)


def _spec_for_rank(lead_axis_pos: int, ndim: int, axis_name) -> P:
    """PartitionSpec placing ``axis_name`` (a mesh axis name or a tuple of
    them, for 2-D meshes) at position ``lead_axis_pos``."""
    dims = [None] * ndim
    dims[lead_axis_pos] = axis_name
    return P(*dims)


def _node_axis_entry(mesh: Mesh, axis_name):
    """The PartitionSpec entry for the node dimension.

    ``axis_name=None`` (the default) derives it from the mesh: the single
    axis of a 1-D mesh, or ALL axes combined on a multi-axis mesh (the node
    population spans hosts x chips). An explicitly passed ``axis_name`` is
    honored verbatim — a caller with a custom multi-axis mesh can pin the
    node dimension to one axis.
    """
    if axis_name is not None:
        return axis_name
    # A "model" axis is tensor parallelism, never part of the node dimension.
    names = tuple(a for a in mesh.axis_names if a != MODEL_AXIS)
    assert names, "mesh has only a model axis; no axis left for nodes"
    if len(names) > 1:
        return names
    return names[0]


def _model_axis_entry(mesh: Mesh, model_axis):
    """The mesh axis used for tensor parallelism, or None.

    ``model_axis=None`` auto-detects: a mesh axis named ``"model"`` enables
    TP; any other mesh is node-parallel only.
    """
    if model_axis is not None:
        return model_axis
    return MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None


def _param_spec(leaf, node_pos: int, node_entry, mesh: Mesh, model_entry) -> P:
    """PartitionSpec for a parameter leaf: node axis at ``node_pos``, plus —
    when TP is on — the largest trailing dimension divisible by the model
    axis size sharded over it (ties broken toward the last dimension, where
    flax dense kernels put features)."""
    dims: list = [None] * leaf.ndim
    dims[node_pos] = node_entry
    if model_entry is not None:
        size = mesh.shape[model_entry]
        cands = [i for i in range(node_pos + 1, leaf.ndim)
                 if leaf.shape[i] >= size and leaf.shape[i] % size == 0]
        if cands and size > 1:
            dims[max(cands, key=lambda i: (leaf.shape[i], i))] = model_entry
    return P(*dims)


def state_shardings(state: SimState, mesh: Mesh,
                    axis_name=None, model_axis=None) -> SimState:
    """A SimState-shaped pytree of NamedShardings.

    - model / phase leaves: node axis leading -> ``P("nodes", ...)``
    - history / mailbox leaves: ``[D, N, ...]`` -> ``P(None, "nodes", ...)``
    - scalars (round counter): replicated
    - on a TP mesh (an axis named ``"model"``, or ``model_axis=...``):
      parameter, optimizer-state, and history-snapshot leaves additionally
      shard their largest eligible non-node dimension over the model axis
    """
    entry = _node_axis_entry(mesh, axis_name)
    model_entry = _model_axis_entry(mesh, model_axis)

    def _shard(leaf, pos, model):
        if not hasattr(leaf, "ndim") or leaf.ndim <= pos:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, _param_spec(leaf, pos, entry, mesh, model))

    def shard(leaf, pos):
        return _shard(leaf, pos, None)

    def shard_param(leaf, pos):
        return _shard(leaf, pos, model_entry)

    model_sh = state.model._replace(
        params=jax.tree.map(lambda l: shard_param(l, 0), state.model.params),
        opt_state=jax.tree.map(lambda l: shard_param(l, 0),
                               state.model.opt_state),
        n_updates=jax.tree.map(lambda l: shard(l, 0), state.model.n_updates),
    )
    phase_sh = shard(state.phase, 0)
    hist_p_sh = jax.tree.map(lambda l: shard_param(l, 1), state.history_params)
    hist_a_sh = shard(state.history_ages, 1)
    mb_sh = jax.tree.map(lambda l: shard(l, 1), state.mailbox)
    rb_sh = jax.tree.map(lambda l: shard(l, 1), state.reply_box)
    aux_sh = jax.tree.map(lambda l: shard(l, 0), state.aux)
    # int8 ring sidecar: [D, N] per leaf — node axis at position 1, like
    # the history ring itself (empty tuple for fp32/bf16 rings).
    hist_s_sh = jax.tree.map(lambda l: shard(l, 1), state.history_scale)
    return SimState(model=model_sh, phase=phase_sh,
                    history_params=hist_p_sh, history_ages=hist_a_sh,
                    mailbox=mb_sh, reply_box=rb_sh,
                    round=NamedSharding(mesh, P()),
                    aux=aux_sh, history_scale=hist_s_sh)


def shard_state(state: SimState, mesh: Mesh,
                axis_name=None, model_axis=None) -> SimState:
    """Place a SimState onto the mesh, node axis sharded (plus model axes on
    a TP mesh)."""
    return jax.device_put(state,
                          state_shardings(state, mesh, axis_name, model_axis))


def shard_data(data: dict, mesh: Mesh, axis_name=None) -> dict:
    """Shard stacked data: per-node arrays over the node axis, the global
    eval set replicated."""
    entry = _node_axis_entry(mesh, axis_name)
    out = {}
    for k, v in data.items():
        arr = jax.numpy.asarray(v)
        if k in ("x_eval", "y_eval"):
            out[k] = jax.device_put(arr, NamedSharding(mesh, P()))
        else:
            out[k] = jax.device_put(
                arr, NamedSharding(mesh, _spec_for_rank(0, arr.ndim, entry)))
    return out
