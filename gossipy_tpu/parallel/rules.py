"""Regex partition-rule registry: every sharding in the repo, derived.

Before this module, node-axis placement was hand-assembled per state group
inside ``parallel.state_shardings`` — a new state leaf (an aux cache, a
quantization sidecar) silently fell through to whatever the nearest
``tree.map`` happened to do, and nothing failed when a leaf went unmatched.
At million-node populations that is exactly the wrong failure mode: one
replicated ``[D, N, ...]`` ring leaf is the difference between fitting and
OOM.

This module is the single source of placement truth (the
``match_partition_rules`` / ``make_shard_and_gather_fns`` pattern of the
pjit-at-scale codebases — SNIPPETS.md [1]/[3]; "Scalable Training of
Language Models using JAX pjit and TPUv4"):

- a **rule table**: ordered ``(path regex, RuleSpec)`` pairs over slash-
  joined pytree leaf paths (``model/params/Dense_0/kernel``,
  ``mailbox/sender``, ``history_scale/...``). First match wins; an
  unmatched leaf RAISES — the coverage contract a test can enforce.
- a **RuleSpec**: where the node axis sits on the leaf (``node_pos``),
  whether the leaf is eligible for model-axis tensor parallelism
  (``tp``), or replicated outright. The spec is resolved against a
  concrete mesh + leaf shape into a ``jax.sharding.PartitionSpec`` —
  shape-dependent choices (which dimension takes the model axis) live in
  ONE resolver instead of being re-derived per call site.
- ``make_shard_and_gather_fns``: per-leaf shard (host -> mesh placement)
  and gather (mesh -> replicated) closures, the public API for moving a
  resident pool or checkpoint leaf-by-leaf without materializing the
  whole tree on one device.

``parallel.state_shardings`` / ``shard_data``, checkpoint mesh-restores
(``GossipSimulator.load(mesh=)``) and the service scheduler's megabatch
placement (``GossipService(mesh=)``) all derive from this table; no
hand-placed ``PartitionSpec`` exists outside this module (tracked by
``tests/test_rules.py``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"
DCN_AXIS = "dcn"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class RuleSpec:
    """Placement of one leaf family.

    - ``node_pos``: index of the leaf dimension carrying the node
      population (``None`` = fully replicated). A leaf with fewer than
      ``node_pos + 1`` dimensions resolves to replicated (scalar leaves
      in an otherwise node-leading family).
    - ``tp``: on a tensor-parallel mesh (an axis named ``"model"``), also
      shard the largest eligible trailing dimension over the model axis
      (parameter/optimizer/ring-snapshot leaves; metadata stays node-only).
    """

    node_pos: Optional[int] = 0
    tp: bool = False

    def describe(self) -> str:
        if self.node_pos is None:
            return "replicated"
        return (f"node_axis@{self.node_pos}" + ("+tp" if self.tp else ""))


REPLICATED = RuleSpec(node_pos=None)

# The SimState rule table. Paths are slash-joined leaf key paths rooted at
# the SimState fields (NamedTuple attributes become path components, dict
# keys likewise). ORDER MATTERS: first match wins. Every family the engine
# or an in-tree variant can put into a SimState must match a rule — adding
# a state field without a rule fails `match_partition_rules` (and the
# coverage test) instead of silently replicating a [D, N, ...] array.
STATE_RULES: tuple[tuple[str, RuleSpec], ...] = (
    # Per-node model: params + optimizer state take the model axis on a TP
    # mesh; the update counter is bookkeeping.
    (r"^model/params(/|$)", RuleSpec(node_pos=0, tp=True)),
    (r"^model/opt_state(/|$)", RuleSpec(node_pos=0, tp=True)),
    (r"^model/n_updates(/|$)", RuleSpec(node_pos=0)),
    # Per-node timing (sync offset / async period).
    (r"^phase$", RuleSpec(node_pos=0)),
    # History ring [D, N, ...]: snapshots are params-shaped past the two
    # leading axes -> TP-eligible; ages and the int8 scale sidecars
    # ([D, N(, extra)] per leaf) are node-only.
    (r"^history_params(/|$)", RuleSpec(node_pos=1, tp=True)),
    (r"^history_ages$", RuleSpec(node_pos=1)),
    (r"^history_scale(/|$)", RuleSpec(node_pos=1)),
    # Mailbox metadata [D, N, K] (push/pull and reply traffic).
    (r"^(mailbox|reply_box)/", RuleSpec(node_pos=1)),
    # Round counter: replicated scalar.
    (r"^round$", REPLICATED),
    # Variant aux state (token balances, neighbor caches, PENS counters,
    # cohort tables): leading node axis by contract (engine.py SimState).
    (r"^aux(/|$)", RuleSpec(node_pos=0)),
)

# Stacked-data rule table (DataDispatcher.stacked() dicts): the global
# eval split is replicated (every node scores the same set), everything
# else is per-node and sharded on its leading axis.
DATA_RULES: tuple[tuple[str, RuleSpec], ...] = (
    (r"^(x_eval|y_eval)$", REPLICATED),
    (r"^", RuleSpec(node_pos=0)),
)


def _key_name(entry) -> str:
    """One path component from a jax key-path entry (attr names for
    NamedTuples, dict keys, sequence indices)."""
    name = getattr(entry, "name", None)
    if name is not None:
        return str(name)
    key = getattr(entry, "key", None)
    if key is not None:
        return str(key)
    idx = getattr(entry, "idx", None)
    if idx is not None:
        return str(idx)
    return str(entry)


def leaf_path(path) -> str:
    """Slash-joined name of a jax key path (the rule-matching string)."""
    return "/".join(_key_name(e) for e in path)


def named_leaves(tree) -> list[tuple[str, object]]:
    """``(path, leaf)`` pairs for every leaf, with slash-joined paths."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(leaf_path(p), leaf) for p, leaf in flat]


class UnmatchedLeafError(ValueError):
    """A pytree leaf no partition rule covers — the coverage contract.

    Raised instead of silently replicating: at population scale an
    unplaced ``[D, N, ...]`` leaf is an OOM, not a fallback.
    """


def match_partition_rules(rules, tree, *, prefix: str = ""):
    """A tree of :class:`RuleSpec` matching ``tree``'s structure.

    Each leaf's slash-joined path (optionally prefixed) is matched against
    the ordered ``(regex, RuleSpec)`` table with ``re.search``; first
    match wins. An unmatched leaf raises :class:`UnmatchedLeafError`
    naming the path and the table — coverage is a hard contract, not a
    default.
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def pick(path, leaf):
        name = prefix + leaf_path(path)
        for pat, spec in compiled:
            if pat.search(name):
                return spec
        raise UnmatchedLeafError(
            f"no partition rule matches leaf {name!r}; add a rule to the "
            "table (parallel/rules.py) — unmatched leaves are an error, "
            "not a replicate-by-default")

    return jax.tree_util.tree_map_with_path(pick, tree)


# -- mesh-axis resolution (the mesh half of a rule) --------------------------

def node_axis_entry(mesh: Mesh, axis_name=None):
    """The PartitionSpec entry for the node dimension.

    ``axis_name=None`` derives it from the mesh: the single axis of a 1-D
    mesh, or ALL non-model axes combined (the node population spans
    hosts x chips). An explicit ``axis_name`` is honored verbatim.
    """
    if axis_name is not None:
        return axis_name
    names = tuple(a for a in mesh.axis_names if a != MODEL_AXIS)
    assert names, "mesh has only a model axis; no axis left for nodes"
    if len(names) > 1:
        return names
    return names[0]


def node_axis_size(mesh: Mesh, axis_name=None) -> int:
    """Total extent of the node axis (product over a combined multi-axis
    entry) — what a node-leading dimension must divide to shard evenly
    (the cohort driver's mesh validation reads this)."""
    entry = node_axis_entry(mesh, axis_name)
    names = entry if isinstance(entry, tuple) else (entry,)
    size = 1
    for a in names:
        size *= int(mesh.shape[a])
    return size


def model_axis_entry(mesh: Mesh, model_axis=None):
    """The mesh axis used for tensor parallelism, or None. Auto-detects an
    axis named ``"model"`` when not given explicitly."""
    if model_axis is not None:
        return model_axis
    return MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None


def node_leading_spec(ndim: int, entry, pos: int = 0) -> P:
    """PartitionSpec with the node entry at ``pos``, rest replicated —
    the registry's primitive every resolved spec is built from (also used
    directly by the explicit collectives for their shard_map I/O specs)."""
    dims: list = [None] * ndim
    if pos < ndim:
        dims[pos] = entry
    return P(*dims)


def replicated_spec(ndim: int) -> P:
    """Fully-replicated PartitionSpec of rank ``ndim``."""
    return P(*([None] * ndim))


def resolve_spec(rule: RuleSpec, leaf, mesh: Mesh, node_entry,
                 model_entry=None, batch_dims: int = 0) -> P:
    """Resolve one rule against a concrete leaf + mesh into a
    PartitionSpec.

    ``batch_dims`` shifts the node position right by that many leading
    axes — the seed/tenant-vmapped megabatch case, where every leaf gains
    a leading [T] lane axis that stays replicated.

    TP resolution (``rule.tp`` on a mesh with a model axis): the largest
    trailing dimension divisible by the model-axis size takes it (ties
    toward the last dimension, where flax dense kernels put features).
    """
    ndim = getattr(leaf, "ndim", 0)
    if rule.node_pos is None:
        return replicated_spec(ndim)
    pos = rule.node_pos + batch_dims
    if ndim <= pos:
        return replicated_spec(ndim)
    dims: list = [None] * ndim
    dims[pos] = node_entry
    if rule.tp and model_entry is not None:
        size = mesh.shape[model_entry]
        cands = [i for i in range(pos + 1, ndim)
                 if leaf.shape[i] >= size and leaf.shape[i] % size == 0]
        if cands and size > 1:
            dims[max(cands, key=lambda i: (leaf.shape[i], i))] = model_entry
    return P(*dims)


def partition_specs(tree, mesh: Mesh, rules=STATE_RULES, axis_name=None,
                    model_axis=None, batch_dims: int = 0):
    """``tree``-shaped pytree of PartitionSpecs: match the rule table,
    resolve each rule against the mesh and leaf shape."""
    node_entry = node_axis_entry(mesh, axis_name)
    model_entry = model_axis_entry(mesh, model_axis)
    rule_tree = match_partition_rules(rules, tree)
    return jax.tree.map(
        lambda leaf, rule: resolve_spec(rule, leaf, mesh, node_entry,
                                        model_entry, batch_dims),
        tree, rule_tree)


def named_shardings(tree, mesh: Mesh, rules=STATE_RULES, axis_name=None,
                    model_axis=None, batch_dims: int = 0):
    """``tree``-shaped pytree of NamedShardings (resolved rule table)."""
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        partition_specs(tree, mesh, rules, axis_name,
                                        model_axis, batch_dims))


def make_shard_and_gather_fns(tree, mesh: Mesh, rules=STATE_RULES,
                              axis_name=None, model_axis=None,
                              batch_dims: int = 0):
    """Per-leaf shard/gather closures from the resolved rule table
    (SNIPPETS.md [1]/[3] ``make_shard_and_gather_fns``).

    Returns ``(shard_fns, gather_fns)``, two pytrees matching ``tree``:

    - ``shard_fns`` leaf: ``fn(array) -> array`` placed per its rule
      (``jax.device_put`` with the resolved NamedSharding) — apply
      leaf-by-leaf to stream a host-resident pool or checkpoint onto the
      mesh without staging the whole tree on one device.
    - ``gather_fns`` leaf: ``fn(array) -> np.ndarray`` fully gathered to
      replicated host memory — the inverse, for checkpointing or host
      inspection of a sharded leaf.
    """
    import numpy as np
    shardings = named_shardings(tree, mesh, rules, axis_name, model_axis,
                                batch_dims)

    def make_shard(sh):
        return lambda x: jax.device_put(x, sh)

    def make_gather(sh):
        del sh
        return lambda x: np.asarray(jax.device_get(x))

    return (jax.tree.map(make_shard, shardings),
            jax.tree.map(make_gather, shardings))


def rules_table(rules=STATE_RULES) -> list[list[str]]:
    """The rule table as ``[pattern, placement]`` string rows — the
    manifest stamp (:class:`~gossipy_tpu.telemetry.RunManifest` records
    which placement registry produced a run's shardings)."""
    return [[pat, spec.describe()] for pat, spec in rules]


def resolved_rules_table(tree, rules=STATE_RULES) -> list[list[str]]:
    """Leaf-resolved table: ``[leaf path, placement]`` for every leaf of
    ``tree`` under ``rules`` (raises on an unmatched leaf). The audit
    view: exactly where every state array of THIS simulator lands."""
    rule_tree = match_partition_rules(rules, tree)
    return [[path, spec.describe()]
            for (path, _), (_, spec) in zip(named_leaves(tree),
                                            named_leaves(rule_tree))]
