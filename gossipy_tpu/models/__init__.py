"""Model zoo: flax.linen re-designs of the reference's model classes.

Reference models live in ``gossipy/model/nn.py`` (Perceptron/MLP/AdaLine/
LogReg/LinReg) and ``main_onoszko_2021.py:28-56`` (CIFAR10Net). Here every
model is a flax module; parameters are plain pytrees so N nodes' models stack
into one leading-axis pytree for vmapped training. The ``Sizeable.get_size``
protocol (reference gossipy/__init__.py:134-156) becomes :func:`param_count`
— static arithmetic over the pytree, no per-message traversal.
"""

from .nn import (
    AdaLine,
    CIFAR10Net,
    LinearRegression,
    LogisticRegression,
    Perceptron,
    MLP,
    param_count,
)

__all__ = [
    "AdaLine", "CIFAR10Net", "LinearRegression", "LogisticRegression",
    "Perceptron", "MLP", "param_count",
]
