"""Flax model definitions.

Output conventions follow the reference exactly so handler/eval semantics
carry over:

- :class:`Perceptron` — sigmoid(linear) -> [B, 1] (reference nn.py:26-64)
- :class:`MLP` — raw logits (reference nn.py:67-113; final layer linear)
- :class:`LogisticRegression` — sigmoid(linear) -> [B, C] (reference nn.py:147-174;
  yes, the reference feeds sigmoid outputs to CrossEntropyLoss — callers pick
  the loss, we keep the forward identical)
- :class:`LinearRegression` — linear (reference nn.py:176-198)
- :class:`CIFAR10Net` — 3xConv+pool, 2xFC CNN (reference main_onoszko_2021.py:28-56);
  NHWC layout for TPU-friendly convolutions
- :class:`AdaLine` — a bare weight vector trained by manual delta rules
  (reference nn.py:116-143); not a flax module, just an init helper
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def param_count(params) -> int:
    """Total number of scalars in a parameter pytree.

    Replaces ``TorchModel.get_size`` (reference gossipy/model/__init__.py:45-58);
    used for message-size accounting in delay models and the report.
    """
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


class Perceptron(nn.Module):
    """Rosenblatt perceptron: sigmoid output neuron (reference nn.py:26-64)."""

    dim: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(1, use_bias=self.use_bias,
                     kernel_init=nn.initializers.xavier_uniform())(x)
        return nn.sigmoid(h)


class MLP(nn.Module):
    """Multi-layer perceptron with configurable hidden dims (reference nn.py:67-113)."""

    input_dim: int
    output_dim: int
    hidden_dims: Sequence[int] = (100,)
    activation: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        for h in self.hidden_dims:
            x = nn.Dense(h, kernel_init=nn.initializers.xavier_uniform())(x)
            x = self.activation(x)
        return nn.Dense(self.output_dim,
                        kernel_init=nn.initializers.xavier_uniform())(x)


class LogisticRegression(nn.Module):
    """sigmoid(Wx + b) with C outputs (reference nn.py:147-174)."""

    input_dim: int
    output_dim: int

    @nn.compact
    def __call__(self, x):
        return nn.sigmoid(nn.Dense(self.output_dim)(x))


class LinearRegression(nn.Module):
    """Wx + b (reference nn.py:176-198)."""

    input_dim: int
    output_dim: int

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.output_dim)(x)


def _im2col_valid(x: jax.Array, kh: int, kw: int) -> jax.Array:
    """VALID-padding im2col as pure data movement: ``[B, Ho, Wo, kh*kw*C]``.

    Built from kh*kw shifted slices + one concat (no convolution primitive),
    so it stays a layout op under any batching transform. The last-axis
    order is (i, j, c) row-major — exactly ``kernel.reshape(kh*kw*C, O)``'s
    flattening of an HWIO kernel, so ``patches @ kernel.reshape(-1, O)``
    reproduces the convolution.
    """
    ho = x.shape[-3] - kh + 1
    wo = x.shape[-2] - kw + 1
    cols = [x[..., i:i + ho, j:j + wo, :]
            for i in range(kh) for j in range(kw)]
    return jnp.concatenate(cols, axis=-1)


class _EinsumConv(nn.Module):
    """3x3 VALID conv computed as im2col + einsum (same params as nn.Conv).

    Why this exists: the simulation engine vmaps the model over the node
    axis with PER-NODE weights. A vmapped ``lax.conv`` becomes a grouped
    convolution with C_in-channel groups — at C_in=3 the MXU runs nearly
    empty. The im2col form vmaps to a *batched matmul* ``[N, M, kh*kw*C] @
    [N, kh*kw*C, O]`` (and when the input is shared across nodes, e.g. the
    global eval set, XLA collapses it further to one ``[M, K] @ [K, N*O]``
    dot). Parameter names/shapes match ``nn.Conv`` (kernel HWIO + bias), so
    the two implementations are checkpoint-interchangeable; outputs are
    equal up to fp reduction order (tested).
    """

    features: int
    kernel_init: Callable = nn.initializers.xavier_uniform()

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (3, 3, x.shape[-1], self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        patches = _im2col_valid(x, 3, 3)
        y = jnp.einsum("...k,ko->...o", patches,
                       kernel.reshape(-1, self.features))
        return y + bias


class CIFAR10Net(nn.Module):
    """Small CIFAR-10 CNN (reference main_onoszko_2021.py:28-56), NHWC.

    conv(3->32,3x3) -> pool -> conv(32->64,3x3) -> pool -> conv(64->64,3x3)
    -> pool -> fc(256->64) -> fc(64->10). VALID padding and 2x2 max-pool to
    match the reference's spatial arithmetic (32->15->6->2).

    ``conv_impl`` selects how the convolutions are computed — same math,
    same parameter tree, different XLA program:

    - ``"conv"``: ``nn.Conv`` (lax.conv_general_dilated).
    - ``"einsum"``: im2col + einsum (:class:`_EinsumConv`) — the MXU-
      friendly form under the engine's per-node vmap, where ``"conv"``
      lowers to tiny-group grouped convolutions.
    - ``"auto"`` (default): einsum. Measured on the engine's vmapped
      shapes (scripts/microbench_components.py, 8 nodes, CPU): the
      train slot is 17x faster under einsum (0.72 s vs 12.3 s — the
      grouped-conv pathology is not TPU-specific); the only regression
      is tiny-eval forward (42 -> 62 ms), dominated by the train win.
    """

    n_classes: int = 10
    conv_impl: str = "auto"

    def _conv(self, features: int, name: str):
        impl = self.conv_impl
        if impl == "auto":
            impl = "einsum"
        init = nn.initializers.xavier_uniform()
        if impl == "einsum":
            return _EinsumConv(features, kernel_init=init, name=name)
        if impl != "conv":
            # Must survive python -O: a typo silently falling through to the
            # 17x-slower grouped-conv lowering would be invisible.
            raise ValueError(f"unknown conv_impl {self.conv_impl!r}; "
                             "options: auto, einsum, conv")
        return nn.Conv(features, (3, 3), padding="VALID", kernel_init=init,
                       name=name)

    @nn.compact
    def __call__(self, x):
        # Accept NCHW input for API parity and transpose to NHWC for the MXU.
        if x.shape[-1] != 3 and x.shape[1] == 3:
            x = jnp.transpose(x, (0, 2, 3, 1))
        init = nn.initializers.xavier_uniform()
        # Explicit names keep the param tree identical across conv_impls
        # (flax would otherwise auto-name by class: Conv_0 vs _EinsumConv_0).
        x = nn.relu(self._conv(32, "Conv_0")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(self._conv(64, "Conv_1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(self._conv(64, "Conv_2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[:-3] + (-1,))
        x = nn.relu(nn.Dense(64, kernel_init=init)(x))
        return nn.Dense(self.n_classes, kernel_init=init)(x)


class AdaLine:
    """AdaLine / Pegasos weight vector (reference nn.py:116-143).

    Not a flax module: the model IS a zero-initialized [dim] vector and its
    training rules are hand-written in the handlers (delta rule / Pegasos),
    exactly as the reference bypasses autograd (``requires_grad=False``).
    """

    def __init__(self, dim: int):
        self.dim = dim

    def init(self) -> jax.Array:
        return jnp.zeros((self.dim,), dtype=jnp.float32)

    @staticmethod
    def apply(w: jax.Array, x: jax.Array) -> jax.Array:
        """Score = x @ w for a batch [B, dim] (reference nn.py:134-135)."""
        return x @ w
