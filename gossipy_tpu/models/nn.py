"""Flax model definitions.

Output conventions follow the reference exactly so handler/eval semantics
carry over:

- :class:`Perceptron` — sigmoid(linear) -> [B, 1] (reference nn.py:26-64)
- :class:`MLP` — raw logits (reference nn.py:67-113; final layer linear)
- :class:`LogisticRegression` — sigmoid(linear) -> [B, C] (reference nn.py:147-174;
  yes, the reference feeds sigmoid outputs to CrossEntropyLoss — callers pick
  the loss, we keep the forward identical)
- :class:`LinearRegression` — linear (reference nn.py:176-198)
- :class:`CIFAR10Net` — 3xConv+pool, 2xFC CNN (reference main_onoszko_2021.py:28-56);
  NHWC layout for TPU-friendly convolutions
- :class:`AdaLine` — a bare weight vector trained by manual delta rules
  (reference nn.py:116-143); not a flax module, just an init helper
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def param_count(params) -> int:
    """Total number of scalars in a parameter pytree.

    Replaces ``TorchModel.get_size`` (reference gossipy/model/__init__.py:45-58);
    used for message-size accounting in delay models and the report.
    """
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


class Perceptron(nn.Module):
    """Rosenblatt perceptron: sigmoid output neuron (reference nn.py:26-64)."""

    dim: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(1, use_bias=self.use_bias,
                     kernel_init=nn.initializers.xavier_uniform())(x)
        return nn.sigmoid(h)


class MLP(nn.Module):
    """Multi-layer perceptron with configurable hidden dims (reference nn.py:67-113)."""

    input_dim: int
    output_dim: int
    hidden_dims: Sequence[int] = (100,)
    activation: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        for h in self.hidden_dims:
            x = nn.Dense(h, kernel_init=nn.initializers.xavier_uniform())(x)
            x = self.activation(x)
        return nn.Dense(self.output_dim,
                        kernel_init=nn.initializers.xavier_uniform())(x)


class LogisticRegression(nn.Module):
    """sigmoid(Wx + b) with C outputs (reference nn.py:147-174)."""

    input_dim: int
    output_dim: int

    @nn.compact
    def __call__(self, x):
        return nn.sigmoid(nn.Dense(self.output_dim)(x))


class LinearRegression(nn.Module):
    """Wx + b (reference nn.py:176-198)."""

    input_dim: int
    output_dim: int

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.output_dim)(x)


class CIFAR10Net(nn.Module):
    """Small CIFAR-10 CNN (reference main_onoszko_2021.py:28-56), NHWC.

    conv(3->32,3x3) -> pool -> conv(32->64,3x3) -> pool -> conv(64->64,3x3)
    -> pool -> fc(256->64) -> fc(64->10). VALID padding and 2x2 max-pool to
    match the reference's spatial arithmetic (32->15->6->2).
    """

    n_classes: int = 10

    @nn.compact
    def __call__(self, x):
        # Accept NCHW input for API parity and transpose to NHWC for the MXU.
        if x.shape[-1] != 3 and x.shape[1] == 3:
            x = jnp.transpose(x, (0, 2, 3, 1))
        init = nn.initializers.xavier_uniform()
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", kernel_init=init)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", kernel_init=init)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", kernel_init=init)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(64, kernel_init=init)(x))
        return nn.Dense(self.n_classes, kernel_init=init)(x)


class AdaLine:
    """AdaLine / Pegasos weight vector (reference nn.py:116-143).

    Not a flax module: the model IS a zero-initialized [dim] vector and its
    training rules are hand-written in the handlers (delta rule / Pegasos),
    exactly as the reference bypasses autograd (``requires_grad=False``).
    """

    def __init__(self, dim: int):
        self.dim = dim

    def init(self) -> jax.Array:
        return jnp.zeros((self.dim,), dtype=jnp.float32)

    @staticmethod
    def apply(w: jax.Array, x: jax.Array) -> jax.Array:
        """Score = x @ w for a batch [B, dim] (reference nn.py:134-135)."""
        return x @ w
