"""Model partitioning and coordinate-sampling compression.

TPU-native re-design of ``gossipy/model/sampling.py``:

- ``TorchModelPartition`` (reference sampling.py:110-198) builds per-layer
  index tuples; here a partition is a *pytree of int32 part-ids*, one per
  parameter coordinate, built once on host. Partition merge becomes
  ``where(part_ids == pid, weighted_avg, keep)`` — branch-free, vmappable.
- ``TorchModelSampling`` (reference sampling.py:37-107) draws ~size*|θ|
  random coordinates with replacement; here a sample is a Bernoulli(size)
  mask drawn from a PRNG key at merge time (same expected coverage, no
  host-side index bookkeeping). Sampled merge = ``where(mask, (p1+p2)/2, p1)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class ModelPartition:
    """Deterministic equal-size partition of all parameters into ``n_parts``.

    Coordinates are split contiguously in flat traversal order (first layer
    to last), sizes differing by at most 1 — the same contract as the
    reference's partitioner (sampling.py:110-198, "divides the parameters
    ... in n_parts parts of equal size starting from the first layer").
    ``part_ids`` is a pytree matching the params template with an int32 part
    id per coordinate.
    """

    def __init__(self, params_template, n_parts: int):
        leaves, treedef = jax.tree_util.tree_flatten(params_template)
        total = sum(l.size for l in leaves)
        self.n_parts = int(min(n_parts, total))
        # Flat coordinate c belongs to part floor(c * n_parts / total) —
        # contiguous blocks whose sizes differ by at most one.
        ids = []
        offset = 0
        for leaf in leaves:
            flat = (np.arange(offset, offset + leaf.size, dtype=np.int64)
                    * self.n_parts) // total
            ids.append(jnp.asarray(flat.reshape(leaf.shape), dtype=jnp.int32))
            offset += leaf.size
        self.part_ids = jax.tree_util.tree_unflatten(treedef, ids)
        self.sizes = np.bincount(
            np.concatenate([np.asarray(i).ravel() for i in ids]),
            minlength=self.n_parts)

    def merge(self, params1, params2, id_part: jax.Array,
              weights: tuple[jax.Array, jax.Array] | None = None):
        """Weighted average of one partition of two models.

        Mirrors ``TorchModelPartition.merge`` (sampling.py:201-234): weights
        (usually the two ages) are normalized; (0, 0) falls back to (1, 1).
        ``id_part`` may be traced (it arrives in a message payload).
        """
        if weights is None:
            w1 = w2 = jnp.float32(0.5)
        else:
            a1 = jnp.asarray(weights[0], dtype=jnp.float32)
            a2 = jnp.asarray(weights[1], dtype=jnp.float32)
            tot = a1 + a2
            w1 = jnp.where(tot > 0, a1 / jnp.where(tot > 0, tot, 1.0), 0.5)
            w2 = jnp.where(tot > 0, a2 / jnp.where(tot > 0, tot, 1.0), 0.5)
        pid = jnp.asarray(id_part, dtype=jnp.int32) % self.n_parts

        def leaf_merge(p1, p2, ids):
            avg = w1 * p1 + w2 * p2
            return jnp.where(ids == pid, avg, p1)

        return jax.tree.map(leaf_merge, params1, params2, self.part_ids)


def sample_mask(key: jax.Array, params_template, sample_size: float):
    """Bernoulli(sample_size) coordinate mask pytree.

    Replaces ``TorchModelSampling.sample`` (sampling.py:37-72): the reference
    draws ~size*|θ| coordinates with replacement (layer chosen ∝ numel);
    an independent Bernoulli per coordinate has the same expected fraction
    and is purely functional.
    """
    leaves, treedef = jax.tree_util.tree_flatten(params_template)
    keys = jax.random.split(key, len(leaves))
    masks = [jax.random.bernoulli(k, p=sample_size, shape=l.shape)
             for k, l in zip(keys, leaves)]
    return jax.tree_util.tree_unflatten(treedef, masks)


def sampled_merge(params1, params2, mask):
    """In the sampled coordinates, average; elsewhere keep ``params1``.

    Mirrors ``TorchModelSampling.merge`` (sampling.py:75-107).
    """
    return jax.tree.map(
        lambda p1, p2, m: jnp.where(m, (p1 + p2) / 2.0, p1),
        params1, params2, mask)
