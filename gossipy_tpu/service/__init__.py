"""Gossip-as-a-service: a multi-tenant run scheduler over one device set.

The "millions of users" axis of the ROADMAP: instead of one process
driving one simulation, this package multiplexes MANY concurrent
experiments ("tenants") through three pieces:

- :mod:`.spec` — :class:`RunRequest` (an
  :class:`~gossipy_tpu.config.ExperimentConfig` + tenant name, JSON spec
  format), :class:`RunHandle` (status / report / artifacts / bundle) and
  the :class:`RunQueue`;
- :mod:`.packer` — buckets queued runs by compiled-program
  :class:`ShapeSignature` (config shape fields + built-simulator
  geometry + topology content + data shapes) so same-shape tenants fuse
  into one seed/config-vmapped megabatch program;
- :mod:`.scheduler` — :class:`GossipService`, the cooperative host-side
  control plane: chunked round slices round-robin across buckets, donated
  state, per-tenant telemetry (JSONL/report/manifest), and sentinel-trip
  eviction with flight-recorder bundles.

See ``docs/service.md`` for the model and ``scripts/serve.py`` /
``examples/main_service.py`` for drivers.
"""

from .packer import (
    Bucket,
    BuiltRun,
    ShapeSignature,
    build_request,
    pack,
    shape_signature,
)
from .scheduler import GossipService, ServiceSession
from .slo import (
    default_spec_pool,
    make_requests,
    poisson_arrivals,
    run_load,
    slo_row,
)
from .spec import RunHandle, RunQueue, RunRequest, RunStatus

__all__ = [
    "RunRequest", "RunHandle", "RunQueue", "RunStatus",
    "ShapeSignature", "BuiltRun", "Bucket", "shape_signature",
    "build_request", "pack",
    "GossipService", "ServiceSession",
    "default_spec_pool", "make_requests", "poisson_arrivals",
    "run_load", "slo_row",
]
