"""Cooperative multi-tenant scheduler: many experiments, one device set.

The Podracer-architecture split, applied to gossip simulation: round
execution is actor-like on-device work (one vmapped megabatch program per
bucket, tenants riding the batch axis), while admission, slicing,
telemetry routing and failure handling live in a host-side control plane
— this module. The scheduler:

- **packs** queued runs into shape buckets (:mod:`.packer`) and compiles
  ONE init program + ONE step program per bucket, whatever the tenant
  count — the compiled program is the scheduling currency, shared further
  across processes via the persistent compilation cache
  (``GOSSIPY_TPU_COMPILATION_CACHE``);
- **drives** buckets cooperatively in chunked round slices (round-robin
  across buckets, state donated between slices so the [T, D, N, ...]
  history rings are never double-buffered);
- **streams** per-tenant telemetry: each tenant gets its own JSONL event
  stream (schema-v5 rows replayed per slice), its own
  :class:`~gossipy_tpu.simulation.report.SimulationReport` and its own
  per-tenant :class:`~gossipy_tpu.telemetry.RunManifest` (fault
  rates/seed patched to the TENANT's values, bucket + signature + the
  bucket's compilation-cache delta stamped into ``extra.service``,
  plus per-tenant cost attribution — tenant-seconds of measured slice
  wall time and estimated FLOPs from the step program's own
  ``cost_analysis()`` — under ``extra.service.perf``);
- **meters** everything into the process SLO metrics registry
  (:mod:`gossipy_tpu.telemetry.metrics`; catalogue in docs/service.md):
  queue-wait and per-bucket compile seconds at admission,
  time-to-first-round per tenant, slice/round latency histograms,
  evictions by cause, and per-tenant tenant-seconds (the fair-share
  currency) — all HOST-side, never from a traced region (tracelint's
  ``metrics-in-trace`` rule enforces the boundary), with the per-tenant
  SLO record also stamped in-band (``extra.service.slo``); an
  incremental :class:`ServiceSession` (admit/poll/finish) lets tenants
  ARRIVE while buckets are mid-flight — the sustained-arrival SLO
  harness (:mod:`gossipy_tpu.service.slo`) drives it open-loop;
- **survives tenant failure**: each slice's start states are kept as
  host-side last-healthy copies; when a tenant's in-graph ``health_trip``
  sentinel fires, the scheduler writes that tenant's flight-recorder
  repro bundle (:meth:`~gossipy_tpu.telemetry.FlightRecorder.
  write_bundle` from its last healthy lane state) and EVICTS the tenant —
  its handle reports ``EVICTED`` with a truncated report — while
  co-tenants in the same bucket keep running untouched (vmapped lanes are
  independent; the tripped lane's numbers are simply no longer read).

Chunk-boundary note: like every chunked driver (``CheckpointManager``,
``FlightRecorder``), a slice's final round counts as a segment-final
round, which under ``eval_every > 1`` evaluates where one continuous scan
would skip — tenant curves can carry those extra eval rows.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import compilation_cache_stats
from ..checkpoint import slice_lane
from ..simulation.engine import BATCH_AXIS
from ..simulation.events import JSONLinesReceiver, SimulationEventSender
from ..telemetry import RunManifest, emit_event
from ..telemetry import tracing as _tracing
from ..telemetry.health import FlightRecorder
from ..telemetry.metrics import MetricsRegistry, get_registry
from .packer import Bucket, BuiltRun, build_request, pack
from .spec import RunQueue, RunRequest, RunStatus


def _service_metrics(reg: MetricsRegistry) -> dict:
    """Get-or-create the scheduler's metric families on ``reg`` (the SLO
    metric catalogue — docs/service.md documents each). Idempotent: the
    registry's family accessors are get-or-create by name."""
    return {
        "admitted": reg.counter(
            "service_tenants_admitted_total",
            "tenants packed into a bucket", ("bucket",)),
        "finished": reg.counter(
            "service_tenants_finished_total",
            "tenants that left the service, by final status",
            ("status",)),
        "evictions": reg.counter(
            "service_evictions_total",
            "tenants evicted/failed mid-run, by cause", ("cause",)),
        "queue_wait": reg.histogram(
            "service_queue_wait_seconds",
            "submission -> bucket admission wait", ("bucket",)),
        "ttfr": reg.histogram(
            "service_ttfr_seconds",
            "submission -> first completed round (time-to-first-round)"),
        "ttfr_tenant": reg.gauge(
            "service_tenant_ttfr_seconds",
            "per-tenant time-to-first-round", ("tenant",)),
        "compile": reg.gauge(
            "service_compile_seconds",
            "bucket program build+compile wall seconds",
            ("bucket", "program")),
        "slice": reg.histogram(
            "service_slice_seconds",
            "one cooperative slice's wall seconds", ("bucket",)),
        "round": reg.histogram(
            "service_round_seconds",
            "per-round latency (slice wall / rounds in slice)",
            ("bucket",)),
        "rounds": reg.counter(
            "service_rounds_total",
            "tenant-rounds harvested", ("bucket",)),
        "tenant_seconds": reg.counter(
            "service_tenant_seconds_total",
            "per-tenant share of measured bucket wall time "
            "(the fair-share currency)", ("tenant",)),
        "host_blocked": reg.gauge(
            "service_host_blocked_frac",
            "fraction of the bucket's cumulative slice wall spent in "
            "host-side work (trace-derived; compile + harvest + repro "
            "copies vs the device execution wait)", ("bucket",)),
    }


class _TenantSender(SimulationEventSender):
    """Per-tenant receiver host: the megabatch program cannot run live
    io_callbacks per tenant, so each slice's recorded rows are replayed
    through this sender to the tenant's receivers (JSONL by default)."""


class _BucketRuntime:
    """One bucket's device-side life: stacked states/keys/data, the two
    compiled programs, and the per-slice harvest loop."""

    def __init__(self, bucket: Bucket, out_root: str, slice_rounds: int,
                 keep_repro: bool, events_jsonl: bool,
                 registry: Optional[MetricsRegistry] = None,
                 mesh=None, tracer=None, ledger=None):
        self.bucket = bucket
        self.mesh = mesh
        # Run ledger (telemetry.ledger), shared across the session's
        # buckets: every finalized tenant appends one digest row, so SLO
        # accounting is continuous across process restarts — a resumed
        # queue served by a fresh service appends to the same file.
        self.ledger = ledger
        self._reg = registry if registry is not None else get_registry()
        self._m = _service_metrics(self._reg)
        self._digest8 = bucket.signature.digest[:8]
        # Host-side span tracer (telemetry.tracing), shared across the
        # session's buckets: slice/compile spans, the tenant lifecycle
        # async track, and the host_blocked accounting below.
        self.tracer = tracer
        self._hb_host = 0.0   # cumulative non-wait host seconds
        self._hb_wall = 0.0   # cumulative slice wall seconds
        self._queue_wait: dict[int, float] = {}
        self.sim = bucket.runs[0].sim  # the representative: ONLY sim run
        self.slice_rounds = int(slice_rounds)
        self.keep_repro = keep_repro
        self.sentinels_on = self.sim.sentinels is not None
        runs = bucket.runs
        self.keys = jnp.stack([r.key for r in runs])
        self.data = jax.tree.map(lambda *ls: jnp.stack(ls),
                                 *[r.sim.data for r in runs])
        self.drop = jnp.asarray([r.request.config.drop_prob for r in runs],
                                jnp.float32)
        self.online = jnp.asarray(
            [r.request.config.online_prob for r in runs], jnp.float32)
        # Chaos schedules are tenant data: same SHAPES within a bucket
        # (the signature's chaos_shape guarantees it), VALUES stacked on
        # the tenant axis and rebound per lane inside the step trace.
        self.chaos_on = getattr(self.sim, "chaos", None) is not None
        if self.chaos_on:
            self.chaos_scheds = jax.tree.map(
                lambda *ls: jnp.stack(ls),
                *[r.sim.chaos_schedule for r in runs])
        else:  # structure-stable dummy lane input, like hc w/o sentinels
            self.chaos_scheds = jnp.zeros((len(runs),), jnp.int32)
        self.requested = [r.request.rounds for r in runs]
        self.total_rounds = max(self.requested)
        self.n_slices = math.ceil(self.total_rounds / self.slice_rounds)
        self.rounds_done = 0
        self.live = True
        self.states = None
        self.hc: Any = jnp.zeros((len(runs),), jnp.int32)  # dummy w/o sentinels
        self._healthy: dict[int, Any] = {}
        self._healthy_round = 0
        self._accum: list[list[dict]] = [[] for _ in runs]
        self._cache_events_before = dict(
            compilation_cache_stats().get("events", {}))
        self._cache_delta: dict = {}
        # Per-tenant cost attribution (telemetry.cost): wall seconds of
        # the bucket's slices split evenly across the live lanes, and
        # estimated FLOPs = the step program's own cost_analysis count
        # divided by the lane count (the vmapped program widens every op
        # by T; XLA counts the scan body once, so program flops ≈ one
        # round of all T lanes) times the rounds the tenant actually
        # took. Stamped into each per-tenant manifest's extra.service.
        self._tenant_seconds = [0.0] * len(runs)
        self._tenant_flops = [0.0] * len(runs)
        self._step_cost = None
        self._step_compiled = None
        # Metric names must be resolved from CONCRETE data before the
        # step program traces with tracer-rebound sim.data (_maybe_eval
        # consults them at trace time under eval_every > 1).
        self.metric_names = self.sim._metric_keys()

        self.out_dirs: dict[int, str] = {}
        self._senders: list[_TenantSender] = []
        self._receivers: list[Optional[JSONLinesReceiver]] = []
        for i, r in enumerate(runs):
            d = os.path.join(out_root, r.tenant)
            os.makedirs(d, exist_ok=True)
            self.out_dirs[i] = d
            sender = _TenantSender()
            rx = None
            if events_jsonl:
                path = os.path.join(d, "events.jsonl")
                rx = JSONLinesReceiver(path)
                sender.add_receiver(rx)
                r.handle.artifacts["events"] = path
            self._senders.append(sender)
            self._receivers.append(rx)

        self._init_fn = None
        self._step_fn = None

    # -- compiled programs -------------------------------------------------

    def _make_init(self):
        sim = self.sim
        common_init = self.bucket.runs[0].request.config.common_init

        def init_one(key, data):
            saved = sim.data
            sim.data = data
            try:
                return sim.init_nodes(key, common_init=common_init)
            finally:
                sim.data = saved

        return jax.jit(jax.vmap(init_one))

    def _make_step(self):
        sim = self.sim
        chunk = self.slice_rounds
        sentinels_on = self.sentinels_on
        chaos_on = self.chaos_on

        def step_one(state, key, data, drop, online, hc, chaos_sched):
            # Rebind the per-tenant lane values onto the representative
            # simulator for the duration of the trace (the _make_run
            # pattern, extended to the fault rates — bernoulli takes a
            # traced p, so tenants in one program may differ in them —
            # and to the chaos schedule tables, whose per-round gathers
            # take traced operands just as well).
            saved = (sim.data, sim.drop_prob, sim.online_prob,
                     getattr(sim, "chaos_schedule", None))
            sim.data = data
            sim.drop_prob = drop
            sim.online_prob = online
            if chaos_on:
                sim.chaos_schedule = chaos_sched
            try:
                last = state.round + chunk - 1

                def body(carry, _):
                    if sentinels_on:
                        st, c = carry
                        pre_params = st.model.params
                        st, stats = sim._round(st, key, last)
                        c, hstats = sim._health_round(c, pre_params, st,
                                                      stats)
                        stats.update(hstats)
                        return (st, c), stats
                    st, stats = sim._round(carry, key, last)
                    return st, stats

                init = (state, hc) if sentinels_on else state
                final, stats = jax.lax.scan(body, init, None, length=chunk)
                if sentinels_on:
                    return final[0], final[1], stats
                return final, hc, stats
            finally:
                (sim.data, sim.drop_prob, sim.online_prob,
                 sim.chaos_schedule) = saved

        # Donate the state batch: the [T, D, N, ...] history rings are the
        # dominant term and each slice's input is dead once the next
        # slice's output exists (last-healthy copies are HOST numpy).
        return jax.jit(jax.vmap(step_one, axis_name=BATCH_AXIS),
                       donate_argnums=(0,))

    def initialize(self) -> None:
        t_adm = time.time()
        for i, r in enumerate(self.bucket.runs):
            # Queue wait: submission -> this bucket starting to compile.
            wait = max(t_adm - r.handle.submitted_at, 0.0)
            self._queue_wait[i] = wait
            self._m["queue_wait"].labels(bucket=self._digest8).observe(wait)
            if self.tracer is not None:
                # The tenant's lifecycle async track opens at admission;
                # first-round and finish markers land in step()/_finalize.
                self.tracer.begin_async(
                    "tenant", aid=r.tenant, bucket=self._digest8,
                    queue_wait_s=round(wait, 3))
        self._m["admitted"].labels(bucket=self._digest8).inc(
            self.bucket.size)
        # The span handle is the ONE timing source: it feeds both the
        # compile gauge and the trace (no parallel perf_counter local).
        sp_i = _tracing.span("service.init", cat="service",
                             tracer=self.tracer, bucket=self._digest8,
                             program="init")
        with sp_i:
            self._init_fn = self._make_init()
            self._step_fn = self._make_step()
            if self.mesh is not None:
                # Megabatch placement derives from the partition-rule
                # registry (parallel/rules.py): the stacked [T, ...]
                # tenant data and the [T, N, ...] state batch shard their
                # node axis per the same table as solo runs —
                # batch_dims=1 shifts every rule's node position past the
                # replicated lane axis.
                from ..parallel import shard_data
                self.data = shard_data(self.data, self.mesh, batch_dims=1)
            self.states = self._init_fn(self.keys, self.data)
            if self.mesh is not None:
                from ..parallel import shard_state
                self.states = shard_state(self.states, self.mesh,
                                          batch_dims=1)
            jax.block_until_ready(jax.tree.leaves(self.states)[0])
        self._m["compile"].labels(bucket=self._digest8,
                                  program="init").set_value(sp_i.duration)
        if self.sentinels_on:
            zero = self.sim._health_zero_carry()
            self.hc = jax.tree.map(
                lambda l: jnp.broadcast_to(
                    l[None], (self.bucket.size,) + l.shape).copy(), zero)
        for r in self.bucket.runs:
            r.handle.status = RunStatus.RUNNING
        emit_event("service_bucket_start", {
            "bucket": self.bucket.signature.digest,
            "tenants": self.bucket.tenants,
            "slice_rounds": self.slice_rounds,
            "total_rounds": self.total_rounds,
        })

    # -- slice driving -----------------------------------------------------

    def _live_lanes(self) -> list[int]:
        return [i for i, r in enumerate(self.bucket.runs)
                if r.handle.status is RunStatus.RUNNING]

    def step(self) -> None:
        """Advance every live tenant by one slice, harvest per-tenant
        rows, and handle completions/evictions."""
        lanes = self._live_lanes()
        if not lanes:
            self.live = False
            return
        chunk_start = self.rounds_done
        # The slice is one trace "run window" (round_start/rounds args
        # are what scripts/trace_report.py reduces on); the span handles
        # replace the t_slice0/t_c0 perf_counter locals — compile vs
        # execute seconds now come from ONE source each (the same span
        # feeds the gauge/histogram AND the trace).
        sp_slice = _tracing.span("service.slice", cat="service",
                                 tracer=self.tracer, bucket=self._digest8,
                                 round_start=chunk_start,
                                 rounds=self.slice_rounds)
        with sp_slice:
            if self.keep_repro:
                # Host copies survive the donation of the batched source
                # and become the bundle checkpoint if this slice trips a
                # lane.
                with _tracing.span("service.snapshot_healthy",
                                   cat="service", tracer=self.tracer):
                    self._healthy = {i: slice_lane(self.states, i)
                                     for i in lanes}
                self._healthy_round = self.rounds_done
            saved_axis = self.sim._batch_axis_name
            self.sim._batch_axis_name = BATCH_AXIS
            sp_c = None
            # cat="host.wait": dispatch + completion wait (the host
            # transfer forces it), not host work — the bridged device
            # span below accounts the window.
            sp_step = _tracing.span("service.step", cat=_tracing.WAIT_CAT,
                                    tracer=self.tracer)
            try:
                try:
                    step_args = (self.states, self.keys, self.data,
                                 self.drop, self.online, self.hc,
                                 self.chaos_scheds)
                    if self._step_compiled is None:
                        sp_c = _tracing.span("service.compile",
                                             cat="service",
                                             tracer=self.tracer,
                                             bucket=self._digest8,
                                             program="step")
                        with sp_c:
                            self._step_compiled = \
                                self._compile_step(step_args)
                        self._m["compile"].labels(
                            bucket=self._digest8,
                            program="step").set_value(sp_c.duration)
                    with sp_step:
                        self.states, self.hc, stats = \
                            self._step_compiled(*step_args)
                        host = jax.tree.map(np.asarray, stats)
                except Exception as e:  # the whole bucket program died
                    self._fail_all(e, chunk_start)
                    return
            finally:
                self.sim._batch_axis_name = saved_axis
            if self.tracer is not None:
                _tracing.attach_device_spans(
                    self.tracer, sp_step.ts_us, sp_step.dur_us,
                    args={"bucket": self._digest8})
            # The host transfer inside the step span forces completion,
            # so compile + step wall is the slice's real cost, attributed
            # evenly across live lanes (span-derived; glue excluded).
            slice_wall = sp_step.duration + \
                (sp_c.duration if sp_c is not None else 0.0)
            self._m["slice"].labels(bucket=self._digest8).observe(
                slice_wall)
            self._m["round"].labels(bucket=self._digest8).observe(
                slice_wall / max(self.slice_rounds, 1))
            per_lane_round_flops = (
                self._step_cost.flops / max(self.bucket.size, 1)
                if self._step_cost is not None and self._step_cost.flops
                else None)
            if not self._cache_delta:
                self._cache_delta = self._compute_cache_delta()
            self.rounds_done += self.slice_rounds

            sp_h = _tracing.span("service.harvest", cat="service",
                                 tracer=self.tracer, bucket=self._digest8)
            with sp_h:
                for i in lanes:
                    run = self.bucket.runs[i]
                    h = run.handle
                    take = min(self.slice_rounds,
                               self.requested[i] - h.rounds_completed)
                    rows = {k: v[i][:take] for k, v in host.items()}
                    trip_idx = None
                    if self.sentinels_on and "health_trip" in rows:
                        nz = np.nonzero(
                            np.asarray(rows["health_trip"]) > 0)[0]
                        trip_idx = int(nz[0]) if nz.size else None
                    self._tenant_seconds[i] += slice_wall / len(lanes)
                    self._m["tenant_seconds"].labels(
                        tenant=run.tenant).inc(slice_wall / len(lanes))
                    if h.rounds_completed == 0 and take > 0:
                        # Time-to-first-round: the tenant's first
                        # completed round became observable when this
                        # slice's results landed.
                        h.first_round_at = time.time()
                        ttfr = max(h.first_round_at - h.submitted_at, 0.0)
                        self._m["ttfr"].observe(ttfr)
                        self._m["ttfr_tenant"].labels(
                            tenant=run.tenant).set_value(ttfr)
                        if self.tracer is not None:
                            self.tracer.async_instant(
                                "first_round", aid=run.tenant,
                                ttfr_s=round(ttfr, 3))
                    if per_lane_round_flops is not None:
                        rounds_taken = (take if trip_idx is None
                                        else trip_idx + 1)
                        self._tenant_flops[i] += \
                            per_lane_round_flops * rounds_taken
                    if trip_idx is not None:
                        rows = {k: v[:trip_idx + 1]
                                for k, v in rows.items()}
                        self._harvest_rows(i, rows, chunk_start)
                        h.rounds_completed += trip_idx + 1
                        self._m["rounds"].labels(
                            bucket=self._digest8).inc(trip_idx + 1)
                        self._evict(i, chunk_start + trip_idx, rows)
                    else:
                        self._harvest_rows(i, rows, chunk_start)
                        h.rounds_completed += take
                        self._m["rounds"].labels(
                            bucket=self._digest8).inc(take)
                        if h.rounds_completed >= self.requested[i]:
                            self._finalize(i, RunStatus.DONE)
        # Per-bucket host-blocked accounting (the service_top column and
        # the trace counter track): everything in the window except the
        # device execution wait is host work; in this synchronous slice
        # loop none of it overlaps the device, so blocked == host-busy.
        self._hb_wall += sp_slice.duration
        self._hb_host += max(sp_slice.duration - sp_step.duration, 0.0)
        if self._hb_wall > 0:
            frac = self._hb_host / self._hb_wall
            self._m["host_blocked"].labels(
                bucket=self._digest8).set_value(round(frac, 4))
            if self.tracer is not None:
                self.tracer.counter_event(
                    f"host_blocked%/{self._digest8}",
                    value=round(frac * 100.0, 2))
        if not self._live_lanes():
            self.live = False

    def _compile_step(self, args):
        """AOT-compile the bucket's ONE step program (the same program
        the dispatch jit would build) so its ``cost_analysis()`` /
        ``memory_analysis()`` can be banked for per-tenant FLOP
        attribution. Falls back to the dispatch jit when the backend
        resists AOT — attribution then degrades to tenant-seconds
        only."""
        try:
            compiled = self._step_fn.lower(*args).compile()
        except Exception:
            return self._step_fn
        from ..telemetry.cost import CostReport
        self._step_cost = CostReport.from_compiled(
            compiled,
            label=f"service/step[{self.bucket.signature.digest[:8]}]",
            n_rounds=self.slice_rounds)
        return compiled

    def _compute_cache_delta(self) -> dict:
        stats = compilation_cache_stats()
        after = dict(stats.get("events", {}))
        delta = {k: after.get(k, 0) - self._cache_events_before.get(k, 0)
                 for k in set(after) | set(self._cache_events_before)}
        return {"enabled": stats.get("enabled", False),
                "events_delta": {k: v for k, v in sorted(delta.items())
                                 if v}}

    def _harvest_rows(self, i: int, rows: dict, chunk_start: int) -> None:
        """Accumulate one tenant's slice rows and stream them out: replay
        through the tenant's receivers (JSONL) and mirror a tagged
        per-round event into the process sink (trailing context for
        flight bundles; filter with ``events(where=...)``)."""
        if rows["sent"].shape[0] == 0:
            return
        run = self.bucket.runs[i]
        self._accum[i].append(rows)
        sender = self._senders[i]
        if sender._receivers_list():
            sender.replay_events(chunk_start, rows, self.metric_names,
                                 fire_end=False)
        trips = rows.get("health_trip")
        for j in range(rows["sent"].shape[0]):
            emit_event("round", {
                "tenant": run.tenant,
                "round": chunk_start + j + 1,
                "sent": int(rows["sent"][j]),
                "failed": int(rows["failed"][j]),
                "trip": bool(trips[j]) if trips is not None else False,
            })

    # -- completion / failure ----------------------------------------------

    def _tenant_stats(self, i: int) -> Optional[dict]:
        chunks = self._accum[i]
        if not chunks:
            return None
        return {k: np.concatenate([c[k] for c in chunks], axis=0)
                for k in chunks[0]}

    def _build_tenant_report(self, i: int):
        stats = self._tenant_stats(i)
        if stats is None:
            return None
        cfg = self.bucket.runs[i].request.config
        sim = self.sim
        # The report's host-side derived fields (probe expected fan-in)
        # read the simulator's fault-rate attributes — patch in the
        # tenant's own for the duration of the build.
        saved = (sim.drop_prob, sim.online_prob)
        sim.drop_prob, sim.online_prob = cfg.drop_prob, cfg.online_prob
        try:
            return sim._build_report(stats)
        finally:
            sim.drop_prob, sim.online_prob = saved

    def _tenant_manifest(self, i: int) -> RunManifest:
        run = self.bucket.runs[i]
        cfg = run.request.config
        h = run.handle
        return RunManifest.from_simulator(
            self.sim,
            extra={"service": {
                "tenant": run.tenant,
                "bucket": self.bucket.signature.digest,
                "bucket_tenants": self.bucket.tenants,
                "bucket_size": self.bucket.size,
                "signature": self.bucket.signature.summary,
                "slice_rounds": self.slice_rounds,
                "rounds_requested": self.requested[i],
                "rounds_completed": h.rounds_completed,
                "status": h.status.value,
                "bucket_compilation_cache": self._cache_delta,
                # Cost attribution for THIS tenant: its share of the
                # bucket's measured wall time and its estimated FLOPs
                # (null-safe: flops need the step program's AOT cost
                # capture, which some backends cannot provide).
                "perf": {
                    "tenant_seconds": round(self._tenant_seconds[i], 6),
                    "tenant_flops_est": (self._tenant_flops[i]
                                         if self._step_cost is not None
                                         else None),
                    "step_program": (self._step_cost.to_dict()
                                     if self._step_cost is not None
                                     else None),
                },
                # In-band SLO record for THIS tenant (telemetry.metrics):
                # the future fair-share scheduler's currency travels with
                # the tenant, not only in the process registry. Bucket
                # round-latency percentiles come from the registry's own
                # log-bucket estimator.
                "slo": self._tenant_slo(i),
            }},
            config_overrides={"drop_prob": cfg.drop_prob,
                              "online_prob": cfg.online_prob,
                              "seed": cfg.seed,
                              "tenant": run.tenant})

    def _tenant_slo(self, i: int) -> dict:
        run = self.bucket.runs[i]
        h = run.handle
        rh = self._m["round"].labels(bucket=self._digest8)
        ttfr = (h.first_round_at - h.submitted_at
                if h.first_round_at is not None else None)
        return {
            "queue_wait_seconds": round(self._queue_wait.get(i, 0.0), 6),
            "ttfr_seconds": round(ttfr, 6) if ttfr is not None else None,
            "tenant_seconds": round(self._tenant_seconds[i], 6),
            "rounds_completed": h.rounds_completed,
            "bucket_round_seconds_p50": rh.quantile(0.5),
            "bucket_round_seconds_p99": rh.quantile(0.99),
        }

    def _finalize(self, i: int, status: RunStatus) -> None:
        run = self.bucket.runs[i]
        h = run.handle
        h.status = status
        self._m["finished"].labels(status=status.value).inc()
        if self.tracer is not None:
            # Close the lifecycle async track opened at admission.
            self.tracer.end_async("tenant", aid=run.tenant,
                                  status=status.value,
                                  rounds=h.rounds_completed)
        h.report = self._build_tenant_report(i)
        out = self.out_dirs[i]
        if h.report is not None:
            path = os.path.join(out, "report.json")
            h.report.save(path)
            h.artifacts["report"] = path
        path = os.path.join(out, "manifest.json")
        manifest = self._tenant_manifest(i)
        manifest.save(path)
        h.artifacts["manifest"] = path
        self._ledger_append(i, manifest)
        self._senders[i]._notify_end()
        rx = self._receivers[i]
        if rx is not None:
            rx.close()
            self._receivers[i] = None

    def _ledger_append(self, i: int, manifest: RunManifest) -> None:
        """One digest row per finalized tenant (telemetry.ledger; no-op
        without a ledger): status + SLO percentiles + hashed artifact
        paths, with the tenant's own ExperimentConfig pinned under
        ``experiment`` so ``scripts/ledger.py bisect`` can replay it.
        Best-effort — a ledger problem must never fail a finalize."""
        if self.ledger is None:
            return
        try:
            import dataclasses

            from ..telemetry import ledger as _ledger
            run = self.bucket.runs[i]
            h = run.handle
            slo = self._tenant_slo(i)
            p50 = slo.get("bucket_round_seconds_p50")
            p99 = slo.get("bucket_round_seconds_p99")
            metrics = {
                "slo_p50_ms": p50 * 1000.0 if p50 is not None else None,
                "slo_p99_ms": p99 * 1000.0 if p99 is not None else None,
            }
            if h.report is not None:
                for name in ("accuracy", "auc", "f1"):
                    acc = h.report.final(name)
                    if acc == acc:
                        metrics["final_accuracy"] = acc
                        break
            failure = None
            if h.status is not RunStatus.DONE:
                failure = {"kind": h.status.value, "error": h.error}
                if h.bundle_path:
                    failure["bundle"] = h.bundle_path
            _ledger.ingest_manifest(
                self.ledger, manifest, kind="tenant",
                metrics=metrics, failure=failure,
                artifacts=dict(h.artifacts),
                experiment=dataclasses.asdict(run.request.config),
                extra={"tenant": run.tenant,
                       "bucket": self.bucket.signature.digest,
                       "status": h.status.value,
                       "rounds_completed": h.rounds_completed,
                       "slo": slo})
        except Exception:
            pass

    def _evict(self, i: int, bad_round: int, rows: dict) -> None:
        """Sentinel trip: write the tenant's repro bundle from its last
        healthy lane state and drop it from the harvest (its lane keeps
        computing garbage in future slices — vmapped lanes are
        independent, so co-tenants are untouched and nothing reads the
        dead lane again)."""
        run = self.bucket.runs[i]
        h = run.handle
        detail: dict = {"tenant": run.tenant,
                        "bucket": self.bucket.signature.digest}
        nf = rows.get("health_nonfinite_params")
        if nf is not None and len(nf):
            detail["nonfinite_params_total"] = int(np.asarray(nf[-1]).sum())
        div = rows.get("health_diverged_per_node")
        if div is not None and len(div):
            detail["diverged_nodes"] = int((np.asarray(div[-1]) > 0).sum())
        if self.keep_repro and i in self._healthy:
            rec = FlightRecorder(self.out_dirs[i])
            h.bundle_path = rec.write_bundle(
                self.sim, self._healthy[i], np.asarray(run.key), "sentinel",
                self._healthy_round, first_bad_round=bad_round,
                detail=detail, rounds_recorded=h.rounds_completed)
        self._m["evictions"].labels(cause="sentinel").inc()
        emit_event("tenant_evicted", {
            "tenant": run.tenant,
            "bucket": self.bucket.signature.digest,
            "first_bad_round": bad_round,
            "bundle_path": h.bundle_path,
        })
        self._finalize(i, RunStatus.EVICTED)

    def _fail_all(self, error: Exception, chunk_start: int) -> None:
        """The bucket's compiled program raised: every live tenant fails
        together (one program, one fate), each with an exception bundle
        from its last healthy state. Other BUCKETS are unaffected — the
        service loop keeps driving them."""
        self.live = False
        for i in self._live_lanes():
            run = self.bucket.runs[i]
            h = run.handle
            h.error = repr(error)[:500]
            self._m["evictions"].labels(cause="exception").inc()
            if self.keep_repro and i in self._healthy:
                rec = FlightRecorder(self.out_dirs[i])
                try:
                    h.bundle_path = rec.write_bundle(
                        self.sim, self._healthy[i], np.asarray(run.key),
                        "exception", self._healthy_round,
                        detail={"error": h.error, "tenant": run.tenant},
                        rounds_recorded=h.rounds_completed)
                except Exception:  # bundle is best-effort forensics
                    pass
            self._finalize(i, RunStatus.FAILED)
        emit_event("bucket_failed", {
            "bucket": self.bucket.signature.digest,
            "error": repr(error)[:500],
            "tenants": self.bucket.tenants,
        })

    def summary(self) -> dict:
        out = {
            "bucket": self.bucket.signature.digest,
            "tenants": self.bucket.tenants,
            "size": self.bucket.size,
            "slice_rounds": self.slice_rounds,
            "slices": math.ceil(self.rounds_done / self.slice_rounds),
            "rounds_driven": self.rounds_done,
            "compilation_cache": self._cache_delta
                or self._compute_cache_delta(),
            "signature": self.bucket.signature.summary,
        }
        # jit-cache proof of megabatching: one compiled step program per
        # bucket regardless of tenant count (the acceptance counter).
        try:
            out["init_jit_cache_size"] = int(self._init_fn._cache_size())
        except Exception:
            out["init_jit_cache_size"] = None
        if self._step_compiled is not None \
                and self._step_compiled is not self._step_fn:
            # Stepping went through the AOT-compiled executable (the
            # cost-capture path): ONE step program by construction — the
            # dispatch jit's cache is empty because it was never called.
            out["step_jit_cache_size"] = 1
        else:
            try:
                out["step_jit_cache_size"] = int(
                    self._step_fn._cache_size())
            except Exception:
                out["step_jit_cache_size"] = None
        return out


class GossipService:
    """Gossip-as-a-service front door: build, pack, schedule, report.

    Usage::

        svc = GossipService(out_dir="runs", slice_rounds=25)
        q = RunQueue()
        h1 = q.submit(RunRequest("alice", cfg_a))
        h2 = q.submit(RunRequest("bob", cfg_b))
        summary = svc.serve(q)          # drains everything pending
        h1.report.final("accuracy")     # per-tenant results

    ``slice_rounds`` is the cooperative quantum: buckets advance
    round-robin one slice at a time, so a 10-tenant bucket cannot starve
    a 1-tenant one. ``keep_repro=False`` skips the per-slice host copies
    (faster slicing, but evictions lose their repro bundles).
    """

    def __init__(self, out_dir: str, slice_rounds: int = 25,
                 keep_repro: bool = True, sentinels_default: bool = True,
                 events_jsonl: bool = True,
                 metrics_dir: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 mesh=None, tracing=None, ledger=None):
        # Optional jax.sharding.Mesh: when given, every bucket's
        # megabatch state/data placement is derived from the partition-
        # rule registry (parallel/rules.py) instead of single-device
        # default placement — the multi-chip service path.
        self.mesh = mesh
        self.out_dir = os.path.abspath(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        self.slice_rounds = int(slice_rounds)
        assert self.slice_rounds >= 1
        self.keep_repro = bool(keep_repro)
        self.sentinels_default = bool(sentinels_default)
        self.events_jsonl = bool(events_jsonl)
        self.metrics_dir = (os.path.abspath(metrics_dir)
                            if metrics_dir else None)
        self.registry = registry if registry is not None else get_registry()
        # Host-side span tracing (telemetry.tracing): same resolution
        # contract as GossipSimulator(tracing=...) — None/False off,
        # True = the process-default tracer, or an explicit Tracer.
        # When on, every poll cycle also writes an atomic trace.json
        # next to metrics.json (scripts/service_top.py reads both).
        if tracing is None or tracing is False:
            self.tracer = None
        elif tracing is True:
            self.tracer = _tracing.ensure_tracer()
        else:
            self.tracer = tracing
        # Run ledger (telemetry.ledger): same resolution contract as
        # GossipSimulator(ledger=...) — None consults the
        # GOSSIPY_TPU_LEDGER env var, False off, path/RunLedger
        # explicit. When on, every finalized tenant appends one digest
        # row, making SLO accounting continuous across restarts (a
        # resumed queue appends to the same ledger file).
        from ..telemetry.ledger import resolve_ledger
        self.ledger = resolve_ledger(ledger)

    def run(self, requests: list[RunRequest]) -> dict:
        """Serve a fixed batch of requests (sugar over :meth:`serve`)."""
        q = RunQueue()
        for r in requests:
            q.submit(r)
        return self.serve(q)

    def session(self, queue: RunQueue) -> "ServiceSession":
        """Open an incremental serving session over ``queue`` — the
        arrival-driven face of the service (``scripts/loadgen.py``):
        tenants may be submitted WHILE earlier buckets are mid-flight;
        each :meth:`ServiceSession.poll` packs whatever is newly pending
        into fresh buckets and advances every live bucket one slice."""
        return ServiceSession(self, queue)

    def serve(self, queue: RunQueue) -> dict:
        """Drain everything pending in ``queue``: build each request,
        pack into shape buckets, drive all buckets to completion, write
        per-tenant artifacts plus a ``service_summary.json``. Returns the
        summary dict; per-tenant state lives on the queue's handles.
        (One-shot sugar over :meth:`session` — batch admission, then
        poll to empty.)"""
        session = self.session(queue)
        while session.poll():
            pass
        return session.finish()


class ServiceSession:
    """One incremental serving run: admission, cooperative driving and
    metrics snapshots, decoupled so arrivals can interleave with
    progress. The scheduler's open-loop face:

    - :meth:`poll` — admit whatever the queue holds as QUEUED (build,
      pack, compile — new buckets only; running buckets are untouched),
      then advance every live bucket by ONE cooperative slice. Returns
      True while anything is still live. Writes a fresh registry
      snapshot to the service's ``metrics_dir`` each cycle — the file
      ``scripts/service_top.py`` tails.
    - :meth:`finish` — per-tenant artifacts are already on disk (written
      at each tenant's finalize); this writes ``service_summary.json``
      plus the final metrics snapshot + OpenMetrics export and returns
      the summary dict.

    Queue-wait and time-to-first-round are measured against each
    handle's ``submitted_at``, so a tenant that waited behind running
    buckets carries its real wait, not the batch's."""

    def __init__(self, service: GossipService, queue: RunQueue):
        self.service = service
        self.queue = queue
        self.runtimes: list[_BucketRuntime] = []
        self.t0 = time.time()
        if service.metrics_dir:
            os.makedirs(service.metrics_dir, exist_ok=True)

    # -- admission ---------------------------------------------------------

    def admit_pending(self) -> int:
        """Build + pack every QUEUED handle into new buckets and start
        them. Returns how many tenants were admitted. A spec that fails
        to build FAILS alone, without disturbing anything running."""
        svc = self.service
        built: list[BuiltRun] = []
        for h in self.queue.pending():
            try:
                built.append(build_request(
                    h.request, handle=h,
                    sentinels_default=svc.sentinels_default))
            except Exception as e:
                h.status = RunStatus.FAILED
                h.error = repr(e)[:500]
        if not built:
            return 0
        buckets = pack(built)
        emit_event("service_packed", {
            "tenants": [b.tenant for b in built],
            "buckets": [{"bucket": b.signature.digest,
                         "tenants": b.tenants} for b in buckets],
        })
        new = [_BucketRuntime(b, svc.out_dir, svc.slice_rounds,
                              svc.keep_repro, svc.events_jsonl,
                              registry=svc.registry, mesh=svc.mesh,
                              tracer=svc.tracer, ledger=svc.ledger)
               for b in buckets]
        for rt in new:
            rt.initialize()
        self.runtimes.extend(new)
        return len(built)

    # -- driving -----------------------------------------------------------

    def any_live(self) -> bool:
        return any(rt.live for rt in self.runtimes)

    def poll(self) -> bool:
        """One cooperative cycle: admit arrivals, advance each live
        bucket one slice, refresh the metrics snapshot. Returns True
        while any bucket is still live (callers loop on it)."""
        self.admit_pending()
        for rt in self.runtimes:
            if rt.live:
                rt.step()
        self._write_metrics()
        return self.any_live()

    def _write_metrics(self) -> None:
        if self.service.metrics_dir:
            self.service.registry.save(
                os.path.join(self.service.metrics_dir, "metrics.json"))
            if self.service.tracer is not None:
                # Atomic like metrics.json: a tailing service_top (or a
                # mid-run Perfetto load) never reads a torn trace.
                self.service.tracer.save(
                    os.path.join(self.service.metrics_dir, "trace.json"))

    # -- completion --------------------------------------------------------

    def finish(self) -> dict:
        svc = self.service
        summary = {
            "out_dir": svc.out_dir,
            "wall_seconds": round(time.time() - self.t0, 3),
            "slice_rounds": svc.slice_rounds,
            "n_tenants": len(self.queue.handles()),
            "n_buckets": len(self.runtimes),
            "megabatch_step_programs": len(self.runtimes),
            "compilation_cache": compilation_cache_stats(),
            "buckets": [rt.summary() for rt in self.runtimes],
            "tenants": [h.to_dict() for h in self.queue.handles()],
        }
        path = os.path.join(svc.out_dir, "service_summary.json")
        with open(path, "w") as fh:
            json.dump(summary, fh, indent=2, default=str)
            fh.write("\n")
        summary["summary_path"] = path
        if svc.metrics_dir:
            self._write_metrics()
            om = os.path.join(svc.metrics_dir, "metrics.prom")
            with open(om, "w") as fh:
                fh.write(svc.registry.to_openmetrics())
            summary["metrics_dir"] = svc.metrics_dir
        emit_event("service_done", {
            "n_tenants": summary["n_tenants"],
            "n_buckets": summary["n_buckets"],
            "wall_seconds": summary["wall_seconds"],
        })
        return summary
