"""Shape-packed megabatching: bucket queued runs by compiled-program shape.

The scheduling currency of the service is the COMPILED PROGRAM (the
"Scalable Training of Language Models using JAX pjit and TPUv4" lesson:
compilation is minutes, execution is milliseconds — reuse is everything).
Two runs can share one program exactly when their round programs trace
identically; then a single ``vmap`` over a tenant axis executes both in
one XLA program, the same mechanism ``run_repetitions`` uses for seeds —
extended here to tenants that also differ in data values and fault rates
(``drop_prob``/``online_prob`` become traced per-lane scalars).

What must match — the :class:`ShapeSignature` — is everything the trace
closes over: the config's :meth:`~gossipy_tpu.config.ExperimentConfig.
shape_fields` (model/handler constants, topology spec, protocol, mailbox
geometry knobs, probes/sentinels), plus facts only the BUILT simulator
knows: the derived mailbox slots ``K``, the delay model (which sets the
history-ring depth ``D``), the history wire format and dtypes, the
topology's actual adjacency content (two seeds that somehow built
different graphs must not share a closed-over adjacency), and the stacked
data array shapes/dtypes. What may differ — and rides the tenant axis as
data — is the PRNG seed, the data values, the fault rates, and the
requested round count.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import zlib
from typing import Any, Optional

import jax
import numpy as np

from .spec import RunHandle, RunRequest


@dataclasses.dataclass(frozen=True)
class ShapeSignature:
    """A bucket key: the digest plus the human-readable field dict it
    hashes (stamped into run summaries and per-tenant manifests so
    cross-tenant program sharing is auditable)."""

    digest: str
    summary: dict

    def __str__(self) -> str:
        return self.digest


@dataclasses.dataclass
class BuiltRun:
    """A request built into a live (but not yet compiled) simulator:
    the packer's unit of work. ``sim`` is only EXECUTED when this run is
    its bucket's representative; for co-tenants it exists to prove the
    signature honest (topology content, derived geometry) and to supply
    the tenant's stacked data values."""

    request: RunRequest
    handle: RunHandle
    sim: Any                 # GossipSimulator (or jitted variant)
    key: jax.Array           # root PRNG key (set_seed(cfg.seed))
    signature: ShapeSignature

    @property
    def tenant(self) -> str:
        return self.request.tenant


def _topology_digest(topology: Any) -> str:
    """Content hash of the topology's edge structure (dense adjacency or
    CSR), so two tenants share a program only when the CLOSED-OVER graph
    is byte-identical — the builder-spec fields alone cannot promise
    that."""
    try:
        adj = topology.adjacency
    except AttributeError:  # SparseTopology refuses dense materialization
        adj = None
    if adj is not None:
        payload = np.ascontiguousarray(np.asarray(adj, dtype=np.int8))
    else:
        payload = np.concatenate([
            np.asarray(topology.degrees, dtype=np.int64).ravel(),
            np.asarray(topology.indices, dtype=np.int64).ravel()])
    return f"{zlib.crc32(payload.tobytes()):08x}"


def _data_shapes(data: dict) -> dict:
    """Stacked-data geometry (``sim.data`` holds jnp arrays)."""
    return {k: [list(v.shape), str(v.dtype)]
            for k, v in sorted(data.items())}


def _chaos_shape(sim: Any) -> Optional[dict]:
    """Trace-pinning chaos facts: the FaultSchedule's array shapes plus
    the static config-derived constants the round program closes over
    (component count for the segment reductions, the edge-mask form).
    None for chaos-free simulators — and for engines predating the
    chaos layer (getattr guards keep old pickles/subclasses packable)."""
    if getattr(sim, "chaos", None) is None:
        return None
    from ..simulation.faults import schedule_shape_summary
    return {
        "schedule": schedule_shape_summary(sim.chaos_schedule),
        "n_components": sim._chaos_ncomp,
        "edge_form": sim._chaos_edge_form,
    }


def shape_signature(request: RunRequest, sim: Any) -> ShapeSignature:
    """The megabatch bucket key for a built run (see module doc for what
    it covers). Built-simulator facts are included on top of the config's
    ``shape_fields()`` because several trace constants are DERIVED at
    construction (mailbox slots from the topology's fan-in, metric names
    from the handler) and a config-only key could lie."""
    fields = {
        "config": request.config.shape_fields(),
        "simulator_class": type(sim).__name__,
        "n_nodes": sim.n_nodes,
        "mailbox_slots": sim.K,
        "reply_slots": sim.Kr,
        "max_fires_per_round": sim.F,
        "history_dtype": sim.history_dtype,
        "fused_merge": sim.fused_merge,
        "delay": repr(sim.delay),
        "probes": sim.probes.to_dict() if sim.probes is not None else None,
        "sentinels": (sim.sentinels.to_dict()
                      if sim.sentinels is not None else None),
        "topology": _topology_digest(sim.topology),
        "data_shapes": _data_shapes(sim.data),
        # Cohort geometry: spec.py rejects cohort requests today (the
        # pool loop is host-driven), but the signature still covers it so
        # a future cohort-capable scheduler can never fuse two tenants
        # whose round programs differ in cohort width / peer mode
        # (getattr-guarded like chaos, for pre-cohort engines).
        "cohort": (sim.cohort.to_dict()
                   if getattr(sim, "cohort", None) is not None else None),
        # Chaos: schedule array SHAPES and the static trace facts split
        # buckets; the schedule VALUES are tenant-variable and ride the
        # batch axis (the scheduler rebinds sim.chaos_schedule per lane,
        # like data and the fault rates).
        "chaos_shape": _chaos_shape(sim),
    }
    digest = hashlib.sha1(
        json.dumps(fields, sort_keys=True, default=str).encode()
    ).hexdigest()[:12]
    return ShapeSignature(digest=digest, summary=fields)


def build_request(request: RunRequest, handle: Optional[RunHandle] = None,
                  sentinels_default: bool = True) -> BuiltRun:
    """Build a request into a :class:`BuiltRun`: seed the host RNGs the
    way ``run_experiment`` does (so a tenant's megabatch trajectory is
    the one its solo run would produce), build the simulator + stacked
    data, and compute the shape signature.

    ``sentinels_default=True`` injects ``sentinels=True`` into the
    simulator unless the config says otherwise — eviction-on-trip (the
    service's failure isolation) needs the in-graph ``health_trip`` flag.
    The injection happens on a config COPY and is part of the signature,
    so explicitly-configured tenants bucket apart, as they must.
    """
    from .. import set_seed
    from ..config import build_experiment

    cfg = request.config
    if sentinels_default and "sentinels" not in cfg.simulator_params:
        cfg = dataclasses.replace(
            cfg, simulator_params={**cfg.simulator_params,
                                   "sentinels": True})
        request = dataclasses.replace(request, config=cfg)
    key = set_seed(cfg.seed)
    sim, _ = build_experiment(cfg, request.data)
    if handle is None:
        handle = RunHandle(request=request)
    else:
        handle.request = request
    sig = shape_signature(request, sim)
    handle.bucket = sig.digest
    return BuiltRun(request=request, handle=handle, sim=sim, key=key,
                    signature=sig)


@dataclasses.dataclass
class Bucket:
    """One megabatch: every run in it shares one compiled init program
    and one compiled step program; the tenant axis is the vmap axis."""

    signature: ShapeSignature
    runs: list

    @property
    def size(self) -> int:
        return len(self.runs)

    @property
    def tenants(self) -> list:
        return [r.tenant for r in self.runs]


def pack(built: list) -> list:
    """Group built runs into buckets by shape signature, preserving
    first-seen order (the scheduler round-robins buckets in this order).
    Identical signatures fuse; ANY divergence — population, model,
    mailbox geometry, dtypes, probes/sentinels config, topology content,
    data shapes — splits."""
    by_sig: dict[str, Bucket] = {}
    order: list[str] = []
    for run in built:
        d = run.signature.digest
        if d not in by_sig:
            by_sig[d] = Bucket(signature=run.signature, runs=[])
            order.append(d)
        by_sig[d].runs.append(run)
    return [by_sig[d] for d in order]
