"""Sustained-arrival SLO harness: Poisson arrivals over a spec pool.

The measurement the ROADMAP's always-on-service item names as its "Done"
evidence: *a sustained mixed-shape arrival benchmark (tenants/hour, p99
time-to-first-round)*. This module is the library core behind
``scripts/loadgen.py`` and ``bench.py --service-slo``:

- :func:`default_spec_pool` — a small mixed-shape pool (two program
  shapes, per-tenant seed/fault-rate variation) so arrivals exercise
  both the fuse path (same shape re-packs into a fresh bucket) and the
  split path (different shape, different program);
- :func:`poisson_arrivals` — exponential inter-arrival offsets at a
  target tenants/hour rate (deterministic under ``seed``);
- :func:`run_load` — the open loop: submit each tenant at its arrival
  time while a :class:`~gossipy_tpu.service.scheduler.ServiceSession`
  keeps driving whatever is already running, so queue-wait and
  time-to-first-round are measured against real contention, not a batch
  admission;
- :func:`slo_row` — reduce the finished run + metrics registry to the
  ``service_slo`` bench row: tenants/hour, p50/p99 time-to-first-round
  (exact, over every admitted tenant's recorded TTFR), p99 per-round
  latency (the registry histogram's estimate), with EVERY admitted
  tenant accounted for (``ttfr_missing`` must be empty — CI asserts).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from ..config import ExperimentConfig
from ..telemetry.metrics import MetricsRegistry, get_registry
from .scheduler import GossipService
from .spec import RunQueue, RunRequest, RunStatus


def default_spec_pool(subsample: int = 400, n_rounds: int = 6) -> list:
    """Two bucket shapes' worth of config templates. ``seed`` and
    ``drop_prob`` are TENANT_VARIABLE_FIELDS — tenants generated from
    the same template pack into one megabatch program; the second shape
    (different population) always splits."""
    small = dict(dataset="spambase", subsample=subsample, n_nodes=16,
                 n_rounds=n_rounds, delta=20, batch_size=8,
                 topology_params={"degree": 4})
    wide = dict(dataset="spambase", subsample=subsample, n_nodes=24,
                n_rounds=n_rounds, delta=20, batch_size=8,
                topology_params={"degree": 4})
    return [small, wide]


def poisson_arrivals(n: int, rate_per_hour: float,
                     seed: int = 0) -> np.ndarray:
    """``n`` cumulative arrival offsets (seconds from load start) of a
    Poisson process at ``rate_per_hour``."""
    if rate_per_hour <= 0:
        raise ValueError(f"rate must be positive, got {rate_per_hour}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(3600.0 / rate_per_hour, size=n)
    return np.cumsum(gaps)


def make_requests(pool: Sequence[dict], n_tenants: int,
                  seed: int = 0) -> list:
    """``n_tenants`` requests drawn round-robin over the pool's shapes,
    each with its own seed and a small per-tenant drop_prob jitter (a
    tenant-variable field: same-shape tenants still fuse)."""
    rng = np.random.default_rng(seed + 1)
    reqs = []
    for t in range(n_tenants):
        cfg = dict(pool[t % len(pool)])
        cfg["seed"] = int(seed * 1000 + t)
        cfg.setdefault("drop_prob",
                       round(float(rng.uniform(0.0, 0.1)), 3))
        reqs.append(RunRequest(tenant=f"t{t:03d}-s{t % len(pool)}",
                               config=ExperimentConfig.from_dict(cfg)))
    return reqs


def run_load(out_dir: str, pool: Optional[Sequence[dict]] = None,
             n_tenants: int = 6, rate_per_hour: float = 3600.0,
             seed: int = 0, slice_rounds: int = 3,
             metrics_dir: Optional[str] = None,
             registry: Optional[MetricsRegistry] = None,
             time_scale: float = 1.0, tracing=None,
             ledger=None) -> dict:
    """Run the sustained-arrival load and return ``{"row": service_slo
    bench row, "summary": service summary, "queue": RunQueue}``.

    ``time_scale`` compresses the arrival schedule (0.01 = 100x faster
    than the nominal rate) so a smoke run exercises real interleaving
    without waiting out the nominal inter-arrival gaps; the reported
    ``offered_rate_per_hour`` uses the COMPRESSED schedule, so the row
    stays honest.

    ``tracing`` follows the GossipService contract (None/True/Tracer):
    when on, every arrival lands as an instant marker + queue-depth
    counter on the service's trace timeline, and the session writes
    ``trace.json`` next to ``metrics.json`` each poll cycle.

    ``ledger`` follows the same contract (telemetry.ledger.
    resolve_ledger): when on, every finalized tenant appends a digest
    row — the continuous-across-restarts SLO account.
    """
    reg = registry if registry is not None else get_registry()
    pool = list(pool) if pool is not None else default_spec_pool()
    svc = GossipService(out_dir, slice_rounds=slice_rounds,
                        metrics_dir=metrics_dir, registry=reg,
                        tracing=tracing, ledger=ledger)
    tracer = svc.tracer
    queue = RunQueue()
    session = svc.session(queue)
    requests = make_requests(pool, n_tenants, seed=seed)
    offsets = poisson_arrivals(n_tenants, rate_per_hour, seed=seed) \
        * float(time_scale)

    t0 = time.perf_counter()
    i = 0
    while i < len(requests) or session.any_live() or queue.pending():
        now = time.perf_counter() - t0
        while i < len(requests) and offsets[i] <= now:
            queue.submit(requests[i])
            if tracer is not None:
                tracer.instant("arrival", cat="loadgen",
                               tenant=requests[i].tenant,
                               offset_s=round(float(offsets[i]), 3))
                tracer.counter_event("loadgen.pending",
                                     value=float(len(queue.pending())))
            i += 1
        progressed = session.poll()   # admits + one slice per live bucket
        if not progressed and i < len(requests):
            # Idle until the next arrival; short naps keep the loop
            # responsive without busy-spinning the host.
            time.sleep(min(max(offsets[i] - (time.perf_counter() - t0),
                               0.0), 0.05))
    wall = time.perf_counter() - t0
    summary = session.finish()
    row = slo_row(queue, reg, wall,
                  offered_rate_per_hour=rate_per_hour / max(time_scale,
                                                            1e-12))
    return {"row": row, "summary": summary, "queue": queue}


def slo_row(queue: RunQueue, registry: MetricsRegistry, wall_seconds: float,
            offered_rate_per_hour: Optional[float] = None) -> dict:
    """The ``service_slo`` bench row (bench.py one-line contract shape).

    ``value`` is the realized service throughput in tenants/hour
    (admitted tenants that finished — DONE or EVICTED — per hour of
    wall time). TTFR percentiles are EXACT, computed over every admitted
    tenant's recorded time-to-first-round (the per-tenant gauge values);
    round-latency percentiles come from the registry histogram's
    log-bucket estimator. ``ttfr_missing`` lists any admitted tenant
    WITHOUT a recorded TTFR — the acceptance invariant is that it is
    empty, and callers exit nonzero when it is not."""
    handles = queue.handles()
    admitted = [h for h in handles
                if h.status in (RunStatus.DONE, RunStatus.EVICTED,
                                RunStatus.RUNNING)]
    finished = [h for h in handles
                if h.status in (RunStatus.DONE, RunStatus.EVICTED)]
    failed = [h for h in handles if h.status is RunStatus.FAILED]
    ttfr = [h.first_round_at - h.submitted_at for h in admitted
            if h.first_round_at is not None]
    missing = [h.tenant for h in admitted if h.first_round_at is None]
    hours = max(wall_seconds, 1e-9) / 3600.0
    tph = round(len(finished) / hours, 2)

    def pct(vals, q):
        return (round(float(np.percentile(vals, q)) * 1e3, 3)
                if vals else None)

    snap = registry.snapshot()
    round_hist = snap["metrics"].get("service_round_seconds")
    qwait_hist = snap["metrics"].get("service_queue_wait_seconds")

    def hist_pct(fam, q):
        if fam is None:
            return None
        from ..telemetry.metrics import quantile_from_counts
        counts = None
        for s in fam["series"]:
            c = s["counts"]
            counts = c if counts is None else [a + b
                                               for a, b in zip(counts, c)]
        if counts is None:
            return None
        mins = [s["min"] for s in fam["series"] if s["min"] is not None]
        maxs = [s["max"] for s in fam["series"] if s["max"] is not None]
        est = quantile_from_counts(fam["buckets"], counts, q,
                                   lo=min(mins) if mins else None,
                                   hi=max(maxs) if maxs else None)
        return round(est * 1e3, 3) if est is not None else None

    return {
        "metric": "service_slo",
        "value": tph,
        "unit": "tenants/hour",
        "raw": {
            "tenants_per_hour": tph,
            "offered_rate_per_hour": (round(offered_rate_per_hour, 2)
                                      if offered_rate_per_hour else None),
            "wall_seconds": round(wall_seconds, 3),
            "n_tenants": len(handles),
            "n_admitted": len(admitted),
            "n_done": sum(h.status is RunStatus.DONE for h in handles),
            "n_evicted": sum(h.status is RunStatus.EVICTED
                             for h in handles),
            "n_failed": len(failed),
            "ttfr_p50_ms": pct(ttfr, 50),
            "ttfr_p99_ms": pct(ttfr, 99),
            "ttfr_recorded": len(ttfr),
            "ttfr_missing": missing,
            "round_p50_ms": hist_pct(round_hist, 0.5),
            "round_p99_ms": hist_pct(round_hist, 0.99),
            "queue_wait_p99_ms": hist_pct(qwait_hist, 0.99),
        },
    }
