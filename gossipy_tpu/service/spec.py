"""Run specs, handles and the queue for gossip-as-a-service.

One process, many experiments: a tenant describes a run as a JSON-able
spec (an :class:`~gossipy_tpu.config.ExperimentConfig` plus a tenant name
and an optional round-count override), submits it to a :class:`RunQueue`,
and gets back a :class:`RunHandle` that tracks the run through the
scheduler — queued, running, done, evicted (sentinel trip + flight-
recorder bundle) or failed — and, on completion, carries the tenant's own
:class:`~gossipy_tpu.simulation.report.SimulationReport` and artifact
paths. The packer (:mod:`gossipy_tpu.service.packer`) fuses same-shape
requests into one vmapped megabatch program; the scheduler
(:mod:`gossipy_tpu.service.scheduler`) drives the buckets cooperatively.

Spec format (``RunRequest.from_spec`` / ``scripts/serve.py``)::

    {"tenant": "alice-lr01",
     "config": { ... ExperimentConfig fields ... },
     "n_rounds": 200}          # optional, overrides config.n_rounds

The spec's ``config`` is strict (unknown fields raise, same as
``ExperimentConfig.from_dict``), so a typo'd knob fails at submission,
not after a bucket compiled.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Optional

from ..config import ExperimentConfig


class RunStatus(enum.Enum):
    """Lifecycle of a tenant run inside the service."""

    QUEUED = "queued"      # submitted, not yet packed into a bucket
    RUNNING = "running"    # its bucket is being driven
    DONE = "done"          # requested rounds completed, report final
    EVICTED = "evicted"    # sentinel tripped: bundle written, lane dropped
    FAILED = "failed"      # its bucket's program raised (all co-tenants too)


# Simulator kinds the megabatch scheduler cannot drive: the sequential
# engine is eager host-side Python (nothing to vmap), and PENS switches
# its traced program mid-run via a host-side phase salt, which a single
# bucket-wide scan cannot express. Submit these as solo runs instead.
UNSERVABLE_SIMULATORS = ("sequential", "pens")


@dataclasses.dataclass
class RunRequest:
    """One tenant's run: a declarative config plus service metadata.

    ``data`` optionally overrides the config's dataset with a pre-loaded
    ``(X, y)`` tuple (same contract as
    :func:`gossipy_tpu.config.build_experiment`) — tenants in one bucket
    may carry entirely different data VALUES; shapes are part of the
    packer's signature.
    """

    tenant: str
    config: ExperimentConfig
    n_rounds: Optional[int] = None   # None = config.n_rounds
    data: Optional[tuple] = None     # (X, y) override for build_experiment

    def __post_init__(self):
        if not self.tenant or "/" in self.tenant:
            raise ValueError(
                "tenant name must be a non-empty path-safe string, got "
                f"{self.tenant!r} (it names the artifact directory)")
        if self.config.simulator in UNSERVABLE_SIMULATORS:
            raise ValueError(
                f"simulator {self.config.simulator!r} cannot be served by "
                f"the megabatch scheduler ({', '.join(UNSERVABLE_SIMULATORS)}"
                " are host-phase/eager engines); run it solo via "
                "run_experiment()")
        if self.config.repetitions != 1:
            raise ValueError(
                "service runs are single-seed per tenant (submit one "
                "request per seed — the packer fuses them into one "
                "program anyway); got repetitions="
                f"{self.config.repetitions}")
        if self.config.cohort is not None:
            raise ValueError(
                "cohort mode is a host-driven resident-pool segment loop "
                "(simulation.cohort) — it cannot ride the megabatch vmap; "
                "run it solo via run_experiment()")

    @property
    def rounds(self) -> int:
        return int(self.n_rounds if self.n_rounds is not None
                   else self.config.n_rounds)

    @staticmethod
    def from_spec(spec: dict) -> "RunRequest":
        """Build a request from the JSON spec format (see module doc)."""
        unknown = set(spec) - {"tenant", "config", "n_rounds"}
        if unknown:
            raise ValueError(f"unknown spec fields: {sorted(unknown)}; "
                             "valid: tenant, config, n_rounds")
        if "tenant" not in spec or "config" not in spec:
            raise ValueError("a run spec needs 'tenant' and 'config'")
        return RunRequest(
            tenant=str(spec["tenant"]),
            config=ExperimentConfig.from_dict(dict(spec["config"])),
            n_rounds=spec.get("n_rounds"),
        )


@dataclasses.dataclass
class RunHandle:
    """Mutable per-tenant tracking record the scheduler updates in place.

    ``report`` is the tenant's own :class:`SimulationReport` (final for
    DONE, truncated at the tripped round for EVICTED, absent for FAILED);
    ``artifacts`` maps artifact names (``report``, ``manifest``,
    ``events``) to written paths; ``bundle_path`` points at the
    flight-recorder repro bundle of an evicted tenant.
    """

    request: RunRequest
    status: RunStatus = RunStatus.QUEUED
    rounds_completed: int = 0
    report: Optional[Any] = None
    bundle_path: Optional[str] = None
    error: Optional[str] = None
    bucket: Optional[str] = None          # signature digest once packed
    artifacts: dict = dataclasses.field(default_factory=dict)
    # SLO clock anchors (telemetry.metrics): stamped at submission /
    # first completed round, the raw material for queue-wait and
    # time-to-first-round. Wall-clock epoch seconds.
    submitted_at: float = dataclasses.field(default_factory=time.time)
    first_round_at: Optional[float] = None

    @property
    def tenant(self) -> str:
        return self.request.tenant

    def to_dict(self) -> dict:
        """JSON-able summary row (the serve CLI's per-tenant output)."""
        return {
            "tenant": self.tenant,
            "status": self.status.value,
            "rounds_requested": self.request.rounds,
            "rounds_completed": self.rounds_completed,
            "bucket": self.bucket,
            "bundle_path": self.bundle_path,
            "error": self.error,
            "artifacts": dict(self.artifacts),
            "submitted_at": self.submitted_at,
            "ttfr_seconds": (
                round(self.first_round_at - self.submitted_at, 6)
                if self.first_round_at is not None else None),
        }


class RunQueue:
    """FIFO submission queue: tenants submit :class:`RunRequest`\\ s, the
    scheduler drains whatever is pending when a service cycle starts.
    Host-side and single-process — the multiplexing happens on the
    device, not here."""

    def __init__(self):
        self._handles: list[RunHandle] = []

    def submit(self, request: RunRequest) -> RunHandle:
        if any(h.tenant == request.tenant for h in self._handles
               if h.status in (RunStatus.QUEUED, RunStatus.RUNNING)):
            raise ValueError(f"tenant {request.tenant!r} already has a "
                             "queued or running request")
        handle = RunHandle(request=request)
        self._handles.append(handle)
        return handle

    def pending(self) -> list[RunHandle]:
        return [h for h in self._handles if h.status is RunStatus.QUEUED]

    def handles(self) -> list[RunHandle]:
        return list(self._handles)
