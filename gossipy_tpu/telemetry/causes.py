"""Traced failure-cause accounting for the simulation engines.

The engines have always computed the three ways a message can die — the
send-time drop draw, an offline receiver at delivery, and mailbox slot
overflow — as separate masks (engine.py ``_send_phase`` /
``_deliver_phase`` / ``_scatter_messages``), then summed them into one
``failed`` counter. :class:`FailureCounts` keeps the three per-cause
tallies apart all the way through the scan's accumulators, at the cost of
two extra int32 scalars per round.

Invariant relied on by the report layer and asserted in tests: the causes
are mutually exclusive per message (a dropped message is never scattered,
an overflowed one is never read back, and the offline check only sees
messages that made it into a slot), so
``drop + offline + overflow == failed`` holds bit-for-bit per round.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp

# Canonical cause ordering — report dicts, JSONL rows and event payloads
# all key on these names.
FAILURE_CAUSES = ("drop", "offline", "overflow")


class FailureCounts(NamedTuple):
    """Per-cause failed-message counters (int32 scalars under trace).

    - ``drop``: lost to the send-time Bernoulli drop draw (reference
      simul.py:403-407) — includes dropped replies and reaction sends.
    - ``offline``: reached a mailbox slot but the receiver's availability
      draw failed at delivery (simul.py:419-428).
    - ``overflow``: no free slot in the receiver's per-round mailbox cell
      (an engine-only cause: the reference's Python queues are unbounded,
      and so are the sequential engine's).
    """

    drop: Union[jax.Array, int]
    offline: Union[jax.Array, int]
    overflow: Union[jax.Array, int]

    @classmethod
    def zeros(cls) -> "FailureCounts":
        return cls(jnp.int32(0), jnp.int32(0), jnp.int32(0))

    # NamedTuple's inherited ``+`` is tuple concatenation — override with
    # the elementwise sum so accumulator code reads naturally.
    def __add__(self, other: "FailureCounts") -> "FailureCounts":  # type: ignore[override]
        return FailureCounts(self.drop + other.drop,
                             self.offline + other.offline,
                             self.overflow + other.overflow)

    def __radd__(self, other):
        if other == 0:  # support sum([...])
            return self
        return self.__add__(other)

    def total(self):
        """The legacy ``failed`` counter: the exact sum of the causes."""
        return self.drop + self.offline + self.overflow

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in FAILURE_CAUSES}
