"""Traced failure-cause accounting for the simulation engines.

The engines have always computed the three ways a message can die — the
send-time drop draw, an offline receiver at delivery, and mailbox slot
overflow — as separate masks (engine.py ``_send_phase`` /
``_deliver_phase`` / ``_scatter_messages``), then summed them into one
``failed`` counter. :class:`FailureCounts` keeps the three per-cause
tallies apart all the way through the scan's accumulators, at the cost of
two extra int32 scalars per round.

Invariant relied on by the report layer and asserted in tests: the causes
are mutually exclusive per message (a dropped message is never scattered,
an overflowed one is never read back, and the offline check only sees
messages that made it into a slot), so
``drop + offline + overflow == failed`` holds bit-for-bit per round.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp

# Canonical cause ordering — report dicts, JSONL rows and event payloads
# all key on these names. The scheduled-fault ``"chaos"`` cause
# (simulation.faults) is ADDITIVE on top: it appears in cause
# breakdowns only when a run was configured with ``chaos=``, so
# chaos-free reports keep exactly these three keys.
FAILURE_CAUSES = ("drop", "offline", "overflow")
CHAOS_CAUSE = "chaos"


class FailureCounts(NamedTuple):
    """Per-cause failed-message counters (int32 scalars under trace).

    - ``drop``: lost to the send-time Bernoulli drop draw (reference
      simul.py:403-407) — includes dropped replies and reaction sends.
    - ``offline``: reached a mailbox slot but the receiver's availability
      draw failed at delivery (simul.py:419-428).
    - ``overflow``: no free slot in the receiver's per-round mailbox cell
      (an engine-only cause: the reference's Python queues are unbounded,
      and so are the sequential engine's).
    - ``chaos``: reached a mailbox slot but the receiver was FORCED
      offline by a scheduled fault window (simulation.faults). The
      default ``()`` is an EMPTY pytree — chaos-free programs carry no
      fourth counter leaf at all, so their scan carries and HLO are
      byte-identical to builds predating the chaos layer. Engines with
      chaos on seed their accumulators via
      ``FailureCounts.zeros(chaos_on=True)``.
    """

    drop: Union[jax.Array, int]
    offline: Union[jax.Array, int]
    overflow: Union[jax.Array, int]
    chaos: Any = ()

    @classmethod
    def zeros(cls, chaos_on: bool = False) -> "FailureCounts":
        return cls(jnp.int32(0), jnp.int32(0), jnp.int32(0),
                   jnp.int32(0) if chaos_on else ())

    # NamedTuple's inherited ``+`` is tuple concatenation — override with
    # the elementwise sum so accumulator code reads naturally.
    def __add__(self, other: "FailureCounts") -> "FailureCounts":  # type: ignore[override]
        a, b = self.chaos, other.chaos
        if isinstance(a, tuple):
            chaos = b
        elif isinstance(b, tuple):
            chaos = a
        else:
            chaos = a + b
        return FailureCounts(self.drop + other.drop,
                             self.offline + other.offline,
                             self.overflow + other.overflow,
                             chaos)

    def __radd__(self, other):
        if other == 0:  # support sum([...])
            return self
        return self.__add__(other)

    def add_chaos(self, n) -> "FailureCounts":
        """Accumulate ``n`` chaos-caused failures (activates the fourth
        counter if this instance still carries the empty default)."""
        c = n if isinstance(self.chaos, tuple) else self.chaos + n
        return self._replace(chaos=c)

    def total(self):
        """The legacy ``failed`` counter: the exact sum of the causes."""
        t = self.drop + self.offline + self.overflow
        if isinstance(self.chaos, tuple):
            return t
        return t + self.chaos

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in FAILURE_CAUSES}
