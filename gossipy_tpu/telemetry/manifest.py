"""Run manifest: the one JSON record that says what produced a curve.

The pjit-at-scale literature leans on exactly this artifact — a structured
snapshot of configuration + software + hardware emitted once per run — to
make throughput numbers and learning curves attributable after the fact.
:class:`RunManifest` collects, host-side and without touching the device:

- the simulator's configuration snapshot (population, protocol, fault
  rates, mailbox geometry, handler/topology classes, delivery path),
- software versions (jax/jaxlib/flax/optax/numpy) and the git revision,
- the backend, device kind/count and mesh shape (when one is attached),
- the engine's :meth:`~gossipy_tpu.simulation.engine.GossipSimulator.
  memory_budget` output and the measured compile wall-time of the last
  cold ``start()`` call.

``bench.py`` emits one per measured run (stderr + optional file; the
stdout one-line metric contract is untouched).
"""

from __future__ import annotations

import json
import subprocess
import time
from dataclasses import dataclass, field
from typing import Any, Optional

MANIFEST_SCHEMA = 1


def _versions() -> dict:
    out = {}
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy"):
        try:
            out[mod] = __import__(mod).__version__
        except Exception:
            out[mod] = None
    return out


def git_revision(cwd: Optional[str] = None) -> Optional[str]:
    """Short git HEAD of the checkout containing THIS package (or of
    ``cwd`` when given), or None outside a repo / without git. Anchoring
    to the package path keeps the recorded rev meaningful no matter what
    directory the run was launched from."""
    if cwd is None:
        import os
        cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        proc = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                              capture_output=True, text=True, timeout=10,
                              cwd=cwd)
    except (OSError, subprocess.TimeoutExpired):
        return None
    rev = proc.stdout.strip()
    return rev if proc.returncode == 0 and rev else None


def git_dirty(cwd: Optional[str] = None) -> Optional[bool]:
    """Whether the checkout containing this package (or ``cwd``) has
    uncommitted changes; None outside a repo / without git. Together
    with :func:`git_revision` this is the ``code_version`` provenance
    block ledger rows and flight-recorder bundles carry — a "regression"
    reproduced from a dirty tree is not pinned to its recorded sha."""
    if cwd is None:
        import os
        cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        proc = subprocess.run(["git", "status", "--porcelain"],
                              capture_output=True, text=True, timeout=10,
                              cwd=cwd)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    return bool(proc.stdout.strip())


def code_version_block() -> Optional[dict]:
    """``{"git_sha", "dirty"}`` or None outside a checkout — the one
    provenance block stamped everywhere manifests are written."""
    sha = git_revision()
    if sha is None:
        return None
    return {"git_sha": sha, "dirty": git_dirty()}


def _backend_info() -> dict:
    import jax
    try:
        devs = jax.devices()
        return {
            "backend": jax.default_backend(),
            "device_kind": devs[0].device_kind if devs else None,
            "device_count": len(devs),
            "process_count": jax.process_count(),
        }
    except Exception as e:  # backend init failure must not kill the record
        return {"backend": None, "error": repr(e)[:200]}


def _config_snapshot(sim: Any) -> dict:
    """Best-effort config dict from a simulator's public attributes.

    Reads via ``getattr`` so every engine subclass (variants, the
    sequential engine) produces a snapshot without implementing anything;
    absent knobs are simply omitted.
    """
    snap: dict = {"simulator": type(sim).__name__}
    handler = getattr(sim, "handler", None)
    if handler is not None:
        snap["handler"] = type(handler).__name__
        mode = getattr(handler, "mode", None)
        if mode is not None:
            snap["create_model_mode"] = getattr(mode, "name", str(mode))
    topo = getattr(sim, "topology", None)
    if topo is not None:
        snap["topology"] = type(topo).__name__
    for attr, key in (("n_nodes", "n_nodes"), ("delta", "delta"),
                      ("drop_prob", "drop_prob"),
                      ("online_prob", "online_prob"),
                      ("sampling_eval", "sampling_eval"),
                      ("eval_every", "eval_every"), ("sync", "sync"),
                      ("K", "mailbox_slots"), ("Kr", "reply_slots"),
                      ("F", "max_fires_per_round"),
                      ("fused_merge", "fused_merge"),
                      ("history_dtype", "history_dtype"),
                      ("_compact_cap", "compact_cap")):
        if hasattr(sim, attr):
            snap[key] = getattr(sim, attr)
    proto = getattr(sim, "protocol", None)
    if proto is not None:
        snap["protocol"] = getattr(proto, "name", str(proto))
    delay = getattr(sim, "delay", None)
    if delay is not None:
        snap["delay"] = repr(delay)
    if hasattr(sim, "probes"):
        # The active ProbeConfig (telemetry.probes) or None: which
        # gossip-dynamics probes this run's report/event stream carries.
        probes = sim.probes
        snap["probes"] = probes.to_dict() if probes is not None else None
    if hasattr(sim, "sentinels"):
        # The active SentinelConfig (telemetry.health) or None: which
        # numerics sentinels this run computed in-graph.
        sentinels = sim.sentinels
        snap["sentinels"] = (sentinels.to_dict()
                             if sentinels is not None else None)
    if hasattr(sim, "chaos"):
        # The active ChaosConfig (simulation.faults) or None: the
        # scheduled fault plane this run executed under — what a bundle
        # or report consumer needs to interpret the "chaos" failure
        # cause and the chaos_* recovery vitals.
        chaos = sim.chaos
        snap["chaos"] = chaos.to_dict() if chaos is not None else None
    if hasattr(sim, "perf"):
        # The active PerfConfig (telemetry.cost) or None: whether this
        # run banked program costs / timing (the collected numbers live
        # in the manifest's top-level ``perf`` block, not here).
        perf = sim.perf
        snap["perf"] = perf.to_dict() if perf is not None else None
    if hasattr(sim, "cohort"):
        # The active CohortConfig (simulation.cohort) or None; cohort
        # runs also record the nominal population (config "n_nodes" is
        # the materialized cohort width C there) and the nominal
        # topology class the inner clique-like round world replaced.
        cohort = sim.cohort
        snap["cohort"] = cohort.to_dict() if cohort is not None else None
        if cohort is not None:
            snap["nominal_n"] = getattr(sim, "nominal_n", None)
            nom = getattr(sim, "nominal_topology", None)
            if nom is not None:
                snap["topology"] = type(nom).__name__
    if hasattr(sim, "topology"):
        # The resolved partition-rule table (parallel/rules.py): which
        # placement registry produced this run's shardings — every spec
        # in parallel/ derives from it, so stamping the table makes a
        # sharded run's placement auditable from the manifest alone.
        try:
            from ..parallel.rules import STATE_RULES, rules_table
            snap["partition_rules"] = rules_table(STATE_RULES)
        except Exception:
            snap["partition_rules"] = None
    if hasattr(sim, "metrics_enabled"):
        # Whether this run fed the host-side SLO metrics registry
        # (telemetry.metrics) — the counters themselves live in the
        # process registry / its exported snapshots, not per run.
        snap["metrics"] = bool(sim.metrics_enabled)
    if hasattr(sim, "tracer"):
        # Whether this run recorded a host span timeline
        # (telemetry.tracing) — the trace itself lives in trace.json /
        # the Tracer object; summary totals land in the manifest's
        # top-level ``trace`` block.
        snap["tracing"] = sim.tracer is not None
    if hasattr(sim, "ledger"):
        # Whether this run appended digest rows to a run ledger
        # (telemetry.ledger) — excluded from the ledger's own config
        # fingerprint, like the other host-observability toggles.
        snap["ledger"] = sim.ledger is not None
    return snap


def _mesh_info(sim: Any) -> Optional[dict]:
    mesh = getattr(sim, "mesh", None)
    if mesh is None:
        return None
    try:
        return {"axis_names": list(mesh.axis_names),
                "shape": {str(k): int(v)
                          for k, v in dict(mesh.shape).items()}}
    except Exception:
        return {"repr": repr(mesh)[:200]}


@dataclass
class RunManifest:
    """Immutable run record; build with :meth:`from_simulator`."""

    config: dict
    backend: dict
    versions: dict
    git_rev: Optional[str] = None
    code_version: Optional[dict] = None
    memory_budget: Optional[dict] = None
    mesh: Optional[dict] = None
    compile_seconds: Optional[float] = None
    compilation_cache: Optional[dict] = None
    telemetry_sink: Optional[dict] = None
    perf: Optional[dict] = None
    trace: Optional[dict] = None
    created_at: float = field(default_factory=time.time)
    extra: dict = field(default_factory=dict)
    schema: int = MANIFEST_SCHEMA

    @classmethod
    def from_simulator(cls, sim: Any,
                       compile_seconds: Optional[float] = None,
                       extra: Optional[dict] = None,
                       config_overrides: Optional[dict] = None
                       ) -> "RunManifest":
        """Collect the manifest for ``sim``.

        ``compile_seconds`` defaults to the simulator's recorded
        ``last_compile_seconds`` (the wall time of the most recent cold
        ``start()`` dispatch — tracing + XLA compilation; execution is
        dispatched asynchronously and not included).

        ``config_overrides`` patches entries of the config snapshot AFTER
        collection — the multi-tenant scheduler records each tenant's OWN
        fault rates/seed through the shared bucket simulator (whose
        attributes hold the representative tenant's values), so a
        per-tenant manifest stays attributable to its tenant.
        """
        budget = None
        if hasattr(sim, "memory_budget"):
            try:
                budget = sim.memory_budget()
            except Exception:  # shape-only eval may resist exotic variants
                budget = None
        if compile_seconds is None:
            compile_seconds = getattr(sim, "last_compile_seconds", None)
        try:
            from .. import compilation_cache_stats
            cache_stats = compilation_cache_stats()
        except Exception:
            cache_stats = None
        try:
            from .sink import get_sink
            sink = get_sink()
            sink_stats = {"events_in_ring": len(sink.events()),
                          "dropped_events": sink.dropped_events,
                          "maxlen": sink.maxlen}
        except Exception:
            sink_stats = None
        perf = None
        if getattr(sim, "perf", None) is not None:
            # The performance-observability block (telemetry.cost):
            # banked program costs, the analytic cross-check, last-run
            # timing/MFU. Null-safe on CPU (real FLOPs/bytes, null MFU)
            # and best-effort — perf context must never kill the record.
            try:
                perf = sim.perf_summary()
            except Exception:
                perf = None
        trace = None
        if getattr(sim, "tracer", None) is not None:
            # Critical-path totals of the run's host span timeline
            # (telemetry.tracing.trace_report): host_blocked_ms /
            # device_ms / overlap_frac over the recorded windows.
            # Best-effort — a trace problem must never kill the record.
            try:
                from .tracing import trace_report
                trace = trace_report(sim.tracer.snapshot())["totals"]
            except Exception:
                trace = None
        config = _config_snapshot(sim)
        if config_overrides:
            config.update(config_overrides)
        return cls(
            config=config,
            backend=_backend_info(),
            versions=_versions(),
            git_rev=git_revision(),
            code_version=code_version_block(),
            memory_budget=budget,
            mesh=_mesh_info(sim),
            compile_seconds=compile_seconds,
            compilation_cache=cache_stats,
            telemetry_sink=sink_stats,
            perf=perf,
            trace=trace,
            extra=dict(extra or {}),
        )

    def to_dict(self) -> dict:
        out = {
            "schema": self.schema,
            "created_at": self.created_at,
            "config": self.config,
            "backend": self.backend,
            "versions": self.versions,
            "git_rev": self.git_rev,
            "code_version": self.code_version,
            "memory_budget": self.memory_budget,
            "mesh": self.mesh,
            "compile_seconds": self.compile_seconds,
            "compilation_cache": self.compilation_cache,
            "telemetry_sink": self.telemetry_sink,
            "perf": self.perf,
            "trace": self.trace,
        }
        if self.extra:
            out["extra"] = self.extra
        return _jsonable(out)

    def to_json(self, **kwargs) -> str:
        return json.dumps(self.to_dict(), **kwargs)

    def save(self, path: str) -> str:
        with open(path, "w") as fh:
            fh.write(self.to_json(indent=2) + "\n")
        return path


def _jsonable(obj):
    """Coerce numpy/jax scalars so ``json.dumps`` never chokes on a
    config value; unknown objects fall back to ``repr``."""
    import numpy as np
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    return repr(obj)[:200]
