"""Telemetry: traced diagnostics, phase scopes, run manifests, event sink.

Four host/trace-side pillars the simulation engines thread through
(none of this module imports the engines — the dependency points the
other way, so everything here is importable standalone):

- :mod:`.causes` — :class:`FailureCounts`, the per-cause failed-message
  accumulator carried through the jitted round scan (drop / offline /
  overflow instead of one opaque ``failed`` sum).
- :mod:`.scopes` — ``jax.named_scope`` phase names (:data:`ROUND_PHASES`)
  wrapped around the round program so XProf traces and compiled HLO show
  send / receive-merge / train / eval attribution.
- :mod:`.manifest` — :class:`RunManifest`, the once-per-run JSON record of
  config + versions + hardware + memory budget + compile wall-time.
- :mod:`.sink` — process-wide structured event sink
  (:func:`emit_event` / :func:`get_sink`) that the engine's diagnostics
  (mailbox undersized, eval-memory) report to alongside their warnings.
- :mod:`.probes` — :class:`ProbeConfig` and the traced gossip-dynamics
  probe math (consensus distance, merge staleness, realized mixing) the
  engines compute inside the jitted round loop when ``probes=`` is set.
- :mod:`.health` — :class:`SentinelConfig` and the traced numerics
  sentinels (non-finite counts, divergence flags, saturation
  watermarks) the engines compute when ``sentinels=`` is set, plus the
  anomaly-triggered :class:`FlightRecorder` and its
  :func:`replay_bundle` deterministic-replay counterpart.
- :mod:`.metrics` — the labeled SLO metrics registry
  (Counter/Gauge/Histogram with log-spaced percentile estimation,
  OpenMetrics export, associative cross-process snapshot merge) the
  service scheduler and the engines feed HOST-side only — the tracelint
  ``metrics-in-trace`` rule enforces the same never-in-a-trace contract
  io_callback bodies live under.
- :mod:`.tracing` — the host-side span tracer (:class:`Tracer`,
  :func:`span`): Chrome-trace-event timelines (Perfetto-loadable
  ``trace.json``) of every host segment — cohort sample/gather/compile/
  run/scatter, engine start, service slices, checkpoint writes — with
  banked device-phase child spans bridged from :mod:`.cost`, an
  associative :func:`merge_traces` for multi-process runs, and
  :func:`trace_report`'s critical-path reduction (per-round
  ``host_blocked_ms`` / ``device_ms`` / ``overlap_frac``). Host-only by
  the same contract as metrics: tracing on/off compiles byte-identical
  HLO, and the tracelint ``trace-in-trace`` rule enforces
  never-in-a-trace.
- :mod:`.ledger` — the crash-safe append-only run index
  (:class:`RunLedger`): one fsync'd CRC-framed JSONL file every
  producer (engine ``start()``, service tenant finalize, bench rows,
  ladder rungs, loadgen SLO rows, flight-recorder bundles) appends a
  schema-stamped digest row to via the ``ingest_*`` adapters — run id,
  code version, config fingerprint, headline metrics, failure causes,
  hashed artifact paths. A torn final record (``kill -9`` mid-append)
  is skipped on read and repaired by the next append; ledgers merge
  associatively (:func:`merge_ledgers`, the ``merge_traces`` contract).
  Host-only like metrics/tracing: ledger on/off compiles byte-identical
  HLO and the tracelint ``ledger-in-trace`` rule enforces
  never-in-a-trace. ``scripts/ledger.py`` is the forensics CLI
  (list/show/diff/trend/bisect).
- :mod:`.cost` — :class:`PerfConfig` and the host-side performance
  observability layer (``perf=``): per-compiled-program
  :class:`CostReport` (XLA cost/memory analysis), the analytic
  per-round estimate, MFU against :data:`PEAK_FLOPS`, and per-phase
  time attribution. Never touches the trace — perf on/off compile
  byte-identical HLO.
"""

from .causes import FAILURE_CAUSES, FailureCounts
from .cost import (
    PEAK_FLOPS,
    PERF_STAT_KEYS,
    CostReport,
    PerfConfig,
    analytic_round_cost,
    cost_report_for,
    differential_phase_attribution,
    mfu_estimate,
    peak_flops,
    perf_event_row,
    phase_times_from_trace,
)
from .health import (
    BUNDLE_VERSION,
    HEALTH_STAT_KEYS,
    FlightRecorder,
    HealthCarry,
    SentinelConfig,
    health_event_row,
    health_round_stats,
    localize_first_nonfinite,
    nonfinite_counts,
    nonfinite_total,
    per_node_param_norm,
    replay_bundle,
)
from .ledger import (
    HEADLINE_METRICS,
    LEDGER_ENV,
    LEDGER_SCHEMA,
    RunLedger,
    config_fingerprint,
    ingest_bench_capsule,
    ingest_bundle,
    ingest_ladder,
    ingest_manifest,
    ingest_slo_row,
    ingest_trace_report,
    merge_ledger_files,
    merge_ledgers,
    resolve_ledger,
)
from .manifest import (
    MANIFEST_SCHEMA,
    RunManifest,
    code_version_block,
    git_dirty,
    git_revision,
)
from .metrics import (
    DEFAULT_BUCKETS,
    METRICS_SCHEMA,
    MetricsRegistry,
    get_registry,
    merge_snapshots,
    observe_engine_run,
    quantile_from_counts,
    set_registry,
    snapshot_to_openmetrics,
)
from .probes import (
    PROBE_STAT_KEYS,
    ProbeAccum,
    ProbeConfig,
    consensus_stats,
    param_layer_names,
    probe_event_row,
)
from .scopes import (
    PHASE_EVAL,
    PHASE_RECEIVE_MERGE,
    PHASE_REPLY,
    PHASE_SEND,
    PHASE_TRAIN,
    ROUND_PHASES,
    phase_scope,
    phases_in_text,
    phases_in_trace_dir,
)
from .sink import TelemetryEvent, TelemetrySink, emit_event, get_sink, set_sink
from .tracing import (
    TRACE_SCHEMA,
    SpanHandle,
    Tracer,
    attach_device_spans,
    ensure_tracer,
    get_tracer,
    merge_traces,
    set_tracer,
    span,
    trace_report,
)

__all__ = [
    "FAILURE_CAUSES", "FailureCounts",
    "RunManifest", "MANIFEST_SCHEMA", "git_revision", "git_dirty",
    "code_version_block",
    "RunLedger", "LEDGER_SCHEMA", "LEDGER_ENV", "HEADLINE_METRICS",
    "config_fingerprint", "resolve_ledger",
    "ingest_manifest", "ingest_bench_capsule", "ingest_trace_report",
    "ingest_ladder", "ingest_slo_row", "ingest_bundle",
    "merge_ledgers", "merge_ledger_files",
    "PHASE_SEND", "PHASE_RECEIVE_MERGE", "PHASE_TRAIN", "PHASE_EVAL",
    "PHASE_REPLY", "ROUND_PHASES", "phase_scope", "phases_in_text",
    "phases_in_trace_dir",
    "TelemetryEvent", "TelemetrySink", "emit_event", "get_sink", "set_sink",
    "ProbeConfig", "ProbeAccum", "PROBE_STAT_KEYS", "consensus_stats",
    "param_layer_names", "probe_event_row",
    "SentinelConfig", "HealthCarry", "HEALTH_STAT_KEYS", "BUNDLE_VERSION",
    "FlightRecorder", "health_event_row", "health_round_stats",
    "localize_first_nonfinite", "nonfinite_counts", "nonfinite_total",
    "per_node_param_norm", "replay_bundle",
    "MetricsRegistry", "METRICS_SCHEMA", "DEFAULT_BUCKETS",
    "get_registry", "set_registry", "merge_snapshots",
    "snapshot_to_openmetrics", "quantile_from_counts",
    "observe_engine_run",
    "PerfConfig", "CostReport", "PEAK_FLOPS", "PERF_STAT_KEYS",
    "analytic_round_cost", "cost_report_for",
    "differential_phase_attribution", "mfu_estimate", "peak_flops",
    "perf_event_row", "phase_times_from_trace",
    "Tracer", "SpanHandle", "TRACE_SCHEMA", "span",
    "get_tracer", "set_tracer", "ensure_tracer",
    "attach_device_spans", "merge_traces", "trace_report",
]
