"""Performance observability: cost/memory introspection, MFU, attribution.

The correctness-facing observability stack (causes, probes, sentinels,
chaos vitals) says WHAT a run computed; this module says what it COST.
Four host-side pillars, all opt-in at runtime and — like every opt-in
layer in this repo — strictly HLO-neutral: nothing here ever touches the
traced program, so ``perf=None`` (the default) and ``perf=True`` compile
byte-identical HLO (gate-enforced in ``scripts/hlo_gate.py``).

- **Per-program cost capture** (:class:`CostReport`): when a simulator is
  built with ``perf=``, every round program it compiles goes through the
  AOT path (``jax.jit(...).lower(...).compile()``) and XLA's own
  ``cost_analysis()`` (FLOPs, bytes accessed) and ``memory_analysis()``
  (argument / output / temp / alias / generated-code bytes) are banked at
  compile time. The same capture backs ``bench.py --mfu`` and the
  scale-ladder forensics, so a crash at large N names the failing
  program's memory numbers instead of losing them with the traceback.
- **Analytic cost model** (:func:`analytic_round_cost`): a model-side
  per-round FLOP/byte estimate derived from the configuration — the
  handler's local-update program is counted at the jaxpr level
  (dot/conv dominant terms, :func:`jaxpr_flops`) and composed with the
  engine's merge and eval geometry. CPU runs therefore still produce a
  model-side number, and the two counters cross-check each other
  (``analytic_vs_xla_flops_ratio`` in the ``perf`` manifest block).
- **MFU** (:func:`mfu_estimate` against :data:`PEAK_FLOPS`, the peak
  table ``bench.py`` now consumes from here): per-round measured wall
  time vs the chip's bf16 dense-matmul peak. The FLOP numerator follows
  XLA's counting convention (a ``fori_loop``/``scan`` body is counted
  ONCE regardless of trip count — the deliver loop executes per occupied
  mailbox slot), so the quoted MFU is *conservative*: throughput against
  the canonical counted workload, not a hardware FLOP counter
  (docs/performance.md).
- **Phase attribution** (:func:`differential_phase_attribution` /
  :func:`phase_times_from_trace`): wall time attributed to the
  ``jax.named_scope`` round phases — from an XProf/perfetto trace when
  profiling is on (the parser reduces the dumped trace to per-phase ms),
  or from structural differencing (eval toggled, one epoch isolated) as
  the host-timer fallback. ``scripts/profile_round.py`` is the CLI
  surface.

Like the rest of :mod:`gossipy_tpu.telemetry`, nothing here imports the
engines — the dependency points the other way.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Union

import numpy as np

# Peak dense matmul throughput per chip, by PJRT device_kind. MFU is
# quoted against the bf16 MXU peak (the rate the CNN config's convs run
# at with bf16 compute); fp32 configs on TPU still route through the MXU
# via multi-pass bf16, so the bf16 peak stays the honest denominator.
# (Moved here from bench.py — ONE definition for bench rows, manifests
# and the scale ladder.)
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e: 197 bf16 TFLOP/s per chip
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
}


def peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    """The chip's peak FLOP/s from :data:`PEAK_FLOPS`, or None for
    unknown kinds (CPU hosts, new chips — MFU is then null, never a
    made-up number). ``device_kind`` defaults to the current backend's
    first device."""
    if device_kind is None:
        import jax
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    return PEAK_FLOPS.get(device_kind)


def mfu_estimate(flops_per_round: Optional[float],
                 seconds_per_round: Optional[float],
                 device_kind: Optional[str] = None) -> Optional[float]:
    """Model-FLOPs-utilization: achieved FLOP/s over the chip's peak.
    None whenever any input is unknown (no FLOP count, no timing, no
    peak for this device kind)."""
    if not flops_per_round or not seconds_per_round:
        return None
    peak = peak_flops(device_kind)
    if not peak:
        return None
    return float(flops_per_round / seconds_per_round / peak)


@dataclass(frozen=True)
class PerfConfig:
    """Which performance-observability facilities a simulator runs.

    - ``cost``: capture a :class:`CostReport` (XLA ``cost_analysis`` +
      ``memory_analysis``) for every round program the simulator
      compiles (routes compilation through the AOT path — the compiled
      program is identical, the executable object is just held long
      enough to read its own cost model).
    - ``analytic``: compute the model-side per-round estimate
      (:func:`analytic_round_cost`) and the cross-check ratio for the
      manifest ``perf`` block.
    - ``timing``: per-run wall timing (adds ONE host sync per
      ``start()`` call — not per round) stamped as ``perf_round_ms`` /
      ``perf_mfu_est`` report rows and ``update_perf`` events.
    """

    cost: bool = True
    analytic: bool = True
    timing: bool = True

    @classmethod
    def coerce(cls, perf: Union[None, bool, "PerfConfig"]
               ) -> Optional["PerfConfig"]:
        """Normalize the ``perf=`` constructor argument: ``None``/
        ``False`` → off (None), ``True`` → everything at defaults, a
        :class:`PerfConfig` → itself (None when every facility is
        off)."""
        if perf is None or perf is False:
            return None
        if perf is True:
            return cls()
        if isinstance(perf, cls):
            if not (perf.cost or perf.analytic or perf.timing):
                return None
            return perf
        raise TypeError("perf= expects None, bool or PerfConfig; got "
                        f"{type(perf).__name__}")

    def to_dict(self) -> dict:
        return {"cost": self.cost, "analytic": self.analytic,
                "timing": self.timing}


@dataclass
class CostReport:
    """XLA's own account of one compiled program, banked at compile time.

    ``flops`` / ``bytes_accessed`` come from ``cost_analysis()`` (the HLO
    cost model: loop bodies counted once, conds priced at the larger
    branch); the ``*_bytes`` fields from ``memory_analysis()``. Any field
    an older jax or an exotic backend cannot produce is None — a capture
    failure must never take down a compile.
    """

    label: str
    n_rounds: Optional[int] = None
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    extra: dict = field(default_factory=dict)

    @property
    def peak_bytes(self) -> Optional[int]:
        """Approximate execution-time device-memory peak: live arguments
        + outputs + XLA temporaries, minus the aliased (donated) overlap.
        A floor on the true peak (allocator slack excluded), but the
        number that says WHICH program blew up at scale."""
        parts = (self.argument_bytes, self.output_bytes, self.temp_bytes)
        if any(p is None for p in parts):
            return None
        return int(sum(parts) - (self.alias_bytes or 0))

    @classmethod
    def from_compiled(cls, compiled: Any, label: str,
                      n_rounds: Optional[int] = None) -> "CostReport":
        """Read ``cost_analysis()`` + ``memory_analysis()`` off a
        ``jax.stages.Compiled``. Best-effort field by field."""
        cr = cls(label=label, n_rounds=n_rounds)
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):  # older jax returns [dict]
                cost = cost[0]
            f = float(cost.get("flops", float("nan")))
            cr.flops = f if math.isfinite(f) else None
            b = float(cost.get("bytes accessed", float("nan")))
            cr.bytes_accessed = b if math.isfinite(b) else None
        except Exception:
            pass
        try:
            ma = compiled.memory_analysis()
            for attr, key in (("argument_size_in_bytes", "argument_bytes"),
                              ("output_size_in_bytes", "output_bytes"),
                              ("temp_size_in_bytes", "temp_bytes"),
                              ("alias_size_in_bytes", "alias_bytes"),
                              ("generated_code_size_in_bytes",
                               "generated_code_bytes")):
                v = getattr(ma, attr, None)
                if v is not None:
                    setattr(cr, key, int(v))
        except Exception:
            pass
        return cr

    def to_dict(self) -> dict:
        out = {
            "label": self.label,
            "n_rounds": self.n_rounds,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "peak_bytes": self.peak_bytes,
        }
        if self.extra:
            out["extra"] = self.extra
        return out


def cost_report_for(sim, state=None, key=None, n_rounds: int = 1,
                    label: Optional[str] = None) -> Optional[CostReport]:
    """AOT-compile the simulator's ``n_rounds`` round program and read
    its :class:`CostReport` — the shared helper behind ``bench.py``'s
    FLOP counting and the scale ladder's per-rung capture. XLA's HLO
    cost model counts a scan body ONCE regardless of trip count
    (verified: 1-round and 10-round programs report equal flops), so a
    1-round program gives per-round FLOPs directly. Returns None when
    the backend cannot lower/compile AOT."""
    import jax
    if key is None:
        key = jax.random.PRNGKey(42)
    if state is None:
        state = sim.init_nodes(key)
    try:
        compiled = sim.lower_start(state, n_rounds=n_rounds,
                                   key=key).compile()
    except Exception:
        return None
    return CostReport.from_compiled(
        compiled, label or f"{type(sim).__name__}[{n_rounds}r]",
        n_rounds=n_rounds)


# -- analytic cost model ----------------------------------------------------


def jaxpr_flops(jaxpr: Any) -> float:
    """Trace-level FLOP count of a (closed or open) jaxpr: ``dot_general``
    and ``conv_general_dilated`` dominant terms, recursing through
    call/scan/while/cond sub-jaxprs (scan bodies multiply by the trip
    count; while bodies count once; cond prices the LARGER branch —
    matching XLA's convention so the two counters stay comparable).
    Elementwise ops are deliberately excluded: this is a dominant-term
    estimate, not a second HLO cost model."""
    if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr
        jaxpr = jaxpr.jaxpr
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_general_flops(eqn)
            continue
        if name == "conv_general_dilated":
            total += _conv_flops(eqn)
            continue
        p = eqn.params
        if "branches" in p:  # cond / switch: larger branch, like XLA
            total += max((jaxpr_flops(b) for b in p["branches"]),
                         default=0.0)
            continue
        mult = 1.0
        subs = []
        if "jaxpr" in p:
            subs.append(p["jaxpr"])
            if name == "scan":
                mult = float(p.get("length", 1))
        for k in ("call_jaxpr", "body_jaxpr", "cond_jaxpr"):
            if k in p:
                subs.append(p[k])
        for sub in subs:
            total += mult * jaxpr_flops(sub)
    return total


def _dot_general_flops(eqn) -> float:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    batch = float(np.prod([lhs.shape[i] for i in lb], dtype=np.float64)) \
        if lb else 1.0
    contract = float(np.prod([lhs.shape[i] for i in lc],
                             dtype=np.float64)) if lc else 1.0
    m = float(np.prod([lhs.shape[i] for i in range(lhs.ndim)
                       if i not in lb and i not in lc], dtype=np.float64))
    rb_set, rc_set = set(_rb), set(rc)
    n = float(np.prod([rhs.shape[i] for i in range(rhs.ndim)
                       if i not in rb_set and i not in rc_set],
                      dtype=np.float64))
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dn = eqn.params["dimension_numbers"]
    # rhs_spec = (out_feature_dim, in_feature_dim, *spatial); the kernel's
    # in-feature dim is already per-group under feature_group_count.
    o_dim, i_dim, *spatial = dn.rhs_spec
    k_spatial = float(np.prod([rhs.shape[d] for d in spatial],
                              dtype=np.float64)) if spatial else 1.0
    in_feat = float(rhs.shape[i_dim])
    return 2.0 * float(np.prod(out.shape, dtype=np.float64)) \
        * k_spatial * in_feat


def _param_count(params) -> int:
    import jax
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(params))


def analytic_round_cost(sim) -> Optional[dict]:
    """Model-side per-round FLOP/byte estimate for a simulator, derived
    from its configuration: the handler's local-update program is
    counted at the jaxpr level (:func:`jaxpr_flops`, one node's data
    shapes) and composed with the engine's geometry — merge math per
    delivered message, the evaluation passes, the history-ring wire
    traffic.

    Two FLOP figures are reported because XLA's cost model counts the
    deliver ``fori_loop`` body ONCE while it executes per occupied
    mailbox slot:

    - ``flops_per_round`` follows the counted-once convention (ONE
      deliver pass) — directly comparable to a compiled round program's
      ``cost_analysis()["flops"]``;
    - ``flops_per_round_executed`` scales the deliver pass by the
      topology's mean expected fan-in (clipped to the mailbox capacity)
      and amortizes evaluation over ``eval_every`` — the honest
      executed-work estimate behind the conservative-MFU caveat (it can
      sit on either side of the counted figure: more deliver passes,
      fewer eval passes).

    Returns None when the handler resists shape-only tracing (exotic
    variants) — an estimate failure must never take down a run.
    """
    import jax

    try:
        st = jax.eval_shape(sim.handler.init, jax.random.PRNGKey(0))
        P = _param_count(st.params)
        n = sim.n_nodes
        xtr, ytr, mtr = sim._local_data()
        one = tuple(jax.ShapeDtypeStruct(a.shape[1:], a.dtype)
                    for a in (xtr, ytr, mtr))
        key = jax.random.PRNGKey(0)
        upd = jax.make_jaxpr(
            lambda s, d, k: sim.handler.update(s, d, k))(st, one, key)
        train_per_node = jaxpr_flops(upd)
    except Exception:
        return None

    # Merge math per delivered message: a leafwise blend of two param
    # sets plus fp32 widening — ~4 FLOPs per scalar is the dominant term
    # for every in-tree merge variant.
    merge_per_msg = 4.0 * P
    deliver_pass = float(n) * (train_per_node + merge_per_msg)

    # Expected occupied mailbox slots per round (mean expected fan-in
    # under the topology, clipped into [1, K]): the executed-work
    # multiplier the counted-once convention drops.
    K = int(getattr(sim, "K", 1))
    try:
        lam_mean = float(np.mean(sim._lam_vector()))
    except Exception:
        lam_mean = 1.0
    passes_exec = min(max(lam_mean, 1.0), float(max(K, 1)))

    # Evaluation: forward passes over the configured test sets, counted
    # from the handler's own evaluate program on the real shapes.
    eval_flops = 0.0
    try:
        n_eval_nodes = (sim._n_eval_nodes()
                        if getattr(sim, "sampling_eval", 0) > 0 else n)
    except Exception:
        n_eval_nodes = n
    import jax.numpy as jnp
    for want, keys in ((getattr(sim, "has_local_test", False),
                        ("xte", "yte", "mte")),
                       (getattr(sim, "has_global_eval", False),
                        ("x_eval", "y_eval", None))):
        if not want:
            continue
        try:
            x = sim.data[keys[0]]
            y = sim.data[keys[1]]
            if keys[2] is not None:  # per-node local test shards
                x, y = x[0], y[0]
                m = sim.data[keys[2]][0]
            else:
                m = jnp.ones(x.shape[0], jnp.float32)
            d = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                      for a in (x, y, m))
            ev = jax.make_jaxpr(
                lambda s, dd: sim.handler.evaluate(s, dd))(st, d)
            eval_flops += n_eval_nodes * jaxpr_flops(ev)
        except Exception:
            continue

    # Counted convention: XLA prices the eval_every lax.cond at its
    # LARGER branch, so the comparable figure carries the FULL eval pass
    # every round; the executed estimate amortizes it over eval_every
    # (and scales the deliver pass by expected occupancy) — the two can
    # land on either side of each other, which is exactly the honesty
    # the caveat documents.
    eval_every = float(getattr(sim, "eval_every", 1) or 1)
    flops_counted = deliver_pass + eval_flops
    flops_executed = deliver_pass * passes_exec + eval_flops / eval_every

    # Bytes per round, dominant terms: the history-ring gather traffic
    # (one wire message per expected delivery), params read+write, and
    # one epoch's training-data read.
    bytes_pr = None
    try:
        wire = sim.wire_bytes_per_message()
        epochs = float(getattr(sim.handler, "local_epochs", 1) or 1)
        data_read = epochs * sum(
            float(np.prod(a.shape[1:])) * np.dtype(a.dtype).itemsize
            for a in (xtr,)) * n
        bytes_pr = float(n) * (lam_mean * wire + 2.0 * 4.0 * P) + data_read
    except Exception:
        pass

    return {
        "flops_per_round": flops_counted,
        "flops_per_round_executed": flops_executed,
        "bytes_per_round": bytes_pr,
        "train_flops_per_node": train_per_node,
        "merge_flops_per_message": merge_per_msg,
        "eval_flops_per_round": eval_flops,
        "expected_deliver_passes": passes_exec,
        "param_count": P,
        "note": "jaxpr-level dominant terms (dot/conv); counted-once "
                "convention for flops_per_round, executed estimate "
                "scales the deliver pass by expected fan-in",
    }


# -- per-round perf stats (report schema 6 / update_perf events) ------------

# Per-round perf stat keys the engines attach host-side after a timed
# run (and the report/event layers consume) — same registry discipline
# as PROBE_STAT_KEYS / HEALTH_STAT_KEYS. Host-derived (there is no
# per-round device boundary in a scanned program), so the per-round
# value is the run's amortized ms/round, uniform within one start()
# call; chunked drivers get per-chunk resolution for free.
PERF_STAT_KEYS = (
    "perf_round_ms",
    "perf_mfu_est",
)


def perf_event_row(vals: dict) -> Optional[dict]:
    """The per-round ``update_perf`` observer payload (JSON-able
    scalars) from one round's perf values — absent facilities are simply
    absent keys. Returns None when ``vals`` carries no perf stat."""
    if not vals:
        return None
    row: dict = {}
    if "perf_round_ms" in vals:
        v = float(vals["perf_round_ms"])
        row["round_ms"] = v if math.isfinite(v) else None
    if "perf_mfu_est" in vals:
        v = float(vals["perf_mfu_est"])
        row["mfu_est"] = v if math.isfinite(v) else None
    return row or None


# -- phase attribution ------------------------------------------------------


def differential_phase_attribution(make_sim: Callable[..., Any],
                                   rounds: int,
                                   key=None) -> dict:
    """Host-timer phase attribution by structural differencing — the
    fallback when no profiler trace is available (and the cross-check
    when one is).

    ``make_sim(**overrides)`` must build the simulator, honoring the
    ``eval_every`` and ``local_epochs`` overrides. Three steady-state
    timings are differenced: full round, evaluation structurally off
    (``eval_every`` past the horizon), and a doubled local-epoch count
    (the extra epoch's marginal cost isolates one epoch of training).
    The exchange leg is defined as the remainder, so the three phases
    sum to the full round time EXACTLY by construction — the 5%
    acceptance band in the tests guards the arithmetic, not the noise.
    """
    import jax

    def time_one(**overrides) -> float:
        sim = make_sim(**overrides)
        k = key if key is not None else jax.random.PRNGKey(42)
        state = sim.init_nodes(k)
        s2, _ = sim.start(state, n_rounds=rounds, key=k,
                          donate_state=False)
        jax.block_until_ready(s2.model.params)
        import time as _time
        t0 = _time.perf_counter()
        s3, _ = sim.start(state, n_rounds=rounds, key=k)
        jax.block_until_ready(s3.model.params)
        return (_time.perf_counter() - t0) / rounds * 1e3

    full = time_one()
    no_eval = time_one(eval_every=10 * rounds)
    two_epochs = time_one(eval_every=10 * rounds, local_epochs=2)
    train = two_epochs - no_eval  # one epoch's marginal cost
    return {
        "method": "differential",
        "full_ms": full,
        "phases_ms": {
            "eval": full - no_eval,
            "train": train,
            "exchange_and_overhead": no_eval - train,
        },
        "rounds": rounds,
        "note": "steady-state differencing; at small round counts the "
                "legs carry run-to-run noise and can go slightly "
                "negative",
    }


def hlo_op_phases(hlo_text: str, phases=None) -> dict:
    """Map compiled-HLO instruction names to the round phase named in
    their ``op_name`` metadata (``jax.named_scope`` survives into it).
    Bridges trace events to phases on backends whose JSON trace carries
    bare HLO op names without metadata (the CPU runtime): pass the
    result as ``op_to_phase`` to :func:`phase_times_from_trace`."""
    import re
    if phases is None:
        from .scopes import ROUND_PHASES
        phases = ROUND_PHASES
    pat = re.compile(r"%([\w.\-]+) = .*?op_name=\"([^\"]*)\"")
    out: dict = {}
    for line in hlo_text.splitlines():
        m = pat.search(line)
        if m is None:
            continue
        name, op = m.groups()
        hit = _deepest_phase(op, phases)
        if hit is not None:
            out[name] = hit
    return out


def _deepest_phase(haystack: str, phases) -> Optional[str]:
    """The phase whose scope name appears DEEPEST in a metadata path —
    ``gossipy.train`` nests inside ``gossipy.receive_merge``/``reply``,
    so an op inside the train scope must attribute to train, not to its
    enclosing phase."""
    best, pos = None, -1
    for p in phases:
        i = haystack.rfind(p)
        if i > pos:
            best, pos = p, i
    return best


def phase_times_from_trace(trace_dir: str,
                           phases=None,
                           op_to_phase: Optional[dict] = None
                           ) -> Optional[dict]:
    """Reduce a ``jax.profiler`` trace directory to per-phase
    milliseconds: device-op durations are summed per
    :data:`~gossipy_tpu.telemetry.scopes.ROUND_PHASES` name found in the
    event metadata. Reads the perfetto/chrome JSON traces
    (``*.json.gz`` — request one with ``jax.profiler.trace(dir,
    create_perfetto_trace=True)``; this runtime also writes
    ``*.trace.json.gz``). Events match a phase when the scope name
    appears in their name/args metadata (XProf TPU dumps) or — pass
    ``op_to_phase`` from :func:`hlo_op_phases` — when their bare HLO op
    name maps to a phase through the compiled program's own metadata
    (the CPU runtime's traces). Returns ``{phase: ms}`` for the phases
    seen, or None when no parsable trace / no phase-tagged events exist
    (the caller falls back to
    :func:`differential_phase_attribution`)."""
    import gzip
    import json
    import os

    if phases is None:
        from .scopes import ROUND_PHASES
        phases = ROUND_PHASES

    def one_file(path: str, gz: bool) -> Optional[dict]:
        try:
            if gz:
                with gzip.open(path, "rt") as fh:
                    doc = json.load(fh)
            else:
                with open(path) as fh:
                    doc = json.load(fh)
        except Exception:
            return None
        events = doc.get("traceEvents", doc) if isinstance(doc, dict) \
            else doc
        if not isinstance(events, list):
            return None
        sums = {p: 0.0 for p in phases}
        found = False
        for ev in events:
            if not isinstance(ev, dict) or ev.get("ph") != "X":
                continue
            dur = ev.get("dur")
            if not dur:
                continue
            name = ev.get("name", "")
            hay = name
            args = ev.get("args")
            if isinstance(args, dict):
                hay += " " + " ".join(str(v) for v in args.values())
            hit = _deepest_phase(hay, phases)
            if hit is None and op_to_phase is not None:
                hit = op_to_phase.get(name)
            if hit is not None:
                sums[hit] += float(dur)  # microseconds
                found = True
        if not found:
            return None
        return {p: v / 1e3 for p, v in sums.items() if v > 0.0}

    # ONE file's account only: XProf mirrors the same events into
    # several JSON dumps (perfetto_trace + <host>.trace), and summing
    # across them would double-count every op.
    for root, _, files in os.walk(trace_dir):
        for fname in sorted(files):
            if not (fname.endswith(".json.gz") or fname.endswith(".json")):
                continue
            result = one_file(os.path.join(root, fname),
                              fname.endswith(".gz"))
            if result is not None:
                return result
    return None
