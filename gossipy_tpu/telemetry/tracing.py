"""Host-side span tracing: one timeline for host segments and device time.

The performance layer (:mod:`.cost`) attributes the *device* round phases;
this module makes the *host* side of a run visible on the same timeline —
the cohort ``sample -> gather -> compile -> run -> scatter`` segments, the
engine's ``start()`` compile/run/report phases, the service scheduler's
per-bucket slices and tenant lifecycles, checkpoint and flight-recorder
writes, loadgen arrivals. The output is an atomic ``trace.json`` in Chrome
trace-event format, loadable directly in Perfetto (https://ui.perfetto.dev)
or ``chrome://tracing``; ``scripts/trace_report.py`` reduces it to the
critical-path numbers (per-round ``host_blocked_ms`` / ``device_ms`` /
``overlap_frac``) the streaming-cohort work is judged by.

Design mirrors :mod:`.metrics` deliberately:

- a process-default instance (:func:`get_tracer` / :func:`set_tracer` /
  :func:`ensure_tracer`) plus explicit instances for tests and multi-run
  isolation;
- thread-safe event recording with per-thread tracks (Chrome ``tid`` +
  ``thread_name`` metadata); timestamps are wall-clock-anchored
  ``perf_counter`` microseconds, so traces from different processes line
  up on one timeline;
- an atomic :meth:`Tracer.save` (tmp + rename — a tailing viewer never
  reads a torn file);
- an associative, commutative :func:`merge_traces` over saved snapshots
  (sorted multiset union of events; structural mismatches raise) — the
  multi-process counterpart of ``metrics.merge_snapshots``.

HOST-SIDE ONLY, statically enforced: tracer calls live under the exact
contract io_callback bodies and the metrics registry live under — never
reachable from a traced (jitted) region. The tracelint ``trace-in-trace``
rule flags any call resolving into this module from a traced root, and
the HLO gate's ``engine/tracing-on`` identity pair proves ``tracing=True``
compiles the byte-identical program (like ``perf``/``metrics``, stronger
than the off-identity contract).

Span API::

    from gossipy_tpu.telemetry import tracing

    tr = tracing.Tracer()
    with tr.span("gather", cat="cohort", rows=256):
        ...                               # context manager

    @tr.span("load_shard")
    def load_shard(path): ...             # decorator (fresh span per call)

    with tracing.span("checkpoint.save"):  # process-default tracer;
        ...                                # no-op (but still timed) when
                                           # none is installed
    tr.counter_event("queued", value=3)
    tr.save("trace.json")

Every span handle measures its own wall duration (``sp.duration``,
seconds) even when no tracer is installed — instrumented code reads ONE
timing source whether tracing is on or off, which is what retires the
ad-hoc ``time.perf_counter()`` locals in the cohort driver and the
service slice loop.
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from typing import Any, Callable, Optional

TRACE_SCHEMA = 1

# Reserved Chrome track for bridged device time (real threads map to
# small positive tids; thread_name metadata names them).
DEVICE_TID = 0


# ---------------------------------------------------------------------------
# Span handle (context manager + decorator)


class SpanHandle:
    """One span's lifetime. Always measures wall duration; emits a Chrome
    complete event only when bound to a live tracer.

    Use as a context manager (``with tracer.span("x") as sp: ...`` —
    ``sp.duration`` / ``sp.ts_us`` / ``sp.dur_us`` are readable after the
    block) or as a decorator (``@tracer.span("x")`` — a FRESH span per
    call, so the handle is reusable as a template)."""

    __slots__ = ("_tracer", "_dynamic", "name", "cat", "args",
                 "_t0", "ts_us", "dur_us", "duration")

    def __init__(self, tracer: Optional["Tracer"], name: str,
                 cat: str = "host", dynamic: bool = False,
                 args: Optional[dict] = None):
        self._tracer = tracer
        self._dynamic = dynamic   # resolve the process default at enter
        self.name = name
        self.cat = cat
        self.args = dict(args or {})
        self._t0: Optional[float] = None
        self.ts_us: Optional[float] = None
        self.dur_us: Optional[float] = None
        self.duration: Optional[float] = None   # seconds

    def __enter__(self) -> "SpanHandle":
        if self._dynamic:
            self._tracer = get_tracer()
        tr = self._tracer
        self._t0 = time.perf_counter()
        self.ts_us = tr._now_us() if tr is not None else None
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.duration = time.perf_counter() - self._t0
        self.dur_us = self.duration * 1e6
        tr = self._tracer
        if tr is not None:
            tr.add_complete(self.name, self.ts_us, self.dur_us,
                            cat=self.cat, args=self.args or None)
        return False

    def __call__(self, fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with SpanHandle(self._tracer, self.name, cat=self.cat,
                            dynamic=self._dynamic, args=self.args):
                return fn(*a, **kw)
        return wrapper


# ---------------------------------------------------------------------------
# Tracer


class Tracer:
    """Thread-safe in-memory collector of Chrome trace events.

    Timestamps are microseconds on a wall-clock-anchored monotonic clock:
    ``wall_origin + (perf_counter - perf_origin)`` — perf_counter
    resolution, but comparable across processes, so :func:`merge_traces`
    produces one coherent multi-process timeline."""

    def __init__(self, process_name: Optional[str] = None,
                 pid: Optional[int] = None):
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self.pid = int(pid if pid is not None else os.getpid())
        self.process_name = process_name or f"gossipy_tpu/{self.pid}"
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()
        self._tids: dict[int, int] = {}   # thread ident -> small tid
        self._meta(self.pid, DEVICE_TID, "process_name",
                   {"name": self.process_name})
        self._meta(self.pid, DEVICE_TID, "thread_name", {"name": "device"})

    # -- clock / tracks -------------------------------------------------

    def _now_us(self) -> float:
        return (self._wall0
                + (time.perf_counter() - self._perf0)) * 1e6

    def _meta(self, pid: int, tid: int, name: str, args: dict) -> None:
        with self._lock:
            self._events.append({"ph": "M", "name": name, "pid": pid,
                                 "tid": tid, "ts": 0, "args": args})

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = len(self._tids) + 1   # 0 is the device track
                self._tids[ident] = tid
                self._events.append(
                    {"ph": "M", "name": "thread_name", "pid": self.pid,
                     "tid": tid, "ts": 0,
                     "args": {"name": threading.current_thread().name}})
        return tid

    # -- recording ------------------------------------------------------

    def span(self, name: str, cat: str = "host", **args) -> SpanHandle:
        """A span handle bound to this tracer: context manager or
        decorator. ``args`` land in the event's ``args`` dict."""
        return SpanHandle(self, name, cat=cat, args=args)

    def add_complete(self, name: str, ts_us: float, dur_us: float,
                     cat: str = "host", tid: Optional[int] = None,
                     args: Optional[dict] = None) -> None:
        """Record one explicit ``"X"`` complete event — the bridge used
        to lay already-measured device time onto the device track."""
        ev = {"ph": "X", "name": str(name), "cat": str(cat),
              "ts": float(ts_us), "dur": max(float(dur_us), 0.0),
              "pid": self.pid,
              "tid": self._tid() if tid is None else int(tid)}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)

    def counter_event(self, name: str, value: Optional[float] = None,
                      **series) -> None:
        """A ``"C"`` counter sample (Perfetto renders a counter track).
        Either ``value=`` (single series) or keyword series.

        Deliberately NOT named ``counter``: tracelint resolves
        ``obj.counter(...)`` to every repo method of that name, and the
        metrics registry already owns it — a shared name would cross-fire
        metrics-in-trace/trace-in-trace findings (the ``Gauge.set_value``
        precedent)."""
        vals = dict(series)
        if value is not None:
            vals["value"] = float(value)
        with self._lock:
            self._events.append({"ph": "C", "name": str(name),
                                 "ts": self._now_us(), "pid": self.pid,
                                 "tid": DEVICE_TID,
                                 "args": {k: float(v)
                                          for k, v in vals.items()}})

    def instant(self, name: str, cat: str = "host", **args) -> None:
        """A thread-scoped ``"i"`` instant marker (e.g. an arrival)."""
        ev = {"ph": "i", "s": "t", "name": str(name), "cat": str(cat),
              "ts": self._now_us(), "pid": self.pid, "tid": self._tid()}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)

    def begin_async(self, name: str, aid: str, cat: str = "async",
                    **args) -> None:
        """Open an async span (``"b"``) — lifecycles that cross stack
        frames, like a tenant's admission -> first-round -> finish."""
        self._async("b", name, aid, cat, args)

    def async_instant(self, name: str, aid: str, cat: str = "async",
                      **args) -> None:
        """An instant (``"n"``) inside an open async span."""
        self._async("n", name, aid, cat, args)

    def end_async(self, name: str, aid: str, cat: str = "async",
                  **args) -> None:
        self._async("e", name, aid, cat, args)

    def _async(self, ph: str, name: str, aid: str, cat: str,
               args: dict) -> None:
        ev = {"ph": ph, "name": str(name), "cat": str(cat),
              "id": str(aid), "ts": self._now_us(), "pid": self.pid,
              "tid": self._tid()}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)

    # -- aggregation surface --------------------------------------------

    def clear(self) -> None:
        with self._lock:
            self._events = [e for e in self._events if e["ph"] == "M"]

    def snapshot(self) -> dict:
        """One JSON-able Chrome-trace dict (object form): the unit that
        gets saved, merged across processes, and fed to
        ``scripts/trace_report.py``."""
        with self._lock:
            events = [dict(e) for e in self._events]
        return {"schema": TRACE_SCHEMA,
                "displayTimeUnit": "ms",
                "otherData": {"process_name": self.process_name,
                              "pid": self.pid},
                "traceEvents": sorted(events, key=_event_key)}

    def save(self, path: str) -> str:
        """Atomic snapshot write (tmp + rename), like
        ``MetricsRegistry.save`` — a live viewer never reads a torn
        file. Returns ``path``."""
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.snapshot(), fh)
            fh.write("\n")
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# Process default (the metrics get_registry/set_registry pattern — except
# the default starts ABSENT: tracing is opt-in, None means strictly no
# event recording anywhere)

_TRACER: Optional[Tracer] = None


def get_tracer() -> Optional[Tracer]:
    """The process-default tracer, or None when tracing is off."""
    return _TRACER


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install (or clear, with None) the process default; returns the
    previous one so tests/tools can restore it."""
    global _TRACER
    prev, _TRACER = _TRACER, tracer
    return prev


def ensure_tracer() -> Tracer:
    """The process-default tracer, installing a fresh one if absent —
    what ``GossipSimulator(tracing=True)`` / ``GossipService``
    resolve through, so engine, scheduler, checkpoint and
    flight-recorder spans all land in ONE trace."""
    global _TRACER
    if _TRACER is None:
        _TRACER = Tracer()
    return _TRACER


def span(name: str, cat: str = "host",
         tracer: Any = "__default__", **args) -> SpanHandle:
    """Module-level span. With ``tracer=`` explicit (a Tracer or None)
    the handle binds to it; otherwise the PROCESS DEFAULT is resolved at
    enter time (so instrumentation in checkpoint/health sees a tracer
    installed after import). Always measures ``sp.duration``, emits only
    when a tracer is live."""
    if tracer == "__default__":
        return SpanHandle(None, name, cat=cat, dynamic=True, args=args)
    return SpanHandle(tracer, name, cat=cat, args=args)


# ---------------------------------------------------------------------------
# Device-time bridge


def attach_device_spans(tracer: Optional[Tracer], ts_us: float,
                        dur_us: float, phase_ms: Optional[dict] = None,
                        args: Optional[dict] = None) -> None:
    """Lay device time onto the device track under a host run window.

    ``phase_ms`` is the banked per-phase attribution ({phase: ms} from
    ``telemetry.cost.phase_times_from_trace`` or
    ``differential_phase_attribution``): phases are scaled to tile the
    ``[ts_us, ts_us + dur_us]`` window proportionally, end to end, as
    ``device.<phase>`` child spans. Without attribution the window gets
    one ``device.execute`` span — the host-observed execution wait is
    then the device-time proxy ``trace_report`` reduces against."""
    if tracer is None or dur_us <= 0:
        return
    phases = {k: float(v) for k, v in (phase_ms or {}).items()
              if v is not None and float(v) > 0.0}
    if not phases:
        tracer.add_complete("device.execute", ts_us, dur_us,
                            cat="device", tid=DEVICE_TID, args=args)
        return
    total = sum(phases.values())
    t = ts_us
    for phase, ms in phases.items():
        d = dur_us * (ms / total)
        pa = {"attributed_ms": round(ms, 3)}
        if args:
            pa.update(args)
        tracer.add_complete(f"device.{phase.split('.')[-1]}", t, d,
                            cat="device", tid=DEVICE_TID, args=pa)
        t += d


# ---------------------------------------------------------------------------
# Snapshot algebra (pure dict -> dict; the multi-process merge currency)


def _event_key(ev: dict) -> tuple:
    # Total, deterministic order: metadata first (ts 0), then by time;
    # the serialized tiebreak makes the sort independent of input order,
    # which is what makes merge_traces associative AND commutative.
    return (0 if ev.get("ph") == "M" else 1, ev.get("ts", 0.0),
            ev.get("pid", 0), ev.get("tid", 0), ev.get("ph", ""),
            ev.get("name", ""), json.dumps(ev, sort_keys=True))


def merge_traces(a: dict, b: dict) -> dict:
    """Combine two trace snapshots into one multi-process timeline
    (associative and commutative — fold any number of per-process
    snapshots in any order/grouping and get the same answer, the
    ``metrics.merge_snapshots`` contract). Events are a sorted multiset
    union; timestamps are wall-anchored, so tracks interleave truthfully.
    A schema mismatch raises — drift between pods is a bug, not
    something to paper over."""
    for snap in (a, b):
        if snap.get("schema") != TRACE_SCHEMA:
            raise ValueError(
                f"cannot merge: trace schema {snap.get('schema')!r} != "
                f"{TRACE_SCHEMA}")
    events = [json.loads(json.dumps(e))
              for e in list(a.get("traceEvents", []))
              + list(b.get("traceEvents", []))]
    pids = sorted({e.get("pid", 0) for e in events})
    return {"schema": TRACE_SCHEMA,
            "displayTimeUnit": "ms",
            "otherData": {"merged_pids": pids},
            "traceEvents": sorted(events, key=_event_key)}


# ---------------------------------------------------------------------------
# Critical-path / overlap analysis (the scripts/trace_report.py core)

# Spans carrying BOTH these args are "run windows": one host-driven
# segment covering args["rounds"] rounds starting after absolute round
# args["round_start"]. Everything inside the window (same pid, interval
# containment) is attributed to it.
_WINDOW_ARGS = ("round_start", "rounds")

# Host spans of this cat are WAITS (host blocked on device dispatch +
# completion), not host work — excluded from the host-busy union so the
# run wait never counts as host-blocked time.
WAIT_CAT = "host.wait"


def _union(intervals: list[tuple]) -> list[tuple]:
    """Merge overlapping [start, end) intervals; returns disjoint sorted."""
    out: list[tuple] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _total(intervals: list[tuple]) -> float:
    return sum(e - s for s, e in intervals)


def _intersect(xs: list[tuple], ys: list[tuple]) -> list[tuple]:
    out, i, j = [], 0, 0
    while i < len(xs) and j < len(ys):
        s = max(xs[i][0], ys[j][0])
        e = min(xs[i][1], ys[j][1])
        if s < e:
            out.append((s, e))
        if xs[i][1] <= ys[j][1]:
            i += 1
        else:
            j += 1
    return out


def _subtract(xs: list[tuple], ys: list[tuple]) -> list[tuple]:
    """xs minus ys (both disjoint sorted)."""
    out = []
    for s, e in xs:
        cur = s
        for ys_s, ys_e in ys:
            if ys_e <= cur or ys_s >= e:
                continue
            if ys_s > cur:
                out.append((cur, ys_s))
            cur = max(cur, ys_e)
            if cur >= e:
                break
        if cur < e:
            out.append((cur, e))
    return out


def trace_report(snapshot: dict) -> dict:
    """Reduce a trace snapshot to the critical-path account.

    For every run window (a span with ``round_start``/``rounds`` args —
    cohort segments, engine start() calls, service slices), host work
    and device time inside the window are reduced to interval unions:

    - ``device_ms`` — union length of ``cat="device"`` spans (bridged
      attribution, or the host-observed execution wait proxy);
    - ``host_busy_ms`` — union length of host spans EXCLUDING waits
      (``cat="host.wait"``) and the window span itself;
    - ``overlap_ms`` — host-busy time overlapping device time: host work
      HIDDEN behind compute (the streaming-cohort A/B currency);
    - ``host_blocked_ms`` — host-busy time NOT overlapped: host work on
      the critical path, the time a streaming driver would recover;
    - ``overlap_frac`` — ``overlap_ms / host_busy_ms`` (0.0 when no host
      work): 0 for today's synchronous drivers, -> 1 when gather/scatter
      hide behind compute;
    - ``unaccounted_ms`` — window wall not covered by device or blocked
      host time (untraced host gaps; small when instrumentation is
      complete — the smoke's self-consistency check
      ``host_blocked + device + unaccounted == wall`` is exact by
      construction, so asserting ``unaccounted`` small IS asserting
      ``host + device + overlap ~= wall``).

    Window totals are distributed evenly over the window's rounds into
    ``per_round`` rows. ``critical_path`` ranks span names by their
    non-overlapped (critical-path) milliseconds across all windows.

    Attribution is robust to windows that OVERLAP in time (the streaming
    cohort pipeline's ``cohort.segment`` windows span [sample start,
    flush end] of concurrent segments): a span carrying a
    ``window=<round_start>`` arg is attributed to the window with that
    ``round_start`` (nearest in time among duplicates); an untagged span
    falls back to its TIGHTEST containing window (exactly one — the old
    convention double-counted spans under nested windows). Overlap and
    blocked time are computed against the pid-wide device union, not
    just the window's own device spans — a gather for segment t+1 hidden
    behind segment t's run is exactly the overlap streaming is buying.
    """
    events = snapshot.get("traceEvents", [])
    spans = [e for e in events if e.get("ph") == "X"]

    def _is_window(e):
        return all(k in e.get("args", {}) for k in _WINDOW_ARGS)

    windows = sorted((e for e in spans if _is_window(e)), key=_event_key)
    others = [e for e in spans if not _is_window(e)]
    # pid-wide device union: the overlap/blocked context. Inside one
    # window host work may hide behind ANOTHER window's device time.
    dev_all: dict = {}
    for e in others:
        if e.get("cat") == "device":
            dev_all.setdefault(e.get("pid"), []).append(
                (e["ts"], e["ts"] + e["dur"]))
    dev_all = {pid: _union(v) for pid, v in dev_all.items()}

    def _dist(w, e):
        return max(w["ts"] - (e["ts"] + e["dur"]),
                   e["ts"] - (w["ts"] + w["dur"]), 0.0)

    assigned: list[list] = [[] for _ in windows]
    widx = {id(w): i for i, w in enumerate(windows)}
    for e in others:
        pid = e.get("pid")
        tag = (e.get("args") or {}).get("window")
        if tag is not None:
            cands = [w for w in windows if w.get("pid") == pid
                     and int(w["args"]["round_start"]) == int(tag)]
            if cands:
                w = min(cands, key=lambda w: _dist(w, e))
                assigned[widx[id(w)]].append(e)
                continue
        cands = [w for w in windows if w.get("pid") == pid
                 and e["ts"] >= w["ts"]
                 and e["ts"] + e["dur"] <= w["ts"] + w["dur"]]
        if cands:
            w = min(cands, key=lambda w: w["dur"])
            assigned[widx[id(w)]].append(e)

    per_round: list[dict] = []
    window_rows: list[dict] = []
    crit: dict[str, float] = {}
    tot = {"wall_ms": 0.0, "host_busy_ms": 0.0, "host_blocked_ms": 0.0,
           "device_ms": 0.0, "overlap_ms": 0.0, "unaccounted_ms": 0.0}

    for w, inner in zip(windows, assigned):
        w0, w1 = w["ts"], w["ts"] + w["dur"]
        dev = _union([(e["ts"], e["ts"] + e["dur"]) for e in inner
                      if e.get("cat") == "device"])
        dev_ctx = dev_all.get(w.get("pid")) or dev
        host_spans = [e for e in inner
                      if e.get("cat") not in ("device", WAIT_CAT)]
        host = _union([(e["ts"], e["ts"] + e["dur"])
                       for e in host_spans])
        overlap = _intersect(host, dev_ctx)
        blocked = _subtract(host, dev_ctx)
        wall_ms = (w1 - w0) / 1e3
        device_ms = _total(dev) / 1e3
        host_busy_ms = _total(host) / 1e3
        overlap_ms = _total(overlap) / 1e3
        host_blocked_ms = _total(blocked) / 1e3
        unaccounted_ms = max(
            wall_ms - device_ms - host_blocked_ms, 0.0)
        row = {
            "name": w.get("name"),
            "round_start": int(w["args"]["round_start"]),
            "rounds": int(w["args"]["rounds"]),
            "wall_ms": round(wall_ms, 3),
            "host_busy_ms": round(host_busy_ms, 3),
            "host_blocked_ms": round(host_blocked_ms, 3),
            "device_ms": round(device_ms, 3),
            "overlap_ms": round(overlap_ms, 3),
            "overlap_frac": round(overlap_ms / host_busy_ms, 4)
            if host_busy_ms > 0 else 0.0,
            "unaccounted_ms": round(unaccounted_ms, 3),
        }
        window_rows.append(row)
        k = max(row["rounds"], 1)
        for i in range(row["rounds"]):
            per_round.append({
                "round": row["round_start"] + i + 1,
                "wall_ms": round(wall_ms / k, 3),
                "host_blocked_ms": round(host_blocked_ms / k, 3),
                "device_ms": round(device_ms / k, 3),
                "overlap_ms": round(overlap_ms / k, 3),
                "overlap_frac": row["overlap_frac"],
            })
        # Critical-path attribution: each host span's non-device-
        # overlapped time (vs the pid-wide device union), plus the
        # device time itself.
        for e in host_spans:
            iv = _subtract([(e["ts"], e["ts"] + e["dur"])], dev_ctx)
            crit[e["name"]] = crit.get(e["name"], 0.0) + _total(iv) / 1e3
        for e in inner:
            if e.get("cat") == "device":
                crit[e["name"]] = crit.get(e["name"], 0.0) + e["dur"] / 1e3
        for key, v in (("wall_ms", wall_ms),
                       ("host_busy_ms", host_busy_ms),
                       ("host_blocked_ms", host_blocked_ms),
                       ("device_ms", device_ms),
                       ("overlap_ms", overlap_ms),
                       ("unaccounted_ms", unaccounted_ms)):
            tot[key] += v

    totals = {k: round(v, 3) for k, v in tot.items()}
    totals["rounds"] = len(per_round)
    totals["host_blocked_frac"] = (
        round(tot["host_blocked_ms"] / tot["wall_ms"], 4)
        if tot["wall_ms"] > 0 else None)
    totals["overlap_frac"] = (
        round(tot["overlap_ms"] / tot["host_busy_ms"], 4)
        if tot["host_busy_ms"] > 0 else 0.0)
    totals["unaccounted_frac"] = (
        round(tot["unaccounted_ms"] / tot["wall_ms"], 4)
        if tot["wall_ms"] > 0 else None)
    crit_rows = [{"name": n, "ms": round(ms, 3),
                  "frac": round(ms / tot["wall_ms"], 4)
                  if tot["wall_ms"] > 0 else None}
                 for n, ms in sorted(crit.items(), key=lambda kv: -kv[1])]
    return {"schema": TRACE_SCHEMA, "n_windows": len(window_rows),
            "totals": totals, "windows": window_rows,
            "per_round": per_round, "critical_path": crit_rows}
