"""Numerics sentinels + anomaly-triggered flight recorder.

The round-5 postmortem of the 50k-node on-TPU crash had to *rank
hypotheses* because the run's traceback was lost (ROUND5_NOTES §2). This
module closes that gap with the two facilities production pjit/TPU
training stacks treat as table stakes:

- **Sentinels** (``GossipSimulator(sentinels=True | SentinelConfig)``):
  per-round numerical-health vitals computed INSIDE the jitted round
  program, the same design discipline as the gossip-dynamics probes —
  ``sentinels=None`` (default) traces the identical HLO:

  * non-finite counts on the params and on the round's param delta,
    per parameter leaf, plus non-finite entries in the round's evaluated
    metric rows;
  * per-node divergence flags — a node whose param L2 norm exceeds a
    configurable multiple of its own EMA — and the population-max norm;
  * the round-delta norm (how far the whole population moved) with its
    running high-water mark, and the run-level mailbox-saturation
    watermark (the traced counterpart of the construction-time
    undersized-mailbox warning);
  * a per-round ``health_trip`` flag: any non-finite count or divergence
    flag fired this round.

- **Flight recorder** (:class:`FlightRecorder`): drives a run in chunks
  and, when a sentinel trips, the run raises, or the watchdog fires,
  writes a self-contained repro bundle — the last healthy
  :class:`~gossipy_tpu.simulation.engine.SimState` checkpoint + PRNG key
  + round index (reusing :mod:`gossipy_tpu.checkpoint`), the
  :class:`~gossipy_tpu.telemetry.RunManifest`, the trailing telemetry
  events from the sink ring, and the sentinel verdict.
  :func:`replay_bundle` (CLI: ``scripts/replay_bundle.py``) restores the
  bundle and replays the offending rounds deterministically, naming the
  first divergent round, parameter leaf and node set, and eagerly
  re-executing the offending round phase by phase (``jax.disable_jit``)
  to localize which engine phase introduced the first non-finite value.

Everything traced here is engine-agnostic pure math (the dependency
points from the engines to this module, like the rest of
:mod:`gossipy_tpu.telemetry`): the jitted engine, the All2All variant
and the sequential high-fidelity engine compute the same vitals through
these helpers, so jitted-vs-sequential health parity is testable.

Bundle directory schema (``BUNDLE_VERSION`` 1)::

    <bundle>/
      checkpoint/      orbax snapshot: {"state": SimState, "key": PRNGKey}
                       (state.round == the last HEALTHY round boundary)
      manifest.json    RunManifest of the recorded simulator
                       (extra.flight_recorder carries the bundle block)
      verdict.json     {"bundle_version", "kind": "sentinel" | "exception"
                        | "watchdog", "chunk_start_round",
                        "first_bad_round" | null, "detail": {...},
                        "perf": {last_round_ms, hbm_peak_bytes,
                                 flops_per_round_xla, compile_count,
                                 mfu_est} | null (perf= runs only)}
      events.jsonl     trailing telemetry events from the sink ring
                       (per-round rows the recorder mirrors in, plus any
                       engine diagnostics), oldest first
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from dataclasses import dataclass
from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .probes import param_layer_names

BUNDLE_VERSION = 1


@dataclass(frozen=True)
class SentinelConfig:
    """Which numerical-health sentinels a simulator computes per round.

    - ``nonfinite``: per-leaf non-finite counts on params / round delta /
      evaluated metrics, and the first mailbox slot whose delivery
      introduced a non-finite value.
    - ``divergence``: per-node param-norm-vs-own-EMA divergence flags.
    - ``saturation``: run-level mailbox occupancy watermark.
    - ``ema_alpha``: EMA coefficient for the per-node norm tracker.
    - ``divergence_factor``: a node trips when its param norm exceeds
      ``divergence_factor * max(ema, norm_floor)``.
    - ``norm_floor``: keeps near-zero EMAs (fresh zero-init models) from
      tripping on the first real update.
    """

    nonfinite: bool = True
    divergence: bool = True
    saturation: bool = True
    ema_alpha: float = 0.1
    divergence_factor: float = 10.0
    norm_floor: float = 1e-6

    def __post_init__(self):
        if not 0.0 < self.ema_alpha <= 1.0:
            raise ValueError("ema_alpha must be in (0, 1]")
        if self.divergence_factor <= 1.0:
            raise ValueError("divergence_factor must be > 1 (a node is "
                             "flagged when its norm EXCEEDS the EMA by "
                             "this factor)")

    @classmethod
    def coerce(cls, sentinels: Union[None, bool, "SentinelConfig"]
               ) -> Optional["SentinelConfig"]:
        """Normalize the ``sentinels=`` constructor argument:
        ``None``/``False`` → off (None), ``True`` → all sentinels at
        defaults, a :class:`SentinelConfig` → itself (None when every
        sentinel is off)."""
        if sentinels is None or sentinels is False:
            return None
        if sentinels is True:
            return cls()
        if isinstance(sentinels, cls):
            if not (sentinels.nonfinite or sentinels.divergence
                    or sentinels.saturation):
                return None
            return sentinels
        raise TypeError("sentinels= expects None, bool or SentinelConfig; "
                        f"got {type(sentinels).__name__}")

    def to_dict(self) -> dict:
        return {"nonfinite": self.nonfinite, "divergence": self.divergence,
                "saturation": self.saturation, "ema_alpha": self.ema_alpha,
                "divergence_factor": self.divergence_factor,
                "norm_floor": self.norm_floor}


class HealthCarry(NamedTuple):
    """Cross-round sentinel state threaded through the round scan's carry
    (the EMA and the high-water marks survive from round to round; the
    per-round vitals land in the stats dict)."""

    norm_ema: jax.Array         # [N] f32: per-node param-norm EMA
    rounds_seen: jax.Array      # i32: rounds folded into the EMA
    delta_hwm: jax.Array        # f32: high-water mark of the round-delta norm
    mailbox_hwm_run: jax.Array  # i32: run-level mailbox occupancy watermark

    @staticmethod
    def zeros(n: int) -> "HealthCarry":
        return HealthCarry(
            norm_ema=jnp.zeros((n,), jnp.float32),
            rounds_seen=jnp.int32(0),
            delta_hwm=jnp.float32(0),
            mailbox_hwm_run=jnp.int32(0),
        )


def nonfinite_counts(tree: Any) -> jax.Array:
    """[L] int32: non-finite scalar count per leaf of ``tree``
    (``tree_leaves`` order; names via
    :func:`~gossipy_tpu.telemetry.probes.param_layer_names`). Computed in
    fp32 regardless of the leaves' storage dtype (integer leaves are
    always finite and count 0)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.stack([
        (~jnp.isfinite(l.astype(jnp.float32))).sum().astype(jnp.int32)
        for l in leaves])


def nonfinite_total(tree: Any) -> jax.Array:
    """Scalar int32: total non-finite count over every leaf of ``tree``."""
    total = jnp.int32(0)
    for l in jax.tree_util.tree_leaves(tree):
        total = total + (~jnp.isfinite(l.astype(jnp.float32))).sum() \
            .astype(jnp.int32)
    return total


def per_node_param_norm(params: Any) -> jax.Array:
    """[N] f32: each node's param L2 norm over stacked params (leaves
    ``[N, ...]``), computed in fp32."""
    leaves = jax.tree_util.tree_leaves(params)
    n = leaves[0].shape[0]
    total = jnp.zeros((n,), jnp.float32)
    for l in leaves:
        x = l.astype(jnp.float32).reshape(n, -1)
        total = total + (x * x).sum(axis=1)
    return jnp.sqrt(total)


# Per-round health stat keys the engines emit (and the report/event
# layers consume), in the fixed order the live io_callback positional
# protocol relies on. ``health_first_bad_slot`` is base-engine only
# (mailbox slot loop); ``health_mix_nonfinite`` is All2All only — both
# layers handle subsets, like the probe keys.
HEALTH_STAT_KEYS = (
    "health_nonfinite_params",
    "health_nonfinite_delta",
    "health_nonfinite_metrics",
    "health_first_bad_slot",
    "health_mix_nonfinite",
    "health_diverged_per_node",
    "health_param_norm_max",
    "health_delta_norm",
    "health_delta_hwm",
    "health_mailbox_hwm_run",
    "health_trip",
)


def health_round_stats(cfg: SentinelConfig, hc: HealthCarry,
                       pre_params: Any, params: Any,
                       local_metrics: Optional[jax.Array],
                       global_metrics: Optional[jax.Array],
                       mailbox_hwm: Optional[jax.Array] = None,
                       ) -> tuple[HealthCarry, dict]:
    """One round's sentinel vitals (pure math; traced by the jitted
    engines, eager in the sequential one).

    ``pre_params``/``params`` are the round-start / round-end stacked
    params; ``local_metrics``/``global_metrics`` the round's evaluated
    metric vectors (an all-NaN row means evaluation was SKIPPED this
    round — the engine's ``eval_every`` contract — and counts zero, so
    the skip marker never trips the sentinel). Returns the advanced
    carry and the round's ``health_*`` stats entries.
    """
    out: dict = {}
    nf_any: Any = False
    div_any: Any = False
    # The round's param delta feeds both the non-finite sentinel and the
    # delta-norm vital — compute it once.
    delta = jax.tree.map(
        lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
        params, pre_params)
    if cfg.nonfinite:
        nf_p = nonfinite_counts(params)
        nf_d = nonfinite_counts(delta)
        out["health_nonfinite_params"] = nf_p
        out["health_nonfinite_delta"] = nf_d
        m = jnp.int32(0)
        for v in (local_metrics, global_metrics):
            if v is None:
                continue
            ran = ~jnp.all(jnp.isnan(v))
            m = m + jnp.where(ran, (~jnp.isfinite(v)).sum(), 0) \
                .astype(jnp.int32)
        out["health_nonfinite_metrics"] = m
        nf_any = (nf_p.sum() + nf_d.sum() + m) > 0

    norms = per_node_param_norm(params)
    if cfg.divergence:
        seeded = hc.rounds_seen > 0
        ema = jnp.where(seeded, hc.norm_ema, norms)
        threshold = cfg.divergence_factor * jnp.maximum(ema, cfg.norm_floor)
        flags = (seeded & (norms > threshold)).astype(jnp.int32)
        # Non-finite norms stay out of the EMA (one NaN round must not
        # poison the baseline the healthy rounds are judged against).
        finite = jnp.isfinite(norms)
        new_ema = jnp.where(
            finite, (1.0 - cfg.ema_alpha) * ema + cfg.ema_alpha * norms, ema)
        hc = hc._replace(norm_ema=new_ema)
        out["health_diverged_per_node"] = flags
        out["health_param_norm_max"] = jnp.max(norms).astype(jnp.float32)
        div_any = flags.sum() > 0

    delta_norm = jnp.sqrt(sum(
        (d * d).sum() for d in jax.tree_util.tree_leaves(delta))
        .astype(jnp.float32))
    new_hwm = jnp.where(jnp.isfinite(delta_norm),
                        jnp.maximum(hc.delta_hwm, delta_norm), hc.delta_hwm)
    out["health_delta_norm"] = delta_norm
    out["health_delta_hwm"] = new_hwm
    hc = hc._replace(delta_hwm=new_hwm, rounds_seen=hc.rounds_seen + 1)

    if cfg.saturation and mailbox_hwm is not None:
        run_hwm = jnp.maximum(hc.mailbox_hwm_run,
                              mailbox_hwm.astype(jnp.int32))
        hc = hc._replace(mailbox_hwm_run=run_hwm)
        out["health_mailbox_hwm_run"] = run_hwm

    trip = jnp.asarray(nf_any) | jnp.asarray(div_any)
    out["health_trip"] = trip.astype(jnp.int32)
    return hc, out


def health_event_row(vals: dict) -> Optional[dict]:
    """The per-round ``update_health`` observer payload (JSON-able
    scalars) from one round's health values — keys for disabled
    sentinels are simply absent. Returns None when ``vals`` carries no
    health stat at all."""
    if not vals:
        return None
    row: dict = {}
    if "health_nonfinite_params" in vals:
        row["nonfinite_params"] = int(
            np.asarray(vals["health_nonfinite_params"]).sum())
        row["nonfinite_delta"] = int(
            np.asarray(vals["health_nonfinite_delta"]).sum())
        row["nonfinite_metrics"] = int(vals["health_nonfinite_metrics"])
    if "health_first_bad_slot" in vals:
        row["first_bad_slot"] = int(vals["health_first_bad_slot"])
    if "health_mix_nonfinite" in vals:
        row["mix_nonfinite"] = int(vals["health_mix_nonfinite"])
    if "health_diverged_per_node" in vals:
        row["diverged"] = int(
            np.asarray(vals["health_diverged_per_node"]).sum())
        row["param_norm_max"] = float(vals["health_param_norm_max"])
    if "health_delta_norm" in vals:
        row["delta_norm"] = float(vals["health_delta_norm"])
        row["delta_hwm"] = float(vals["health_delta_hwm"])
    if "health_mailbox_hwm_run" in vals:
        row["mailbox_hwm_run"] = int(vals["health_mailbox_hwm_run"])
    if "health_trip" in vals:
        row["trip"] = bool(int(vals["health_trip"]))
    return row or None


# -- flight recorder --------------------------------------------------------


def _first_trip_index(report) -> Optional[int]:
    """0-based index of the first tripped round in a report's
    ``health_trip`` array, or None."""
    trips = getattr(report, "health_trip", None)
    if trips is None:
        return None
    idx = np.nonzero(np.asarray(trips) > 0)[0]
    return int(idx[0]) if idx.size else None


class FlightRecorder:
    """Chunked run driver that captures a repro bundle on anomaly.

    Drives ``sim.start`` in ``chunk``-round segments, keeping the
    segment-start state as the last healthy checkpoint (randomness is
    keyed on the absolute round number, so segmentation does not change
    the trajectory). On the first tripped sentinel round, an exception
    out of ``start``, or the per-chunk watchdog deadline, the bundle is
    written (see the module doc for the directory schema) and recording
    stops. The recorder also mirrors each round's event row into the
    process telemetry sink (kind ``"round"``), so the bundle's
    ``events.jsonl`` carries the trailing per-round history; when the
    sink ring's eviction truncated that window, a warning says so once.

    Usage::

        rec = FlightRecorder(out_dir, chunk=50)
        state, reports, bundle = rec.run(sim, state, n_rounds=1000, key=key)
        if bundle is not None:
            ...  # scripts/replay_bundle.py <bundle> localizes the fault
    """

    def __init__(self, out_dir: str, chunk: int = 50,
                 trailing_rounds: int = 64,
                 watchdog_seconds: Optional[float] = None):
        self.out_dir = os.path.abspath(out_dir)
        self.chunk = int(chunk)
        assert self.chunk >= 1
        self.trailing_rounds = int(trailing_rounds)
        self.watchdog_seconds = watchdog_seconds
        self.bundle_path: Optional[str] = None
        self._rounds_recorded = 0
        self._warned_truncated = False

    # -- bundle writing ----------------------------------------------------

    def _write_bundle(self, sim, state, key, kind: str,
                      chunk_start_round: int,
                      first_bad_round: Optional[int] = None,
                      detail: Optional[dict] = None) -> str:
        """Write the repro bundle for ``state`` (the last HEALTHY state,
        at round ``chunk_start_round``). Returns the bundle path; never
        raises past best effort — a recorder failure must not mask the
        run's own failure."""
        from ..checkpoint import save_checkpoint
        from .sink import get_sink
        from .tracing import span

        name = f"bundle_r{chunk_start_round:06d}_{kind}"
        path = os.path.join(self.out_dir, name)
        # Bundle capture on the run's trace timeline (process-default
        # tracer, no-op when tracing is off): a trip that stalls the run
        # writing its post-mortem shows up as host-blocked time with a name.
        with span("flight_recorder.write_bundle", cat="checkpoint",
                  kind=kind, round=int(chunk_start_round)):
            os.makedirs(path, exist_ok=True)
            save_checkpoint(os.path.join(path, "checkpoint"), state, key=key,
                            meta={"bundle_version": BUNDLE_VERSION,
                                  "kind": kind,
                                  "round": int(chunk_start_round)})

            detail = dict(detail or {})
            chaos_cfg = getattr(sim, "chaos", None)
            if chaos_cfg is not None and "chaos_windows" not in detail:
                # A chaos-scenario bundle names the fault windows active at
                # the tripped round AND at the checkpoint round the replay
                # restores from — a heal-induced trip (the common partition
                # failure mode) fires just AFTER its window closes, so the
                # trip round alone can read as fault-free.
                at = (first_bad_round if first_bad_round is not None
                      else chunk_start_round)
                try:
                    detail["chaos_windows"] = chaos_cfg.active_at(at)
                    detail["chaos_windows_at_checkpoint"] = \
                        chaos_cfg.active_at(chunk_start_round)
                    detail["chaos_horizon"] = int(chaos_cfg.horizon)
                except Exception:  # verdict context is best-effort
                    pass
            verdict = {
                "bundle_version": BUNDLE_VERSION,
                "kind": kind,
                "chunk_start_round": int(chunk_start_round),
                "first_bad_round": (int(first_bad_round)
                                    if first_bad_round is not None else None),
                "detail": detail,
                # Performance context of the failure (telemetry.cost): a
                # dead-run bundle carries the last round's cost, not just
                # its numerics. Null when the simulator runs without perf=.
                "perf": _verdict_perf(sim),
            }
            with open(os.path.join(path, "verdict.json"), "w") as fh:
                json.dump(verdict, fh, indent=2)
                fh.write("\n")

            try:
                sim.run_manifest(extra={"flight_recorder": {
                    "bundle_version": BUNDLE_VERSION, "kind": kind,
                    "chunk_start_round": int(chunk_start_round),
                    "trailing_rounds": self.trailing_rounds,
                }}).save(os.path.join(path, "manifest.json"))
            except Exception as e:  # manifest is context, not the evidence
                warnings.warn("flight recorder could not collect the run "
                              f"manifest: {e!r}")

            sink = get_sink()
            events = sink.events()
            round_events = [e for e in events if e.kind == "round"]
            want = min(self.trailing_rounds, self._rounds_recorded)
            if len(round_events) < want and sink.dropped_events > 0 \
                    and not self._warned_truncated:
                self._warned_truncated = True
                warnings.warn(
                    "flight recorder trailing window truncated: the "
                    "telemetry "
                    f"sink ring evicted {sink.dropped_events} events "
                    f"(maxlen {sink.maxlen}); the bundle carries "
                    f"{len(round_events)} of the requested {want} trailing "
                    "rounds. Install a larger TelemetrySink to keep more.")
            with open(os.path.join(path, "events.jsonl"), "w") as fh:
                for ev in events[-max(self.trailing_rounds, 1) * 2:]:
                    fh.write(json.dumps(ev.to_dict()) + "\n")

        self.bundle_path = path
        # Crash bundles are first-class run-ledger rows (verdict inline,
        # bundle + manifest as hashed artifacts) — opt-in via the
        # GOSSIPY_TPU_LEDGER env var, best-effort like the manifest.
        try:
            from .ledger import ingest_bundle, resolve_ledger
            led = resolve_ledger(None)
            if led is not None:
                ingest_bundle(led, path)
        except Exception:
            pass
        return path

    def write_bundle(self, sim, state, key, kind: str,
                     chunk_start_round: int,
                     first_bad_round: Optional[int] = None,
                     detail: Optional[dict] = None,
                     rounds_recorded: Optional[int] = None) -> str:
        """Public bundle capture for EXTERNAL drivers — chunked loops the
        recorder does not own, like the multi-tenant service scheduler
        evicting a tripped tenant. ``state`` must be the last HEALTHY
        state at round ``chunk_start_round`` (host numpy copies are fine —
        :func:`gossipy_tpu.checkpoint.slice_lane` extracts a tenant lane
        from a batched megabatch state). ``rounds_recorded`` tells the
        trailing-window truncation check how many rounds the driver
        mirrored into the sink (0/None disables the warning). Returns the
        bundle path; :meth:`run` callers never need this."""
        if rounds_recorded is not None:
            self._rounds_recorded = int(rounds_recorded)
        return self._write_bundle(sim, state, key, kind, chunk_start_round,
                                  first_bad_round=first_bad_round,
                                  detail=detail)

    # -- driving -----------------------------------------------------------

    def run(self, sim, state, n_rounds: int, key,
            ) -> tuple[Any, list, Optional[str]]:
        """Run ``n_rounds`` rounds in chunks; returns ``(state, reports,
        bundle_path)`` where ``bundle_path`` is None for a clean run. On
        an exception out of ``sim.start`` the bundle is written first,
        then the exception re-raised."""
        assert getattr(sim, "sentinels", None) is not None, \
            "FlightRecorder needs a sentinel-enabled simulator " \
            "(GossipSimulator(sentinels=True))"
        from ..simulation.events import CallbackReceiver
        from .sink import emit_event

        tap = CallbackReceiver(
            lambda row: emit_event("round", row), live=False)
        sim.add_receiver(tap)
        reports: list = []
        bundle: Optional[str] = None
        try:
            done = 0
            while done < n_rounds:
                c = min(self.chunk, n_rounds - done)
                start_state = state
                start_round = int(np.asarray(state.round))
                timer = None
                if self.watchdog_seconds is not None:
                    timer = threading.Timer(
                        self.watchdog_seconds, self._write_bundle,
                        args=(sim, start_state, key, "watchdog",
                              start_round),
                        kwargs={"detail": {
                            "watchdog_seconds": self.watchdog_seconds}})
                    timer.daemon = True
                    timer.start()
                try:
                    state, report = sim.start(state, n_rounds=c, key=key,
                                              donate_state=False)
                    jax.block_until_ready(
                        jax.tree_util.tree_leaves(state.model.params)[0])
                except Exception as e:
                    bundle = self._write_bundle(
                        sim, start_state, key, "exception", start_round,
                        detail={"error": repr(e)[:500]})
                    raise
                finally:
                    if timer is not None:
                        timer.cancel()
                self._rounds_recorded += c
                reports.append(report)
                idx = _first_trip_index(report)
                if idx is not None:
                    bundle = self._write_bundle(
                        sim, start_state, key, "sentinel", start_round,
                        first_bad_round=start_round + idx,
                        detail=_trip_detail(sim, report, idx))
                    break
                done += c
        finally:
            sim.remove_receiver(tap)
        if bundle is None and self.bundle_path is not None:
            bundle = self.bundle_path  # watchdog fired mid-chunk
        return state, reports, bundle


def _verdict_perf(sim) -> Optional[dict]:
    """The bundle verdict's ``perf`` section: last-round ms, HBM peak and
    compile counts from the simulator's perf layer (telemetry.cost) —
    None when the run had ``perf=`` off, and best-effort always (the
    perf context must never mask the failure being recorded)."""
    try:
        summary = (sim.perf_summary()
                   if hasattr(sim, "perf_summary") else None)
    except Exception:
        return None
    if summary is None:
        return None
    last = summary.get("last_run") or {}
    return {
        "last_round_ms": last.get("ms_per_round"),
        "mfu_est": last.get("mfu_est"),
        "hbm_peak_bytes": summary.get("hbm_peak_bytes"),
        "flops_per_round_xla": summary.get("flops_per_round_xla"),
        "compile_count": summary.get("compile_count"),
    }


def _trip_detail(sim, report, idx: int) -> dict:
    """JSON-able summary of the tripped round ``idx`` (0-based within the
    report) for the bundle verdict."""
    detail: dict = {}

    def arr(name):
        v = getattr(report, name, None)
        return None if v is None else np.asarray(v[idx])

    nf = arr("health_nonfinite_params")
    if nf is not None:
        detail["nonfinite_params_total"] = int(nf.sum())
        if nf.sum() > 0:
            names = _layer_names(sim)
            bad = [names[i] if names and i < len(names) else str(i)
                   for i in np.nonzero(nf > 0)[0]]
            detail["nonfinite_leaves"] = bad
    flags = arr("health_diverged_per_node")
    if flags is not None:
        detail["diverged_nodes"] = [int(i) for i in
                                    np.nonzero(flags > 0)[0][:32]]
    for name, key in (("health_delta_norm", "delta_norm"),
                      ("health_param_norm_max", "param_norm_max")):
        v = arr(name)
        if v is not None:
            # Strict JSON: a NaN vital (the usual case on the tripped
            # round) serializes as null, not a bare NaN token.
            detail[key] = float(v) if np.isfinite(v) else None
    return detail


def _layer_names(sim) -> Optional[list]:
    try:
        st = jax.eval_shape(sim.handler.init, jax.random.PRNGKey(0))
        return param_layer_names(st.params)
    except Exception:
        return None


# -- replay -----------------------------------------------------------------


def localize_first_nonfinite(sim, state, key) -> dict:
    """Eagerly re-execute ONE round phase by phase (``jax.disable_jit``)
    from ``state`` and name the first engine phase after which the
    model params carry a non-finite value. Only meaningful for
    simulators using the base round decomposition (variants overriding
    ``_round`` wholesale, e.g. All2All, report phase ``"round"``)."""
    from ..simulation.engine import GossipSimulator
    if type(sim)._round is not GossipSimulator._round:
        return {"phase": "round"}
    r = state.round
    with jax.disable_jit():
        st = sim._pre_send(state, key, r)
        st = sim._snapshot(st, r)
        st, _, _, _ = sim._send_phase(st, key, r)
        phases = [("send", st)]
        st, _, _, _, _ = sim._deliver_phase(st, key, r)
        phases.append(("receive_merge", st))
        st, _, _ = sim._reply_phase(st, key, r)
        phases.append(("reply", st))
    for phase, st in phases:
        if int(np.asarray(nonfinite_total(st.model.params))) > 0:
            return {"phase": phase}
    return {"phase": "eval_or_none"}


def replay_bundle(bundle_dir: str, sim, max_rounds: Optional[int] = None,
                  localize: bool = True) -> dict:
    """Restore a flight-recorder bundle into ``sim`` and replay forward
    deterministically until the first tripped round.

    ``sim`` must be built with the SAME configuration as the recorded
    run (the bundle's ``manifest.json`` ``config`` block says what that
    was) and with sentinels enabled. Rounds are replayed one at a time
    (randomness is keyed on the absolute round number, so the 1-round
    segmentation reproduces the recorded trajectory); each round's
    sentinel verdict is read back on the host, so the first divergent
    round, parameter leaf and node set are named exactly.

    Returns a verdict dict::

        {"first_bad_round": int | None,     # absolute round index
         "trip": "nonfinite" | "divergence" | None,
         "leaf": str | None,                # first non-finite leaf
         "leaf_index": int | None,
         "nodes": [int, ...],               # affected node ids (<= 32)
         "nonfinite_per_leaf": [int, ...],
         "phase": str | None,               # eager per-phase localization
         "start_round": int,
         "matches_recorded": bool | None}   # vs the bundle's verdict
    """
    assert getattr(sim, "sentinels", None) is not None, \
        "replay needs a sentinel-enabled simulator (sentinels=True)"
    from ..checkpoint import restore_checkpoint

    with open(os.path.join(bundle_dir, "verdict.json")) as fh:
        recorded = json.load(fh)

    template = sim.init_nodes(jax.random.PRNGKey(0), local_train=False)
    state, key = restore_checkpoint(
        os.path.join(bundle_dir, "checkpoint"), template)
    if key is None:
        key = jax.random.PRNGKey(42)
    start_round = int(np.asarray(state.round))

    if max_rounds is None:
        if recorded.get("first_bad_round") is not None:
            max_rounds = recorded["first_bad_round"] - start_round + 1
        else:
            max_rounds = 64
    names = _layer_names(sim)

    verdict: dict = {"first_bad_round": None, "trip": None, "leaf": None,
                     "leaf_index": None, "nodes": [],
                     "nonfinite_per_leaf": None, "phase": None,
                     "start_round": start_round, "matches_recorded": None}
    for j in range(max_rounds):
        prev = state
        state, report = sim.start(state, n_rounds=1, key=key,
                                  donate_state=False)
        if _first_trip_index(report) is None:
            continue
        verdict["first_bad_round"] = start_round + j
        counts = np.asarray(nonfinite_counts(state.model.params))
        verdict["nonfinite_per_leaf"] = [int(c) for c in counts]
        if counts.sum() > 0:
            verdict["trip"] = "nonfinite"
            li = int(np.nonzero(counts > 0)[0][0])
            verdict["leaf_index"] = li
            verdict["leaf"] = (names[li] if names and li < len(names)
                               else str(li))
            leaf = jax.tree_util.tree_leaves(state.model.params)[li]
            rows = np.asarray(
                ~np.isfinite(np.asarray(leaf, np.float32).reshape(
                    leaf.shape[0], -1))).any(axis=1)
            verdict["nodes"] = [int(i) for i in np.nonzero(rows)[0][:32]]
            if localize:
                verdict["phase"] = localize_first_nonfinite(
                    sim, prev, key)["phase"]
        else:
            verdict["trip"] = "divergence"
            flags = np.asarray(report.health_diverged_per_node[0])
            verdict["nodes"] = [int(i) for i in np.nonzero(flags > 0)[0][:32]]
        break
    if recorded.get("first_bad_round") is not None:
        verdict["matches_recorded"] = (
            verdict["first_bad_round"] == recorded["first_bad_round"])
    return verdict
