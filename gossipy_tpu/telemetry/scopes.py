"""Named profiler scopes for the round program's phases.

``jax.named_scope`` pushes a name onto JAX's tracing name stack; the name
survives into the compiled HLO's per-op metadata (``op_name``) and into
XProf/TensorBoard traces captured via ``GossipSimulator.start(...,
profile_dir=...)``. With the engine's phases wrapped, a trace shows
``gossipy.send`` / ``gossipy.receive_merge`` / ``gossipy.train`` /
``gossipy.eval`` bands instead of one opaque scan body — direct phase
attribution where ``scripts/profile_round.py`` previously had to
difference whole-run configurations.

The scope names are plain attributes here (not an enum) so host-side
tools — the profiler script's HLO/trace cross-check, tests — can iterate
:data:`ROUND_PHASES` without importing any engine code.
"""

from __future__ import annotations

import jax

PHASE_SEND = "gossipy.send"                    # fire mask, peer sampling, scatter
PHASE_RECEIVE_MERGE = "gossipy.receive_merge"  # mailbox read, gather, merge dispatch
PHASE_TRAIN = "gossipy.train"                  # the vmapped handler call/update pass
PHASE_EVAL = "gossipy.eval"                    # local/global evaluation
PHASE_REPLY = "gossipy.reply"                  # PULL/PUSH_PULL reply drain (elided for PUSH)

# The four phases every protocol's round program contains (PHASE_REPLY is
# structurally absent from PUSH-only programs, so it is not in this list).
ROUND_PHASES = (PHASE_SEND, PHASE_RECEIVE_MERGE, PHASE_TRAIN, PHASE_EVAL)


def phase_scope(name: str):
    """A ``jax.named_scope`` for one round phase (context manager)."""
    return jax.named_scope(name)


def phases_in_text(text: str, phases=ROUND_PHASES) -> list:
    """Which phase names appear in ``text`` (compiled-HLO dump or any
    decoded trace content). Order follows ``phases``."""
    return [p for p in phases if p in text]


def phases_in_trace_dir(trace_dir: str, phases=ROUND_PHASES) -> list:
    """Which phase names appear anywhere in a ``jax.profiler`` trace
    directory. XProf writes protobuf ``.xplane.pb`` (and optionally
    ``.json.gz``) files whose event names embed the HLO op metadata as
    plain bytes, so a substring scan over the raw files is a reliable
    presence check without a protobuf dependency."""
    import gzip
    import os

    needles = {p: p.encode() for p in phases}
    found = set()
    for root, _, files in os.walk(trace_dir):
        for fname in files:
            path = os.path.join(root, fname)
            try:
                with open(path, "rb") as fh:
                    blob = fh.read()
                if fname.endswith(".gz"):
                    blob = gzip.decompress(blob)
            except OSError:
                continue
            for p, needle in needles.items():
                if p not in found and needle in blob:
                    found.add(p)
        if len(found) == len(phases):
            break
    return [p for p in phases if p in found]
