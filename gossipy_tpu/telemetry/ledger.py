"""Run ledger: the crash-safe, append-only index of every run artifact.

The platform emits six kinds of run artifacts (report.json, RunManifest,
events.jsonl, trace.json / trace_report.json, metrics.json/.prom,
BENCH/ladder capsules) but — before this module — no single index:
answering "what changed between the run that hit 44 r/s and this one?"
meant hand-correlating directories. :class:`RunLedger` is that index:
ONE fsync'd, CRC-framed JSONL file where every run appends a compact
schema-stamped digest row — run id, wall timestamp, code version,
config fingerprint, backend/device/degraded, headline metrics
(rounds/sec, ``mfu_est``, ``host_blocked_frac``, ``overlap_frac``,
``stream_speedup``, final accuracy, SLO p50/p99), failure/eviction
causes, and artifact paths with content hashes.

Crash-safety contract (the ROADMAP's "SLO accounting that survives
``kill -9``" phase):

- **Appends are atomic and durable**: one framed line per row, written
  with a single ``write`` on an ``O_APPEND`` descriptor and ``fsync``'d
  before :meth:`RunLedger.append` returns.
- **A torn final record is detected and skipped on read, never fatal**:
  each line carries a CRC32 of its JSON payload (``"%08x %s\\n"``); a
  line that fails the frame, the CRC or the parse is counted as skipped
  and reads return every COMPLETE row.
- **The next append repairs the tail**: before writing, a file that does
  not end in a newline is truncated back to its last complete line — a
  kill mid-append never poisons the file for future writers.

Ingest adapters wire every producer into the ledger with one call each:
:func:`ingest_manifest` (engine ``start()`` and the service scheduler's
per-tenant finalize), :func:`ingest_bench_capsule` (``bench.py`` rows
and driver ``BENCH_r*.json`` capsules), :func:`ingest_trace_report`,
:func:`ingest_ladder` (``scale_ladder.py`` rungs + verdict),
:func:`ingest_slo_row` (``loadgen.py``) and :func:`ingest_bundle`
(FlightRecorder crash bundles — failures are first-class rows with the
verdict inline). The engine/service opt-in follows the tracing
contract (:func:`resolve_ledger`): ``ledger=None`` consults the
``GOSSIPY_TPU_LEDGER`` environment variable, ``False`` is off, a path
or a :class:`RunLedger` is explicit. Everything here is HOST-side only
— ledger on vs off compiles byte-identical HLO (gate pair
``engine/ledger-on`` in :mod:`gossipy_tpu.analysis.hlo`) and the
tracelint ``ledger-in-trace`` rule proves nothing traced can reach it.

:func:`merge_ledgers` is an associative + commutative (and, rows being
unique by run id, idempotent) union keyed like
:func:`~gossipy_tpu.telemetry.tracing.merge_traces` — fold any number
of per-process/per-pod ledgers in any order and get the same fleet-wide
index. ``scripts/ledger.py`` is the forensics CLI on top: ``list`` /
``show`` / ``diff`` / ``trend`` / ``bisect``.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import uuid
import zlib
from typing import Any, Optional, Union

LEDGER_SCHEMA = 1

# Environment opt-in consulted by :func:`resolve_ledger` (the engine's
# ``ledger=None`` default and the service scheduler): point it at a
# ledger path and every run in the process appends its digest row.
LEDGER_ENV = "GOSSIPY_TPU_LEDGER"

# The headline metric keys a row's ``metrics`` block may carry — the
# queryable currency of `ledger list/diff/trend/bisect`. Producers fill
# whatever subset they measure; absent keys mean "not measured", not 0.
HEADLINE_METRICS = (
    "rounds_per_sec", "mfu_est", "host_blocked_frac", "overlap_frac",
    "stream_speedup", "final_accuracy", "slo_p50_ms", "slo_p99_ms",
)

# Config-snapshot keys excluded from the fingerprint: host-side-only
# observability toggles and the (global, config-independent) partition
# rule table. The fingerprint is shape-signature style — it pins what
# the compiled program and the learning dynamics depend on, so a run
# with tracing on fingerprints identically to the same run without.
_FINGERPRINT_EXCLUDE = frozenset(
    {"metrics", "tracing", "perf", "ledger", "partition_rules"})


def config_fingerprint(config: Optional[dict]) -> Optional[str]:
    """Short stable hash of a config snapshot (host-observability knobs
    excluded — see ``_FINGERPRINT_EXCLUDE``): two rows with the same
    fingerprint ran the same program shape + dynamics config."""
    if not isinstance(config, dict):
        return None
    pinned = {k: v for k, v in config.items()
              if k not in _FINGERPRINT_EXCLUDE}
    canon = json.dumps(_jsonable(pinned), sort_keys=True,
                       separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def file_digest(path: str) -> Optional[str]:
    """sha256 of a file's bytes (short form), or None when unreadable —
    artifact rows must never fail because an artifact moved."""
    try:
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()[:16]
    except OSError:
        return None


def artifact_entry(path: str) -> dict:
    """``{"path", "sha256"}`` for one artifact file — the content hash
    makes a ledger row's evidence tamper-evident and lets ``diff``
    notice a report that was rewritten after the row landed."""
    return {"path": os.path.abspath(path), "sha256": file_digest(path)}


def code_version() -> Optional[dict]:
    """``{"git_sha", "dirty"}`` of the checkout containing this package,
    or None outside a repo (null-safe everywhere, like
    :func:`~gossipy_tpu.telemetry.manifest.git_revision`)."""
    from .manifest import code_version_block
    return code_version_block()


def _frame(payload: str) -> bytes:
    return (f"{zlib.crc32(payload.encode('utf-8')) & 0xffffffff:08x} "
            f"{payload}\n").encode("utf-8")


def _parse_frame(raw: bytes) -> Optional[dict]:
    """One framed line -> row dict, or None for anything torn/corrupt
    (bad CRC, bad JSON, bad frame) — skipping is the contract, raising
    is not."""
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError:
        return None
    if len(text) < 10 or text[8] != " ":
        return None
    crc_hex, payload = text[:8], text[9:]
    try:
        if int(crc_hex, 16) != zlib.crc32(payload.encode("utf-8")):
            return None
        row = json.loads(payload)
    except (ValueError, TypeError):
        return None
    return row if isinstance(row, dict) else None


class RunLedger:
    """One append-only CRC-framed JSONL run index (module docstring has
    the crash-safety contract). Cheap to construct — the file is only
    touched by :meth:`append` / :meth:`read`."""

    def __init__(self, path: str):
        self.path = os.path.abspath(os.fspath(path))
        self._lock = threading.Lock()

    # -- writing -----------------------------------------------------------

    def _repair_tail(self) -> None:
        """Truncate a torn final record (no trailing newline) back to the
        last complete line — the ``kill -9`` mid-append repair."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size == 0:
            return
        with open(self.path, "rb+") as fh:
            fh.seek(-1, os.SEEK_END)
            if fh.read(1) == b"\n":
                return
            fh.seek(0)
            data = fh.read()
            fh.truncate(data.rfind(b"\n") + 1)
            fh.flush()
            os.fsync(fh.fileno())

    def append(self, row: dict) -> dict:
        """Append one digest row (schema/run_id/ts stamped when absent);
        repairs a torn tail first, writes one framed line, fsyncs, and
        returns the stamped row."""
        row = dict(row)
        row.setdefault("schema", LEDGER_SCHEMA)
        row.setdefault("run_id", uuid.uuid4().hex[:12])
        row.setdefault("ts", time.time())
        payload = json.dumps(_jsonable(row), sort_keys=True,
                             separators=(",", ":"))
        with self._lock:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._repair_tail()
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            try:
                os.write(fd, _frame(payload))
                os.fsync(fd)
            finally:
                os.close(fd)
        return row

    # -- reading -----------------------------------------------------------

    def read(self) -> dict:
        """``{"rows": [...], "skipped": n}`` — every complete row, in
        file order; torn/corrupt lines are counted, never fatal. A
        missing file is an empty ledger."""
        try:
            with open(self.path, "rb") as fh:
                data = fh.read()
        except OSError:
            return {"rows": [], "skipped": 0}
        rows: list = []
        skipped = 0
        for raw in data.split(b"\n"):
            if not raw.strip():
                continue
            row = _parse_frame(raw)
            if row is None:
                skipped += 1
            else:
                rows.append(row)
        return {"rows": rows, "skipped": skipped}

    def rows(self) -> list:
        return self.read()["rows"]

    def find(self, run_id_prefix: str) -> list:
        """Every row whose run id starts with ``run_id_prefix`` (the CLI
        accepts abbreviated ids, git style)."""
        return [r for r in self.rows()
                if str(r.get("run_id", "")).startswith(run_id_prefix)]


def resolve_ledger(ledger: Union[None, bool, str, RunLedger]
                   ) -> Optional[RunLedger]:
    """The engine/service option contract (same shape as ``tracing=``):
    ``None`` consults ``$GOSSIPY_TPU_LEDGER`` (unset = off), ``False``
    is strictly off, a path string opens that file, a :class:`RunLedger`
    is used as-is."""
    if ledger is False:
        return None
    if ledger is None:
        path = os.environ.get(LEDGER_ENV)
        return RunLedger(path) if path else None
    if isinstance(ledger, RunLedger):
        return ledger
    return RunLedger(os.fspath(ledger))


# ---------------------------------------------------------------------------
# Ingest adapters — one call per producer


def _clean_metrics(metrics: Optional[dict]) -> dict:
    out = {}
    for k, v in (metrics or {}).items():
        if v is None:
            continue
        try:
            f = float(v)
        except (TypeError, ValueError):
            continue
        if f == f:  # drop NaN — "not measured", not a value
            out[k] = f
    return out


def headline_from_manifest(manifest: dict) -> dict:
    """Pull whatever headline metrics a RunManifest dict carries: MFU
    from the perf block, host_blocked/overlap from the trace totals,
    SLO percentiles from a service tenant's ``extra.service.slo``."""
    out: dict = {}
    perf = manifest.get("perf") or {}
    last = perf.get("last_run") or {}
    for src in (last, perf):
        if isinstance(src, dict) and src.get("mfu_est") is not None:
            out.setdefault("mfu_est", src["mfu_est"])
    trace = manifest.get("trace") or {}
    if isinstance(trace, dict):
        out["host_blocked_frac"] = trace.get("host_blocked_frac")
        out["overlap_frac"] = trace.get("overlap_frac")
    slo = ((manifest.get("extra") or {}).get("service") or {}).get("slo")
    if isinstance(slo, dict):
        p50 = slo.get("bucket_round_seconds_p50")
        p99 = slo.get("bucket_round_seconds_p99")
        out["slo_p50_ms"] = p50 * 1000.0 if p50 is not None else None
        out["slo_p99_ms"] = p99 * 1000.0 if p99 is not None else None
    return _clean_metrics(out)


def ingest_manifest(ledger: RunLedger, manifest: Any, *,
                    kind: str = "engine",
                    run_id: Optional[str] = None,
                    metrics: Optional[dict] = None,
                    failure: Optional[dict] = None,
                    artifacts: Optional[dict] = None,
                    experiment: Optional[dict] = None,
                    extra: Optional[dict] = None) -> dict:
    """One digest row from a :class:`~gossipy_tpu.telemetry.RunManifest`
    (instance or dict) — the engine ``start()`` and service per-tenant
    adapter. ``metrics`` merges over what the manifest itself carries;
    ``artifacts`` maps name -> path (hashed here); ``experiment`` is the
    replay-pinned ExperimentConfig dict ``ledger bisect`` re-runs."""
    if hasattr(manifest, "to_dict"):
        manifest = manifest.to_dict()
    backend = manifest.get("backend") or {}
    config = manifest.get("config") or {}
    merged = headline_from_manifest(manifest)
    merged.update(_clean_metrics(metrics))
    row = {
        "kind": kind,
        "config": {k: v for k, v in config.items()
                   if k != "partition_rules"},
        "config_fingerprint": config_fingerprint(config),
        "code_version": manifest.get("code_version")
        or ({"git_sha": manifest["git_rev"], "dirty": None}
            if manifest.get("git_rev") else None),
        "backend": backend.get("backend"),
        "device_kind": backend.get("device_kind"),
        "degraded": (backend.get("backend") == "cpu"
                     if backend.get("backend") else None),
        "metrics": merged,
        "failure": failure,
        "artifacts": {name: artifact_entry(path)
                      for name, path in (artifacts or {}).items()},
    }
    if run_id:
        row["run_id"] = run_id
    if experiment is not None:
        row["experiment"] = experiment
    if extra:
        row["extra"] = extra
    return ledger.append(row)


def ingest_bench_capsule(ledger: RunLedger, capsule: Any,
                         source: Optional[str] = None) -> dict:
    """One row from a bench row / driver capsule (path, ``{"n", "parsed":
    row}`` capsule dict, or bare row dict). The original row travels
    whole under ``bench_row`` so ``bench_trend --ledger`` folds it
    losslessly."""
    if isinstance(capsule, str):
        source = source or os.path.basename(capsule)
        with open(capsule) as fh:
            capsule = json.load(fh)
    bench_row = capsule.get("parsed", capsule) \
        if isinstance(capsule, dict) else {}
    raw = bench_row.get("raw") or {}
    metrics = {
        "host_blocked_frac": raw.get("host_blocked_frac"),
        "overlap_frac": raw.get("trace_overlap_frac"),
        "stream_speedup": raw.get("stream_speedup"),
        "mfu_est": raw.get("mfu_est"),
        "slo_p50_ms": raw.get("ttfr_p50_ms"),
        "slo_p99_ms": raw.get("ttfr_p99_ms"),
    }
    metric = str(bench_row.get("metric", ""))
    if metric in ("rounds_per_sec", "throughput"):
        metrics["rounds_per_sec"] = bench_row.get("value")
    if metric.startswith("final_") or metric == "accuracy":
        metrics["final_accuracy"] = bench_row.get("value")
    row = {
        "kind": "bench",
        "config": {k: raw[k] for k in
                   ("n_nodes", "rounds", "data_version") if k in raw},
        "code_version": code_version(),
        "backend": raw.get("backend"),
        "device_kind": raw.get("device_kind"),
        "degraded": bool(raw.get("degraded")) or None,
        "metrics": _clean_metrics(metrics),
        "failure": ({"kind": "degraded",
                     "reason": raw.get("degrade_reason")}
                    if raw.get("degrade_reason") else None),
        "bench_row": bench_row,
    }
    if source:
        row["source"] = source
    return ledger.append(row)


def ingest_trace_report(ledger: RunLedger, report: Any, *,
                        run_id: Optional[str] = None,
                        artifacts: Optional[dict] = None) -> dict:
    """One row from a :func:`~gossipy_tpu.telemetry.tracing.trace_report`
    dict (or a path to one): the critical-path headline
    (host_blocked_frac / overlap_frac) becomes queryable next to the
    throughput rows it explains."""
    if isinstance(report, str):
        path = report
        with open(path) as fh:
            report = json.load(fh)
        artifacts = dict(artifacts or {})
        artifacts.setdefault("trace_report", path)
    totals = report.get("totals") or {}
    row = {
        "kind": "trace",
        "code_version": code_version(),
        "metrics": _clean_metrics({
            "host_blocked_frac": totals.get("host_blocked_frac"),
            "overlap_frac": totals.get("overlap_frac"),
        }),
        "extra": {"n_windows": report.get("n_windows"),
                  "wall_ms": totals.get("wall_ms")},
        "artifacts": {name: artifact_entry(path)
                      for name, path in (artifacts or {}).items()},
    }
    if run_id:
        row["run_id"] = run_id
    return ledger.append(row)


def ingest_ladder(ledger: RunLedger, ladder: Any,
                  path: Optional[str] = None) -> list:
    """One row per scale-ladder rung (dict or ``ladder.json`` path) plus,
    when the ladder ended in a verdict, one failure row naming the rung,
    program and bundle. Returns every appended row."""
    if isinstance(ladder, str):
        path = path or ladder
        with open(ladder) as fh:
            ladder = json.load(fh)
    arts = {"ladder": artifact_entry(path)} if path else {}
    base = {
        "code_version": code_version(),
        "backend": ladder.get("backend"),
        "device_kind": ladder.get("device_kind"),
        "degraded": (ladder.get("backend") == "cpu"
                     if ladder.get("backend") else None),
        "artifacts": arts,
    }
    out = []
    for rung in ladder.get("rungs") or []:
        measured = rung.get("measured") or {}
        ms = measured.get("ms_per_round")
        row = dict(base)
        row.update({
            "kind": "ladder_rung",
            "config": {k: rung[k] for k in
                       ("n_nodes", "nominal_n", "cohort_size", "degree",
                        "history_dtype", "prefetch") if k in rung},
            "metrics": _clean_metrics({
                "rounds_per_sec": 1000.0 / ms if ms else None,
                "mfu_est": measured.get("mfu_est"),
                "stream_speedup": rung.get("stream_speedup"),
            }),
            "failure": ({"kind": "rung_failed"}
                        if rung.get("failed") else None),
        })
        row["config_fingerprint"] = config_fingerprint(row["config"])
        out.append(ledger.append(row))
    verdict = ladder.get("verdict")
    if verdict:
        out.append(ledger.append(dict(base, kind="ladder_verdict",
                                      failure=verdict, metrics={})))
    return out


def ingest_slo_row(ledger: RunLedger, row: Any, *,
                   run_id: Optional[str] = None,
                   artifacts: Optional[dict] = None) -> dict:
    """One row from a ``service_slo`` bench row (``loadgen.py``'s
    ``slo_row.json`` dict or path): tenants/hour + SLO percentiles +
    the trace headline, with the full row under ``bench_row``."""
    if isinstance(row, str):
        path = row
        with open(path) as fh:
            row = json.load(fh)
        artifacts = dict(artifacts or {})
        artifacts.setdefault("slo_row", path)
    raw = row.get("raw") or {}
    out = {
        "kind": "loadgen",
        "config": {k: raw[k] for k in
                   ("n_admitted", "offered_rate_per_hour", "time_scale")
                   if k in raw},
        "code_version": code_version(),
        "backend": raw.get("backend"),
        "device_kind": raw.get("device_kind"),
        "degraded": bool(raw.get("degraded")) or None,
        "metrics": _clean_metrics({
            "slo_p50_ms": raw.get("ttfr_p50_ms"),
            "slo_p99_ms": raw.get("ttfr_p99_ms"),
            "host_blocked_frac": raw.get("host_blocked_frac"),
            "overlap_frac": raw.get("trace_overlap_frac"),
        }),
        "bench_row": row,
        "artifacts": {name: artifact_entry(p)
                      for name, p in (artifacts or {}).items()},
    }
    out["config_fingerprint"] = config_fingerprint(out["config"])
    if run_id:
        out["run_id"] = run_id
    return ledger.append(out)


def ingest_bundle(ledger: RunLedger, bundle_dir: str) -> dict:
    """One failure row from a FlightRecorder bundle directory: the
    verdict travels inline (crashes are first-class ledger rows), the
    bundle + its manifest land as hashed artifacts."""
    verdict: dict = {}
    manifest: dict = {}
    try:
        with open(os.path.join(bundle_dir, "verdict.json")) as fh:
            verdict = json.load(fh)
    except (OSError, json.JSONDecodeError):
        pass
    try:
        with open(os.path.join(bundle_dir, "manifest.json")) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError):
        pass
    backend = manifest.get("backend") or {}
    config = manifest.get("config") or {}
    row = {
        "kind": "bundle",
        "config": {k: v for k, v in config.items()
                   if k != "partition_rules"},
        "config_fingerprint": config_fingerprint(config),
        "code_version": manifest.get("code_version") or code_version(),
        "backend": backend.get("backend"),
        "device_kind": backend.get("device_kind"),
        "metrics": {},
        "failure": {"kind": verdict.get("kind", "unknown"),
                    "verdict": verdict},
        "artifacts": {
            "bundle": {"path": os.path.abspath(bundle_dir),
                       "sha256": None},
            "verdict": artifact_entry(
                os.path.join(bundle_dir, "verdict.json")),
        },
    }
    return ledger.append(row)


# ---------------------------------------------------------------------------
# Merge — the fleet-wide index


def _row_key(row: dict) -> tuple:
    return (row.get("ts") or 0.0, str(row.get("run_id", "")),
            str(row.get("kind", "")),
            json.dumps(row, sort_keys=True, separators=(",", ":")))


def merge_ledgers(a: list, b: list) -> list:
    """Combine two row lists into one fleet-wide index (associative and
    commutative — fold any number of per-process ledgers in any
    order/grouping and get the same answer, the ``merge_snapshots`` /
    ``merge_traces`` contract; rows being unique by run id, the union is
    also idempotent: re-merging a ledger into itself is a no-op). Rows
    are keyed like ``merge_traces`` events — (ts, run id, kind,
    canonical JSON) — deep-copied, deduplicated on the full key, and
    returned sorted. A schema mismatch raises — drift between pods is a
    bug, not something to paper over."""
    seen: dict[tuple, dict] = {}
    for row in list(a) + list(b):
        if row.get("schema") != LEDGER_SCHEMA:
            raise ValueError(
                f"cannot merge: ledger row schema {row.get('schema')!r} "
                f"!= {LEDGER_SCHEMA}")
        seen.setdefault(_row_key(row), json.loads(json.dumps(row)))
    return [seen[k] for k in sorted(seen)]


def merge_ledger_files(out_path: str, paths: list) -> int:
    """Fold several ledger files into one (rewritten atomically via a
    temp file + ``os.replace``, the Tracer.save idiom). Returns the
    merged row count."""
    merged: list = []
    for p in paths:
        merged = merge_ledgers(merged, RunLedger(p).rows())
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        for row in merged:
            payload = json.dumps(_jsonable(row), sort_keys=True,
                                 separators=(",", ":"))
            fh.write(_frame(payload).decode("utf-8"))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, out_path)
    return len(merged)


def _jsonable(obj):
    """JSON coercion without importing numpy at module scope — the
    ledger must stay importable (and cheap) in stub environments."""
    from .manifest import _jsonable as coerce
    return coerce(obj)
