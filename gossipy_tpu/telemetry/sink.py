"""Structured telemetry events: a process-wide sink for engine diagnostics.

The engine's construction-time diagnostics (undersized mailbox, huge eval
tensor) have so far been ``warnings.warn`` strings — visible on a terminal,
invisible to any tool. Each such diagnostic now ALSO lands here as a
:class:`TelemetryEvent` (machine-readable kind + payload dict), kept in an
in-memory ring and optionally mirrored to a JSONL file, so a run harness
can assert on them, a dashboard can tail them, and a post-mortem can read
what the engine knew before the run started. The human warning is
unchanged — the sink is an addition, not a replacement.

Usage::

    from gossipy_tpu.telemetry import get_sink, set_sink, TelemetrySink
    set_sink(TelemetrySink(jsonl_path="events.jsonl"))  # optional mirror
    ...build/run simulators...
    for ev in get_sink().events(kind="mailbox_undersized"):
        print(ev.kind, ev.data)
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class TelemetryEvent:
    """One structured diagnostic: a ``kind`` tag plus a JSON-able payload."""

    kind: str
    data: dict
    ts: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "ts": self.ts, "data": self.data}


class TelemetrySink:
    """Bounded in-memory event ring with an optional JSONL mirror.

    ``maxlen`` bounds host memory (old events fall off the front — the
    ring counts every silent eviction in :attr:`dropped_events`, so
    consumers of the tail, like the flight recorder's trailing-round
    window, can tell a short history from a truncated one);
    ``jsonl_path`` appends every event as one JSON line the moment it is
    emitted (line-buffered, so a crashed run keeps its events).
    """

    def __init__(self, maxlen: int = 1024,
                 jsonl_path: Optional[str] = None):
        self.maxlen = int(maxlen)
        self._events: deque = deque(maxlen=maxlen)
        self._fh = open(jsonl_path, "a", buffering=1) if jsonl_path else None
        self.dropped_events: int = 0

    def emit(self, kind: str, data: dict) -> TelemetryEvent:
        ev = TelemetryEvent(kind=kind, data=dict(data))
        if len(self._events) == self.maxlen:
            # deque(maxlen=) silently evicts the oldest on append; count
            # the loss so ring consumers know the head is gone.
            self.dropped_events += 1
        self._events.append(ev)
        if self._fh is not None:
            self._fh.write(json.dumps(ev.to_dict()) + "\n")
        return ev

    def events(self, kind: Optional[str] = None,
               where: Optional[Callable[[TelemetryEvent], bool]] = None
               ) -> list:
        """Events currently in the ring, optionally filtered by ``kind``
        and/or an arbitrary ``where`` predicate — multi-tenant drivers tag
        their events (``data["tenant"]``) and route per-tenant views out
        of the one process ring with
        ``events(where=lambda e: e.data.get("tenant") == tid)``."""
        evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e.kind == kind]
        if where is not None:
            evs = [e for e in evs if where(e)]
        return evs

    def clear(self) -> None:
        self._events.clear()

    def close(self) -> None:
        """Close the JSONL mirror. A terminal ``metrics_snapshot`` line
        first carries the process metrics registry's final state into
        the mirror (when any metric was recorded — a post-mortem reads
        the run's SLO counters next to its last events; mirror-only, so
        the live ring and its ``dropped_events`` accounting are
        untouched), then, when the ring evicted events, a final
        ``sink_closed`` line records the loss (the in-memory tail cannot
        carry what it already dropped)."""
        if self._fh is not None:
            try:
                from .metrics import get_registry
                snap = get_registry().snapshot()
                if snap["metrics"]:
                    # Mirror-only on purpose: close() is terminal, so the
                    # snapshot goes to the durable file, not the live
                    # ring — appending to the ring here would evict real
                    # trailing events and skew dropped_events.
                    self._fh.write(json.dumps(TelemetryEvent(
                        kind="metrics_snapshot",
                        data={"snapshot": snap}).to_dict()) + "\n")
            except Exception:  # a snapshot failure must never block close
                pass
        if self._fh is not None:
            if self.dropped_events:
                self._fh.write(json.dumps(TelemetryEvent(
                    kind="sink_closed",
                    data={"dropped_events": self.dropped_events,
                          "maxlen": self.maxlen}).to_dict()) + "\n")
            self._fh.close()
            self._fh = None


_SINK: TelemetrySink = TelemetrySink()


def get_sink() -> TelemetrySink:
    return _SINK


def set_sink(sink: TelemetrySink) -> TelemetrySink:
    """Install ``sink`` as the process-wide sink; returns the previous one
    (so tests can restore it)."""
    global _SINK
    prev, _SINK = _SINK, sink
    return prev


def emit_event(kind: str, data: dict) -> TelemetryEvent:
    """Emit one structured event to the current process-wide sink."""
    return _SINK.emit(kind, data)
