"""Host-side SLO metrics: a labeled Counter/Gauge/Histogram registry.

The service scheduler's control signal plane (ISSUE-11; Podracer and the
pjit-at-scale paper both treat continuous utilization/latency telemetry
as the input to elastic scheduling). Everything here is HOST-side by
construction — the registry never appears inside a traced program, the
same contract io_callback bodies live under; the tracelint
``metrics-in-trace`` rule (analysis/tracelint.py) enforces it statically
and the HLO gate's ``engine/metrics-on`` identity pair enforces it on
the lowered program.

Three metric kinds, each a *family* keyed by a label set:

- :class:`Counter` — monotone accumulator (``inc``); merged by sum.
- :class:`Gauge` — last-written value (``set_value``/``inc``/``dec``)
  with a wall-clock stamp; merged last-writer-wins by stamp (the stamp
  makes the merge associative and commutative).
- :class:`Histogram` — fixed log-spaced buckets shared by every child
  (so cross-process merge is a plain vector add), with p50/p90/p99
  estimation by geometric interpolation inside the covering bucket,
  clamped to the observed min/max.

Naming note: the gauge setter is ``set_value`` (not prometheus-client's
``set``) on purpose — the engine's ubiquitous ``x.at[i].set(v)`` would
otherwise be indistinguishable from a registry call to tracelint's
attribute-resolution heuristic; likewise there is deliberately no method
named ``merge`` (the handlers' traced ``merge`` would collide), the
cross-process combinator is the module function :func:`merge_snapshots`.

Aggregation surface:

- ``registry.snapshot()`` — one JSON-able dict (``METRICS_SCHEMA``),
  the unit ``scripts/serve.py --metrics-dir`` writes periodically and
  ``scripts/service_top.py`` tails;
- :func:`merge_snapshots` — associative/commutative combination of two
  snapshots (the multi-pod prerequisite: every pod snapshots locally,
  anything can fold the pile);
- ``registry.to_openmetrics()`` / :func:`snapshot_to_openmetrics` —
  OpenMetrics/Prometheus text exposition, so any off-the-shelf scraper
  ingests a service run without bespoke glue.

Usage::

    from gossipy_tpu.telemetry.metrics import get_registry
    reg = get_registry()
    reg.counter("service_evictions_total",
                "tenants evicted", ("cause",)).labels(
                    cause="sentinel").inc()
    h = reg.histogram("service_round_seconds", "per-round latency",
                      ("bucket",))
    h.labels(bucket="ab12").observe(0.004)
    print(reg.to_openmetrics())
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from typing import Optional, Sequence

METRICS_SCHEMA = 1

# Default per-family series cap: the cardinality guard. Tenant-labeled
# families in a long-lived service are the realistic way a registry
# balloons; past the cap new label sets collapse into one shared
# overflow series (labels all ``_other_``) so TOTALS stay right while
# memory stays bounded, and the family counts what it dropped.
DEFAULT_MAX_SERIES = 512
OVERFLOW_LABEL = "_other_"

# Fixed log-spaced bucket upper bounds (seconds-flavoured, but unitless):
# 4 per decade from 100 us to 10 ks, ~1.78x resolution. FIXED so that
# histogram merge across processes is a plain per-bucket add — the
# multi-pod prerequisite rules out adaptive buckets.
_DECADES = range(-4, 5)
_MANTISSAS = (1.0, 1.778, 3.162, 5.623)
DEFAULT_BUCKETS = tuple(
    round(m * 10.0 ** d, 10) for d in _DECADES for m in _MANTISSAS)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric/label name {name!r}")
    return name


def _label_key(labelnames: Sequence[str], labels: dict) -> tuple:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Child:
    """One series: a concrete label-set of a family."""

    def __init__(self, family: "_Family", key: tuple):
        self.family = family
        self.key = key

    @property
    def labels_dict(self) -> dict:
        return dict(zip(self.family.labelnames, self.key))


class CounterChild(_Child):
    def __init__(self, family, key):
        super().__init__(family, key)
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(f"counters only go up; inc({v})")
        with self.family.registry._lock:
            self.value += float(v)


class GaugeChild(_Child):
    def __init__(self, family, key):
        super().__init__(family, key)
        self.value = 0.0
        self.ts = 0.0   # never written

    def set_value(self, v: float) -> None:
        with self.family.registry._lock:
            self.value = float(v)
            self.ts = time.time()

    def inc(self, v: float = 1.0) -> None:
        with self.family.registry._lock:
            self.value += float(v)
            self.ts = time.time()

    def dec(self, v: float = 1.0) -> None:
        self.inc(-v)


class HistogramChild(_Child):
    def __init__(self, family, key):
        super().__init__(family, key)
        n = len(family.buckets)
        self.counts = [0] * (n + 1)   # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        if math.isnan(v):
            return  # a NaN observation would poison sum forever
        with self.family.registry._lock:
            self.counts[_bucket_index(self.family.buckets, v)] += 1
            self.sum += v
            self.count += 1
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def quantile(self, q: float) -> Optional[float]:
        """Estimated ``q``-quantile (0..1) from the bucket counts, or
        None when empty. Geometric interpolation inside the covering
        bucket, clamped to the observed [min, max] envelope — accuracy
        is bounded by the ~1.78x bucket resolution (tested against
        numpy in tests/test_metrics_registry.py)."""
        return quantile_from_counts(self.family.buckets, self.counts, q,
                                    lo=self.min, hi=self.max)


def _bucket_index(buckets: tuple, v: float) -> int:
    import bisect
    return bisect.bisect_left(buckets, v)


def quantile_from_counts(buckets: Sequence[float], counts: Sequence[int],
                         q: float, lo: Optional[float] = None,
                         hi: Optional[float] = None) -> Optional[float]:
    """Quantile estimate from (bucket upper bounds, per-bucket counts).

    Works on live children and on snapshot series alike (the status
    board calls it on tailed snapshots). ``lo``/``hi`` are the observed
    min/max when known — the estimate is clamped into that envelope,
    which fixes the degenerate first/last-bucket cases.
    """
    total = sum(counts)
    if total == 0:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank and c:
            if i >= len(buckets):
                # +Inf bucket: no upper bound — the observed max (or the
                # last finite boundary) is the best available answer.
                est = hi if hi is not None else float(buckets[-1])
                break
            upper = float(buckets[i])
            lower = float(buckets[i - 1]) if i else upper / _MANTISSAS[1]
            frac = (rank - (cum - c)) / c
            if lower > 0 and upper > 0:
                est = lower * (upper / lower) ** frac
            else:   # non-positive observations land in bucket 0
                est = lower + (upper - lower) * frac
            break
    else:
        return None
    if lo is not None:
        est = max(est, lo)
    if hi is not None:
        est = min(est, hi)
    return est


_CHILD_CLASSES = {"counter": CounterChild, "gauge": GaugeChild,
                  "histogram": HistogramChild}


class _Family:
    """One named metric: a label schema plus its children (series)."""

    def __init__(self, registry: "MetricsRegistry", name: str, kind: str,
                 help: str, labelnames: Sequence[str],
                 buckets: Optional[Sequence[float]] = None,
                 max_series: int = DEFAULT_MAX_SERIES):
        self.registry = registry
        self.name = _check_name(name)
        self.kind = kind
        self.help = help
        self.labelnames = tuple(_check_name(n) for n in labelnames)
        self.max_series = int(max_series)
        self.overflowed = 0
        if kind == "histogram":
            self.buckets = tuple(sorted(float(b) for b in
                                        (buckets or DEFAULT_BUCKETS)))
            if not self.buckets:
                raise ValueError("histogram needs at least one bucket")
        else:
            self.buckets = None
        self._children: dict[tuple, _Child] = {}

    def labels(self, **labels) -> _Child:
        """The child for this label set (created on first use). Past
        ``max_series`` distinct label sets, NEW sets collapse into one
        shared overflow child (every label ``_other_``) — totals stay
        correct, memory stays bounded, ``overflowed`` counts the
        collapses."""
        key = _label_key(self.labelnames, labels)
        with self.registry._lock:
            child = self._children.get(key)
            if child is not None:
                return child
            if len(self._children) >= self.max_series:
                self.overflowed += 1
                key = tuple(OVERFLOW_LABEL for _ in self.labelnames)
                child = self._children.get(key)
                if child is not None:
                    return child
            child = _CHILD_CLASSES[self.kind](self, key)
            self._children[key] = child
            return child

    # Zero-label sugar: counter("x").inc() etc. without .labels().
    def _default(self) -> _Child:
        if self.labelnames:
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}; "
                "use .labels(...)")
        return self.labels()

    def inc(self, v: float = 1.0) -> None:
        self._default().inc(v)

    def dec(self, v: float = 1.0) -> None:
        self._default().dec(v)

    def set_value(self, v: float) -> None:
        self._default().set_value(v)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def quantile(self, q: float) -> Optional[float]:
        return self._default().quantile(q)

    def series(self) -> list:
        return list(self._children.values())

    def _snapshot(self) -> dict:
        out: dict = {"type": self.kind, "help": self.help,
                     "labelnames": list(self.labelnames),
                     "max_series": self.max_series,
                     "overflowed": self.overflowed}
        if self.kind == "histogram":
            out["buckets"] = list(self.buckets)
        rows = []
        for child in self._children.values():
            row: dict = {"labels": child.labels_dict}
            if self.kind == "counter":
                row["value"] = child.value
            elif self.kind == "gauge":
                row["value"] = child.value
                row["ts"] = child.ts
            else:
                row.update({"counts": list(child.counts),
                            "sum": child.sum, "count": child.count,
                            "min": child.min, "max": child.max})
            rows.append(row)
        rows.sort(key=lambda r: tuple(sorted(r["labels"].items())))
        out["series"] = rows
        return out


class MetricsRegistry:
    """A process-local collection of metric families.

    Thread-safe (one coarse lock — the hot path is a dict hit plus a
    float add; contention is not a concern at host-control-plane rates).
    The module-level default registry (:func:`get_registry`) is what the
    engine, the service scheduler and the CLIs share; tests install
    their own via :func:`set_registry`.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}

    # -- family accessors (get-or-create; kind/schema mismatches raise) --

    def _family(self, name: str, kind: str, help: str,
                labelnames: Sequence[str],
                buckets: Optional[Sequence[float]] = None,
                max_series: int = DEFAULT_MAX_SERIES) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(self, name, kind, help or name, labelnames,
                              buckets=buckets, max_series=max_series)
                self._families[name] = fam
                return fam
            if fam.kind != kind:
                raise ValueError(
                    f"{name} already registered as {fam.kind}, not {kind}")
            if tuple(labelnames) != fam.labelnames:
                raise ValueError(
                    f"{name} labelnames {fam.labelnames} != "
                    f"{tuple(labelnames)}")
            if kind == "histogram" and buckets is not None and \
                    tuple(sorted(float(b) for b in buckets)) != fam.buckets:
                raise ValueError(f"{name} re-registered with different "
                                 "buckets")
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = (),
                max_series: int = DEFAULT_MAX_SERIES) -> _Family:
        return self._family(name, "counter", help, labelnames,
                            max_series=max_series)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = (),
              max_series: int = DEFAULT_MAX_SERIES) -> _Family:
        return self._family(name, "gauge", help, labelnames,
                            max_series=max_series)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None,
                  max_series: int = DEFAULT_MAX_SERIES) -> _Family:
        return self._family(name, "histogram", help, labelnames,
                            buckets=buckets, max_series=max_series)

    def families(self) -> dict:
        with self._lock:
            return dict(self._families)

    def clear(self) -> None:
        with self._lock:
            self._families.clear()

    # -- aggregation surface --------------------------------------------

    def snapshot(self) -> dict:
        """One JSON-able dict of everything: the unit that gets written
        to ``--metrics-dir``, merged across processes, stamped into
        manifests and carried by the terminal ``metrics_snapshot``
        telemetry event."""
        with self._lock:
            return {"schema": METRICS_SCHEMA, "ts": time.time(),
                    "metrics": {name: fam._snapshot()
                                for name, fam in
                                sorted(self._families.items())}}

    def load_snapshot(self, snap: dict) -> None:
        """Fold a snapshot INTO this registry (live counters add, gauges
        last-writer-win, histogram buckets add) — the in-process face of
        :func:`merge_snapshots`."""
        merged = merge_snapshots(self.snapshot(), snap)
        with self._lock:
            self._families.clear()
            self._load(merged)

    def _load(self, snap: dict) -> None:
        for name, fam_snap in snap.get("metrics", {}).items():
            kind = fam_snap["type"]
            fam = self._family(
                name, kind, fam_snap.get("help", ""),
                fam_snap.get("labelnames", ()),
                buckets=fam_snap.get("buckets"),
                max_series=fam_snap.get("max_series", DEFAULT_MAX_SERIES))
            fam.overflowed = fam_snap.get("overflowed", 0)
            for row in fam_snap.get("series", []):
                child = fam.labels(**row["labels"])
                if kind == "counter":
                    child.value = row["value"]
                elif kind == "gauge":
                    child.value = row["value"]
                    child.ts = row.get("ts", 0.0)
                else:
                    child.counts = list(row["counts"])
                    child.sum = row["sum"]
                    child.count = row["count"]
                    child.min = row.get("min")
                    child.max = row.get("max")

    def to_openmetrics(self) -> str:
        return snapshot_to_openmetrics(self.snapshot())

    def save(self, path: str) -> None:
        """Atomic snapshot write (tmp + rename) so a tailing status
        board never reads a torn file."""
        import os
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.snapshot(), fh, indent=2)
            fh.write("\n")
        os.replace(tmp, path)


# ---------------------------------------------------------------------------
# Snapshot algebra (pure dict -> dict; the multi-pod merge currency)


def _merge_series(kind: str, rows_a: list, rows_b: list) -> list:
    by_key: dict[tuple, dict] = {}
    for row in rows_a:
        by_key[tuple(sorted(row["labels"].items()))] = \
            json.loads(json.dumps(row))
    for row in rows_b:
        k = tuple(sorted(row["labels"].items()))
        if k not in by_key:
            by_key[k] = json.loads(json.dumps(row))
            continue
        cur = by_key[k]
        if kind == "counter":
            cur["value"] += row["value"]
        elif kind == "gauge":
            # Last-writer-wins by stamp; the (ts, value) tiebreak keeps
            # the pick deterministic, hence the merge associative.
            if (row.get("ts", 0.0), row["value"]) > \
                    (cur.get("ts", 0.0), cur["value"]):
                cur.update(value=row["value"], ts=row.get("ts", 0.0))
        else:
            cur["counts"] = [x + y for x, y in
                             zip(cur["counts"], row["counts"])]
            cur["sum"] += row["sum"]
            cur["count"] += row["count"]
            mins = [m for m in (cur.get("min"), row.get("min"))
                    if m is not None]
            maxs = [m for m in (cur.get("max"), row.get("max"))
                    if m is not None]
            cur["min"] = min(mins) if mins else None
            cur["max"] = max(maxs) if maxs else None
    return [by_key[k] for k in sorted(by_key)]


def merge_snapshots(a: dict, b: dict) -> dict:
    """Combine two registry snapshots into one (associative and
    commutative — fold any number of per-process snapshots in any
    order/grouping and get the same answer; tested). Counters and
    histogram buckets add; gauges take the latest stamp; structural
    mismatches (same name, different type/labelnames/buckets) raise —
    a schema drift between pods is a bug, not something to paper over."""
    out: dict = {"schema": METRICS_SCHEMA,
                 "ts": max(a.get("ts", 0.0), b.get("ts", 0.0)),
                 "metrics": {}}
    names = sorted(set(a.get("metrics", {})) | set(b.get("metrics", {})))
    for name in names:
        fa, fb = a.get("metrics", {}).get(name), \
            b.get("metrics", {}).get(name)
        if fa is None or fb is None:
            out["metrics"][name] = json.loads(json.dumps(fa or fb))
            continue
        for field in ("type", "labelnames"):
            if fa.get(field) != fb.get(field):
                raise ValueError(
                    f"cannot merge {name}: {field} mismatch "
                    f"({fa.get(field)!r} vs {fb.get(field)!r})")
        if fa["type"] == "histogram" and \
                list(fa["buckets"]) != list(fb["buckets"]):
            raise ValueError(f"cannot merge {name}: bucket mismatch")
        merged = {k: fa[k] for k in fa if k != "series"}
        merged["overflowed"] = fa.get("overflowed", 0) + \
            fb.get("overflowed", 0)
        merged["series"] = _merge_series(fa["type"], fa["series"],
                                         fb["series"])
        out["metrics"][name] = merged
    return out


def _om_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _om_num(v: float) -> str:
    if v != v:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _om_labels(labels: dict, extra: Optional[tuple] = None) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items = items + [extra]
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_om_escape(str(v))}"'
                          for k, v in items) + "}"


def snapshot_to_openmetrics(snap: dict) -> str:
    """OpenMetrics text exposition of a snapshot (``# HELP``/``# TYPE``
    metadata, counter ``_total`` sample suffix, histogram
    ``_bucket{le=}``/``_sum``/``_count`` expansion, terminal ``# EOF``)
    — the format every Prometheus-compatible scraper ingests."""
    lines: list[str] = []
    for name, fam in sorted(snap.get("metrics", {}).items()):
        kind = fam["type"]
        lines.append(f"# HELP {name} {_om_escape(fam.get('help', name))}")
        lines.append(f"# TYPE {name} {kind}")
        for row in fam.get("series", []):
            labels = row["labels"]
            if kind == "counter":
                suffix = "" if name.endswith("_total") else "_total"
                lines.append(f"{name}{suffix}{_om_labels(labels)} "
                             f"{_om_num(row['value'])}")
            elif kind == "gauge":
                lines.append(f"{name}{_om_labels(labels)} "
                             f"{_om_num(row['value'])}")
            else:
                cum = 0
                for bound, c in zip(list(fam["buckets"]) + [math.inf],
                                    row["counts"]):
                    cum += c
                    le = "+Inf" if bound == math.inf else _om_num(bound)
                    lines.append(
                        f"{name}_bucket{_om_labels(labels, ('le', le))} "
                        f"{cum}")
                lines.append(f"{name}_sum{_om_labels(labels)} "
                             f"{_om_num(row['sum'])}")
                lines.append(f"{name}_count{_om_labels(labels)} "
                             f"{row['count']}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Process-wide default registry (the engine / scheduler / CLI rendezvous)


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Install ``registry`` as the process default; returns the previous
    one (so tests can restore it)."""
    global _REGISTRY
    prev, _REGISTRY = _REGISTRY, registry
    return prev


def observe_engine_run(simulator: str, n_rounds: int, sent: float,
                       failed_by_cause: dict,
                       registry: Optional[MetricsRegistry] = None) -> None:
    """Feed one finished engine segment into the registry: the
    engine-level rounds/sent/failed-by-cause counters, sourced from the
    per-cause :class:`~gossipy_tpu.telemetry.FailureCounts` arrays the
    report already carries. Called HOST-side after the compiled program
    returned — never from a traced region."""
    reg = registry if registry is not None else get_registry()
    reg.counter("engine_rounds_total",
                "simulation rounds completed",
                ("simulator",)).labels(simulator=simulator).inc(n_rounds)
    reg.counter("engine_messages_sent_total",
                "gossip messages generated",
                ("simulator",)).labels(simulator=simulator).inc(sent)
    fam = reg.counter("engine_messages_failed_total",
                      "messages lost, by cause",
                      ("simulator", "cause"))
    for cause, n in failed_by_cause.items():
        fam.labels(simulator=simulator, cause=cause).inc(float(n))
