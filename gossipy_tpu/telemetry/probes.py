"""Gossip-dynamics probes: in-graph consensus, staleness and mixing health.

The failure counters and phase scopes (PR 1) answer the *systems*
questions; this module carries the *learning-dynamics* quantities the
gossip-averaging literature actually reasons about, computed INSIDE the
jitted round program over the stacked ``[N, params]`` pytree:

- **consensus distance** — per-round mean/max L2 distance of each node's
  params from the population mean, plus a per-layer (per parameter leaf)
  breakdown. The canonical Lyapunov quantity of gossip averaging: on a
  connected static topology with training disabled it must decay.
- **merge staleness** — the distribution of ``current_round − send_round``
  over accepted model-carrying messages (mean/max plus a clamped
  histogram). Non-zero only under message delay; the histogram's row sum
  equals the round's accepted-message count bit-for-bit.
- **realized mixing** — per-node accepted-merge counts (to compare against
  the topology's expected fan-in,
  :meth:`~gossipy_tpu.simulation.engine.GossipSimulator._expected_fanin_vector`)
  and the per-round *merge-delta vs train-delta* norms: how far gossip
  moved the models vs how far local SGD did.

Everything here is engine-agnostic pure math (the dependency points from
the engines to this module, like the rest of :mod:`gossipy_tpu.telemetry`):
the jitted engine, the All2All variant, and the sequential high-fidelity
engine all compute the same quantities through these helpers, so
jitted-vs-sequential probe parity is testable.

Probes are OPT-IN (``GossipSimulator(probes=...)``): with the default
``probes=None`` the round program traces exactly as before — no extra
accumulators, no extra HLO.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class ProbeConfig:
    """Which gossip-dynamics probes a simulator computes per round.

    - ``consensus``: mean/max L2 distance from the population-mean params
      plus the per-layer breakdown.
    - ``staleness``: mean/max + bucketed histogram of
      ``current_round − send_round`` over accepted messages.
    - ``mixing``: per-node accepted-merge counts and the merge-delta vs
      train-delta norm decomposition.
    - ``staleness_buckets``: histogram length; staleness values at or
      beyond the last bucket are clamped into it.
    """

    consensus: bool = True
    staleness: bool = True
    mixing: bool = True
    staleness_buckets: int = 8

    def __post_init__(self):
        if self.staleness_buckets < 2:
            raise ValueError("staleness_buckets must be >= 2 (bucket 0 "
                             "holds same-round merges; the last bucket "
                             "clamps the tail)")

    @classmethod
    def coerce(cls, probes: Union[None, bool, "ProbeConfig"]
               ) -> Optional["ProbeConfig"]:
        """Normalize the ``probes=`` constructor argument: ``None``/``False``
        → off (None), ``True`` → all probes at defaults, a
        :class:`ProbeConfig` → itself (None when every probe is off)."""
        if probes is None or probes is False:
            return None
        if probes is True:
            return cls()
        if isinstance(probes, cls):
            if not (probes.consensus or probes.staleness or probes.mixing):
                return None
            return probes
        raise TypeError("probes= expects None, bool or ProbeConfig; got "
                        f"{type(probes).__name__}")

    def to_dict(self) -> dict:
        return {"consensus": self.consensus, "staleness": self.staleness,
                "mixing": self.mixing,
                "staleness_buckets": self.staleness_buckets}


class ProbeAccum(NamedTuple):
    """Traced per-round probe accumulator threaded through the deliver and
    reply slot loops (one instance per round; summed across the phases)."""

    accepted: jax.Array    # [N] int32: accepted model-carrying merges
    stale_sum: jax.Array   # int32: sum of staleness over accepted messages
    stale_max: jax.Array   # int32: max staleness (0 when nothing accepted)
    stale_hist: jax.Array  # [B] int32: clamped staleness histogram
    merge_sq: jax.Array    # f32: sum of squared merge-delta norms
    train_sq: jax.Array    # f32: sum of squared train-delta norms

    @staticmethod
    def zeros(n: int, buckets: int) -> "ProbeAccum":
        return ProbeAccum(
            accepted=jnp.zeros((n,), jnp.int32),
            stale_sum=jnp.int32(0),
            stale_max=jnp.int32(0),
            stale_hist=jnp.zeros((buckets,), jnp.int32),
            merge_sq=jnp.float32(0),
            train_sq=jnp.float32(0),
        )

    def __add__(self, other: "ProbeAccum") -> "ProbeAccum":  # type: ignore[override]
        return ProbeAccum(
            accepted=self.accepted + other.accepted,
            stale_sum=self.stale_sum + other.stale_sum,
            stale_max=jnp.maximum(self.stale_max, other.stale_max),
            stale_hist=self.stale_hist + other.stale_hist,
            merge_sq=self.merge_sq + other.merge_sq,
            train_sq=self.train_sq + other.train_sq,
        )

    def record_slot(self, accepted_mask: jax.Array,
                    staleness: jax.Array) -> "ProbeAccum":
        """Fold one mailbox slot's accepted messages in: ``accepted_mask``
        [N] bool, ``staleness`` [N] int32 (rounds since the payload
        snapshot; read only where the mask holds). Each accepted message
        adds exactly 1 to ``accepted[receiver]`` AND to exactly one
        histogram bucket, so ``stale_hist.sum() == accepted.sum()`` holds
        bit-for-bit by construction."""
        acc = accepted_mask.astype(jnp.int32)
        stale = jnp.where(accepted_mask, staleness, 0).astype(jnp.int32)
        buckets = self.stale_hist.shape[0]
        bucket = jnp.clip(stale, 0, buckets - 1)
        return self._replace(
            accepted=self.accepted + acc,
            stale_sum=self.stale_sum + stale.sum(),
            stale_max=jnp.maximum(self.stale_max, stale.max()),
            stale_hist=self.stale_hist.at[bucket].add(acc),
        )


def sq_param_distance(a: Any, b: Any) -> jax.Array:
    """Scalar f32: total squared L2 distance between two params pytrees
    (computed in fp32 regardless of the leaves' storage dtype)."""
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    total = jnp.float32(0)
    for la, lb in zip(leaves_a, leaves_b):
        d = la.astype(jnp.float32) - lb.astype(jnp.float32)
        total = total + (d * d).sum()
    return total


def consensus_stats(params: Any) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Consensus-distance statistics over stacked params (leaves ``[N, ...]``).

    Returns ``(mean, max, per_layer)``:

    - ``mean``/``max``: the mean/max over nodes of each node's L2 distance
      from the population-mean parameter vector (all leaves concatenated).
    - ``per_layer``: ``[L]`` f32, the mean over nodes of the per-LEAF L2
      distance, one entry per parameter leaf in ``tree_leaves`` order
      (names via :func:`param_layer_names`).
    """
    leaves = jax.tree_util.tree_leaves(params)
    n = leaves[0].shape[0]
    per_leaf_sq = []
    for l in leaves:
        x = l.astype(jnp.float32).reshape(n, -1)
        d = x - x.mean(axis=0, keepdims=True)
        per_leaf_sq.append((d * d).sum(axis=1))  # [N]
    total_sq = sum(per_leaf_sq)
    dist = jnp.sqrt(total_sq)
    per_layer = jnp.stack([jnp.sqrt(s).mean() for s in per_leaf_sq])
    return dist.mean(), dist.max(), per_layer


def param_layer_names(params: Any) -> list[str]:
    """Host-side leaf names ("path/to/leaf") matching
    :func:`consensus_stats`'s ``per_layer`` ordering (``tree_leaves``
    order)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    names = []
    for path, _leaf in flat:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        names.append("/".join(parts) if parts else "param")
    return names


# Per-round probe stat keys the engines emit (and the report/event layers
# consume). Grouped by the ProbeConfig flag that enables them.
CONSENSUS_KEYS = ("probe_consensus_mean", "probe_consensus_max",
                  "probe_consensus_per_layer")
STALENESS_KEYS = ("probe_stale_mean", "probe_stale_max", "probe_stale_hist")
MIXING_KEYS = ("probe_accepted_per_node", "probe_merge_delta",
               "probe_train_delta")
PROBE_STAT_KEYS = CONSENSUS_KEYS + STALENESS_KEYS + MIXING_KEYS


def probe_stats_from_accum(cfg: ProbeConfig, pa: ProbeAccum,
                           delta_ok: bool) -> dict:
    """The staleness/mixing entries of a round's stats dict from the
    accumulated :class:`ProbeAccum`. ``delta_ok`` is the static flag saying
    the merge/train-delta decomposition is exact for this simulator's
    receive path (base pipeline, MERGE_UPDATE); when False the delta
    columns carry NaN rather than a wrong number."""
    out: dict = {}
    if cfg.staleness:
        count = pa.stale_hist.sum()
        out["probe_stale_mean"] = jnp.where(
            count > 0,
            pa.stale_sum.astype(jnp.float32) /
            jnp.maximum(count, 1).astype(jnp.float32),
            jnp.float32(0))
        out["probe_stale_max"] = pa.stale_max
        out["probe_stale_hist"] = pa.stale_hist
    if cfg.mixing:
        out["probe_accepted_per_node"] = pa.accepted
        if delta_ok:
            out["probe_merge_delta"] = jnp.sqrt(pa.merge_sq)
            out["probe_train_delta"] = jnp.sqrt(pa.train_sq)
        else:
            out["probe_merge_delta"] = jnp.float32(jnp.nan)
            out["probe_train_delta"] = jnp.float32(jnp.nan)
    return out


def probe_event_row(vals: dict) -> Optional[dict]:
    """The per-round ``update_probes`` observer payload (JSON-able scalars
    + the histogram) from one round's probe values. ``vals`` maps the
    ``probe_*`` stat keys to host scalars/arrays for ONE round; keys for
    disabled probes are simply absent. Returns None when ``vals`` carries
    no probe at all."""
    if not vals:
        return None
    row: dict = {}
    if "probe_consensus_mean" in vals:
        row["consensus_mean"] = float(vals["probe_consensus_mean"])
        row["consensus_max"] = float(vals["probe_consensus_max"])
    if "probe_stale_mean" in vals:
        row["stale_mean"] = float(vals["probe_stale_mean"])
        row["stale_max"] = int(vals["probe_stale_max"])
        row["stale_hist"] = [int(v) for v in
                             np.asarray(vals["probe_stale_hist"])]
    if "probe_accepted_per_node" in vals:
        row["accepted_total"] = int(
            np.asarray(vals["probe_accepted_per_node"]).sum())
        md = float(vals["probe_merge_delta"])
        td = float(vals["probe_train_delta"])
        row["merge_delta"] = None if np.isnan(md) else md
        row["train_delta"] = None if np.isnan(td) else td
    return row or None
