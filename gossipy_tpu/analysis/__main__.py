"""``python -m gossipy_tpu.analysis`` — run tracelint over the repo.

Exit status: 0 when every finding is suppressed or baselined, 1 when NEW
findings exist (CI fails only on regressions), 2 on usage errors.

Typical invocations::

    python -m gossipy_tpu.analysis                    # lint, fail on new
    python -m gossipy_tpu.analysis --json out.json    # + machine-readable
    python -m gossipy_tpu.analysis --write-baseline   # accept current tree
    python -m gossipy_tpu.analysis --all              # ignore the baseline
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .tracelint import (
    baseline_from_findings,
    filter_baselined,
    load_baseline,
    run_tracelint,
)

_DEFAULT_BASELINE = Path(__file__).parent / "baseline.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m gossipy_tpu.analysis",
                                 description=__doc__)
    ap.add_argument("--root", default=None,
                    help="repo root containing the gossipy_tpu package "
                         "(default: auto-detected from the installed "
                         "package location)")
    ap.add_argument("--baseline", default=str(_DEFAULT_BASELINE),
                    help="baseline JSON waiving pre-existing findings")
    ap.add_argument("--all", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the findings (all + new) as JSON")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline to accept the current tree")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else Path(__file__).parents[2]
    if not (root / "gossipy_tpu").is_dir():
        print(f"tracelint: no gossipy_tpu package under {root}",
              file=sys.stderr)
        return 2

    findings = run_tracelint(root)
    baseline = load_baseline(args.baseline)
    new = findings if args.all else filter_baselined(findings, baseline)

    if args.write_baseline:
        Path(args.baseline).write_text(
            json.dumps(baseline_from_findings(findings), indent=2,
                       sort_keys=True) + "\n")
        print(f"tracelint: baseline rewritten with {len(findings)} "
              f"finding(s) -> {args.baseline}")
        return 0

    if args.json_out:
        Path(args.json_out).write_text(json.dumps({
            "total": len(findings),
            "new": [f.to_dict() for f in new],
            "all": [f.to_dict() for f in findings],
        }, indent=2) + "\n")

    for f in new:
        print(f)
        print(f"    {f.snippet}")
    waived = len(findings) - len(new)
    print(f"tracelint: {len(findings)} finding(s), {waived} baselined, "
          f"{len(new)} new")
    if new:
        print("tracelint: fix the new finding(s), suppress with "
              "`# tracelint: disable=<rule>`, or re-baseline with "
              "--write-baseline (reviewed changes only)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
