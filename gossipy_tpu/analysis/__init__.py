"""Static-analysis layer for the jitted gossip engine.

Two halves, both repo-specific (docs/analysis.md):

- :mod:`~gossipy_tpu.analysis.tracelint` — an AST linter (stdlib ``ast``,
  no new dependencies) tuned to this codebase's real bug classes: host-side
  coercion or branching on traced values inside functions reachable from
  the engine's ``jax.jit`` / ``lax.scan`` / ``fori_loop`` bodies, silent
  ``np.*``/``math.*`` constant folding in traced regions, non-shape-static
  slicing, use-after-donate of donated state buffers, and the
  registry-completeness cross-checks (report field registry, JSONL schema
  tolerance).  ``python -m gossipy_tpu.analysis`` runs it; a committed
  ``analysis/baseline.json`` waives pre-existing findings so CI fails only
  on NEW violations; ``# tracelint: disable=<rule>`` suppresses a line.

- :mod:`~gossipy_tpu.analysis.hlo` — canonicalized StableHLO fingerprints
  for the engine's round program.  ``assert_identical_hlo`` is the shared
  helper behind every "feature off traces the identical program" test, and
  ``scripts/hlo_gate.py`` drives the full feature-flag matrix against the
  committed golden manifest (``analysis/hlo_golden.json``).

The linter half imports only the stdlib so it stays fast and usable from
hooks; the HLO half imports jax lazily on first use.
"""

from .tracelint import (  # noqa: F401
    ALL_RULES,
    Finding,
    baseline_from_findings,
    filter_baselined,
    load_baseline,
    run_tracelint,
)


def __getattr__(name):
    # HLO helpers pull in jax + the engine; keep them lazy so pure-lint
    # consumers (pre-commit hooks, the CI lint job) never pay that import.
    _hlo_names = (
        "canonicalize_hlo", "hlo_fingerprint", "fingerprint_text",
        "lower_text", "compiled_text", "first_divergence",
        "assert_identical_hlo", "gate_cases",
    )
    if name in _hlo_names:
        from . import hlo
        return getattr(hlo, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
